#!/usr/bin/env bash
# Style/syntax gate for cometbft_tpu/ + tests/ — catches rot BEFORE the
# 870 s tier-1 budget is spent on it.
#
# Linter resolution order (the container bakes no linters, CI may):
#   1. ruff            (fast, superset of pyflakes)
#   2. pyflakes        (undefined names, unused imports, syntax)
#   3. compileall      (always available: pure syntax pass)
# The fallback is weaker but never silently green on a syntax error.
set -u
cd "$(dirname "$0")/.."

TARGETS=(cometbft_tpu tests bench.py)
rc=0

if command -v ruff >/dev/null 2>&1; then
    echo "[lint] ruff check ${TARGETS[*]}"
    # E9/F = syntax errors + pyflakes classes; style classes stay off so
    # the gate matches what pyflakes-only environments enforce
    ruff check --select E9,F --no-cache "${TARGETS[@]}" || rc=1
elif python -c 'import pyflakes' >/dev/null 2>&1; then
    echo "[lint] pyflakes ${TARGETS[*]}"
    python -m pyflakes "${TARGETS[@]}" || rc=1
else
    echo "[lint] no ruff/pyflakes in this environment; syntax-only pass"
    python -m compileall -q "${TARGETS[@]}" || rc=1
fi

if [ "$rc" -ne 0 ]; then
    echo "[lint] FAILED"
else
    echo "[lint] clean"
fi
exit "$rc"
