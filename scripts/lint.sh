#!/usr/bin/env bash
# Style/syntax gate for cometbft_tpu/ + tests/ — catches rot BEFORE the
# 870 s tier-1 budget is spent on it.
#
# Linter resolution order (the container bakes no linters, CI may):
#   1. ruff            (fast, superset of pyflakes)
#   2. pyflakes        (undefined names, unused imports, syntax)
#   3. compileall      (always available: pure syntax pass)
# The fallback is weaker but never silently green on a syntax error.
set -u
cd "$(dirname "$0")/.."

TARGETS=(cometbft_tpu tests bench.py)
rc=0

if command -v ruff >/dev/null 2>&1; then
    echo "[lint] ruff check ${TARGETS[*]}"
    # E9/F = syntax errors + pyflakes classes; style classes stay off so
    # the gate matches what pyflakes-only environments enforce
    ruff check --select E9,F --no-cache "${TARGETS[@]}" || rc=1
elif python -c 'import pyflakes' >/dev/null 2>&1; then
    echo "[lint] pyflakes ${TARGETS[*]}"
    python -m pyflakes "${TARGETS[@]}" || rc=1
else
    echo "[lint] no ruff/pyflakes in this environment; syntax-only pass"
    python -m compileall -q "${TARGETS[@]}" || rc=1
fi

# Clock-seam guard: the clock-managed packages must route every sleep /
# monotonic read through libs/clock (a direct call reads REAL time under
# the scenario lab's virtual clock — a determinism bug, the exact class
# PR 15 flushed out).  Enforced by bftlint's CLK001 (scripts/analysis):
# scope-aware, resolves aliased imports (`from time import monotonic as
# m`) and flags `loop.time()` — both invisible to the old regex.  Legit
# exceptions carry `# bftlint: disable=CLK001 -- reason` on (or directly
# above) the line.  The grep remains ONLY as a degraded fallback for
# environments whose python can't run the engine.
if python -c 'import analysis' >/dev/null 2>&1 || \
        (cd scripts && python -c 'import analysis' >/dev/null 2>&1); then
    echo "[lint] bftlint CLK001 (clock-seam, AST)"
    (cd scripts && python -m analysis --rules CLK001) || rc=1
else
    echo "[lint] bftlint unavailable; regex clock-seam fallback"
    CLOCK_PKGS=(cometbft_tpu/consensus cometbft_tpu/p2p cometbft_tpu/node
                cometbft_tpu/mempool cometbft_tpu/blocksync
                cometbft_tpu/statesync)
    # awk instead of grep -v: the suppression grammar also allows the
    # marker on a comment-only line directly ABOVE the call
    hits=$(find "${CLOCK_PKGS[@]}" -name '*.py' -exec awk '
        FNR == 1 { prev = "" }
        /asyncio\.sleep\(|time\.monotonic\(|time\.time\(|time\.time_ns\(/ {
            if (index($0, "bftlint: disable=CLK001") == 0 &&
                index(prev, "bftlint: disable=CLK001") == 0)
                print FILENAME ":" FNR ":" $0
        }
        { prev = $0 }' {} + 2>/dev/null || true)
    if [ -n "$hits" ]; then
        echo "[lint] direct real-time calls in clock-managed packages" \
             "(route through libs/clock or bftlint: disable=CLK001):"
        echo "$hits"
        rc=1
    fi
fi

if [ "$rc" -ne 0 ]; then
    echo "[lint] FAILED"
else
    echo "[lint] clean"
fi
exit "$rc"
