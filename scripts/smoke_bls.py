#!/usr/bin/env python
"""CI BLS aggregate-commit smoke: one seeded 4-node MIXED-KEY net
(validators 0/2 sign bls12_381, 1/3 ed25519) on the virtual clock.

The run must:

- reach the target height FORK-FREE — BLS precommits fold into ONE
  aggregate signature + signer bitmap per commit (types/commit.py
  aggregate lane block), so any domain mix-up between the
  zero-timestamp aggregation encoding and the reference Ed25519
  encoding stalls or forks the chain here;
- actually exercise the aggregate fast path, confirmed via the
  ``crypto_bls_*`` metrics: successful aggregate-commit verifications,
  lanes proven via the aggregate (never individually verified), and at
  least one per-valset cohort table build;
- replay byte-identically: a second same-seed run must produce the
  identical verdict JSON (block hashes included).

Exit 0 on success, 1 with a reason on any failure.  Wired into the lint
workflow beside smoke_scenarios; runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_bls.py
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEED = 20260807


def scenario():
    from cometbft_tpu.sim import Scenario

    return Scenario(
        name="smoke-bls-mixed",
        seed=SEED, n_nodes=4, out_links=2, target_height=5,
        max_virtual_s=600.0,
        key_types=["bls12_381", "ed25519", "bls12_381", "ed25519"])


def fail(msg: str) -> None:
    print(f"[smoke-bls] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.sim.scenario import run_scenario

    ok_before = m.counter("crypto_bls_verify_total").value(result="ok")
    bad_before = (m.counter("crypto_bls_verify_total")
                  .value(result="bad_signature"))
    lanes_before = m.counter("crypto_bls_lanes_total").value()

    t0 = time.monotonic()
    v1 = run_scenario(scenario())
    t1 = time.monotonic() - t0
    v2 = run_scenario(scenario())
    wall = time.monotonic() - t0
    print(f"[smoke-bls] run1 {t1:.1f}s, total {wall:.1f}s real for "
          f"2 x {v1['virtual_duration_s']}s virtual (4 nodes, 2 BLS)")

    if not v1["fork_free"]:
        fail(f"fork detected: {v1['block_hashes']}")
    if not v1["reached_target"]:
        fail(f"stuck at height {v1['common_height']} "
             f"< {v1['target_height']}")

    agg_ok = m.counter("crypto_bls_verify_total").value(result="ok") \
        - ok_before
    agg_bad = (m.counter("crypto_bls_verify_total")
               .value(result="bad_signature")) - bad_before
    agg_lanes = m.counter("crypto_bls_lanes_total").value() - lanes_before
    if agg_ok < 1:
        fail("no successful aggregate-commit verification recorded "
             "(crypto_bls_verify_total{result=ok}) — the BLS cohort "
             "never folded")
    if agg_bad > 0:
        fail(f"{agg_bad:.0f} aggregate verifications FAILED "
             "(crypto_bls_verify_total{result=bad_signature}) on an "
             "honest net — aggregation domain mismatch")
    if agg_lanes < 2 * agg_ok:
        fail(f"aggregate proved only {agg_lanes:.0f} lanes over "
             f"{agg_ok:.0f} verifications — the 2-validator BLS cohort "
             "should fold both lanes every time")

    j1 = json.dumps(v1, sort_keys=True)
    j2 = json.dumps(v2, sort_keys=True)
    if j1 != j2:
        for k in v1:
            if json.dumps(v1[k], sort_keys=True) != \
                    json.dumps(v2[k], sort_keys=True):
                print(f"  diverged field {k!r}:\n    {v1[k]}\n    {v2[k]}",
                      file=sys.stderr)
        fail("verdict JSON diverged across same-seed runs")

    print(f"[smoke-bls] OK: fork-free at {v1['common_height']}, "
          f"{agg_ok:.0f} aggregate verifications proving "
          f"{agg_lanes:.0f} lanes, replay identical")


if __name__ == "__main__":
    main()
