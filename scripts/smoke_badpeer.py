#!/usr/bin/env python
"""CI bad-peer smoke: a seeded 3-node net where ONE node's outbound
links are armed with ``p2p.send.corrupt`` (the ``node=`` selector of the
fault plane).  Asserts the peer-quality defense layer end to end:

- the victim's scorer accumulates misbehavior for the corrupting peer
  and issues a TIMED ban (visible in the scorer, the ban metric, and
  /net_info's ``bans`` block),
- the victim keeps committing off the good validator THROUGH the ban
  (fork-free liveness),
- the corruption schedule drains and the banned peer is READMITTED
  after the TTL expires,
- the fault schedule fired at its exact seeded call indices (the
  same-seed reproduction contract — ``every=2`` over the bad node's
  send stream only).

Exit 0 on success, 1 with a reason on any failure.  Used by the lint
workflow next to ``scripts/smoke_chaos.py``; runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_badpeer.py
"""

import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEED = 20260811
MAX_FIRES = 8
SPEC = f"p2p.send.corrupt:node=bp-bad:every=2:max={MAX_FIRES}"


async def scenario() -> None:
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.libs import failures as F
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.rpc.core import Environment, net_info
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    F.reset()
    F.configure(enabled=True, seed=SEED, faults=[SPEC])
    pvs = [MockPV.from_secret(b"bp-%d" % i) for i in range(2)]
    doc = GenesisDoc(chain_id="badpeer-smoke",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])

    async def mk(name, pv, victim=False):
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""
        cfg.base.signature_backend = "cpu"
        cfg.instrumentation.watchdog_stall_threshold_s = 0.0
        if victim:
            cfg.p2p.quality_disconnect_score = 1.5
            cfg.p2p.quality_ban_score = 3.5
            cfg.p2p.quality_ban_ttl_s = 1.5
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pv, config=cfg,
            node_key=NodeKey.from_secret(name.encode()), name=name)
        await node.start()
        return node

    victim = await mk("bp-victim", pvs[0], victim=True)
    good = await mk("bp-good", pvs[1])
    bad = await mk("bp-bad", None)          # observer; its links corrupt
    nodes = [victim, good, bad]
    try:
        await good.dial_peer(victim.listen_addr, persistent=True)
        await bad.dial_peer(victim.listen_addr, persistent=True)
        bad_id = bad.node_key.id
        vsw = victim.switch

        deadline = time.monotonic() + 20
        while not all(n.height() >= 2 for n in (victim, good)):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no progress: {[n.height() for n in nodes]}")
            await asyncio.sleep(0.1)

        # score decay -> timed ban
        deadline = time.monotonic() + 25
        while vsw.scorer.bans_total < 1:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"victim never banned the corrupting peer: "
                    f"scorer={vsw.scorer.snapshot()} "
                    f"chaos={F.stats()['sites']}")
            await asyncio.sleep(0.05)
        info = vsw.scorer.peer_info(bad_id)
        if info.get("ban_count", 0) < 1:
            raise RuntimeError(f"ban did not target the bad peer: {info}")
        ni = await net_info(Environment(victim))
        if vsw.scorer.is_banned(bad_id) and \
                not any(b["node_id"] == bad_id for b in ni["bans"]):
            raise RuntimeError(f"/net_info bans block missing: {ni['bans']}")
        bans_counter = m.counter("p2p_peer_bans_total")
        bans = sum(bans_counter.value(node=victim.node_key.id[:8],
                                      reason=r)
                   for r in ("protocol_error", "malformed_frame",
                             "invalid_vote", "invalid_part",
                             "invalid_proposal", "pong_timeout"))
        if bans < 1:
            raise RuntimeError("p2p_peer_bans_total never incremented")

        # liveness off the good peer through the ban
        h_ban = victim.height()
        deadline = time.monotonic() + 20
        while victim.height() < h_ban + 3:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"victim stalled after the ban at {victim.height()}")
            await asyncio.sleep(0.1)

        # schedule drains -> ban expires -> readmission
        deadline = time.monotonic() + 30
        while True:
            fired = F.stats()["sites"]["p2p.send.corrupt"]["fired"]
            if fired >= MAX_FIRES and not vsw.scorer.is_banned(bad_id) \
                    and bad_id in vsw.peers:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no readmission: fired={fired} "
                    f"banned={vsw.scorer.is_banned(bad_id)} "
                    f"connected={bad_id in vsw.peers}")
            await asyncio.sleep(0.1)

        # fork-free at every common height
        common = min(victim.height(), good.height())
        for h in range(1, common + 1):
            hs = {n.block_store.load_block(h).hash()
                  for n in (victim, good)
                  if n.block_store.load_block(h) is not None}
            if len(hs) != 1:
                raise RuntimeError(f"fork at height {h}: {hs}")

        # seeded-schedule reproduction: every=2 over the bad node's
        # stream fires at exactly 2,4,...,2*MAX_FIRES
        corrupts = sorted((n for s, n, _ in F.signature()
                           if s == "p2p.send.corrupt"))
        expected = [2 * k for k in range(1, MAX_FIRES + 1)]
        if corrupts != expected:
            raise RuntimeError(
                f"corruption schedule drifted: {corrupts} != {expected}")
        print(f"badpeer smoke ok: ban after "
              f"{info.get('events_total', '?')} scored events, "
              f"{common} heights fork-free, peer readmitted, "
              f"{MAX_FIRES} faults at the seeded indices")
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass
        F.reset()


def main() -> int:
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(scenario())
    except RuntimeError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    finally:
        loop.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
