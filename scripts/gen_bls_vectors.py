"""Regenerate tests/vectors/bls12381_conformance.json.

The vector file pins cross-backend BLS12-381 behavior that consensus
depends on but that a plausible backend could silently get wrong —
above all the G2/G1 SUBGROUP checks.  A same-message aggregate is the
one place where the subgroup check is the ONLY defense (verification of
an individual signature fails the pairing equation anyway; aggregation
does no pairing at all), so a backend that skips the check would accept
a poisoned aggregate input here and nowhere else.  These vectors make
that a test failure instead of a consensus fork.

Deterministic: fixed IKM seeds, fixed message, smallest-x curve scan
for the out-of-subgroup points.  Run from the repo root:

    python scripts/gen_bls_vectors.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.crypto import _bls12381_py as py  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "vectors", "bls12381_conformance.json")

MESSAGE = b"tpu-bft bls conformance r20"


def _hex(b: bytes) -> str:
    return bytes(b).hex()


def find_g1_wrong_subgroup() -> bytes:
    """Smallest-x on-curve G1 point outside the order-r subgroup,
    compressed.  The G1 cofactor is ~2^125 so the scan terminates almost
    immediately; g1_in_subgroup pins the exclusion."""
    x = 0
    while True:
        x += 1
        y2 = (x * x * x + 4) % py.P
        y = pow(y2, (py.P + 1) // 4, py.P)
        if y * y % py.P != y2:
            continue
        pt = (x, min(y, py.P - y))
        if not py.g1_in_subgroup(pt):
            return py.g1_compress(pt)


def find_g2_wrong_subgroup() -> bytes:
    """Same scan over the twist: x = x0 (real), smallest x0 whose curve
    equation has a root and whose point is outside the subgroup."""
    x0 = 0
    while True:
        x0 += 1
        raw = bytearray(96)
        raw[0] = 0x80                       # compressed, positive y
        raw[48:96] = x0.to_bytes(48, "big")  # c0 in the low half
        try:
            pt = py.g2_decompress(bytes(raw))
        except ValueError:
            continue
        if pt is None or py.g2_in_subgroup(pt):
            continue
        return py.g2_compress(pt)


def main() -> None:
    keys = []
    sigs = []
    for i in range(1, 5):
        sk = py.keygen(bytes([i]) * 48)
        pk = py.sk_to_pk(sk)
        keys.append({
            "ikm": _hex(bytes([i]) * 48),
            "sk": sk.to_bytes(32, "big").hex(),
            "pk": _hex(pk),
            "pop": _hex(py.pop_prove(sk)),
            "sig": _hex(py.sign(sk, MESSAGE)),
        })
        sigs.append(py.sign(sk, MESSAGE))

    pks = [bytes.fromhex(k["pk"]) for k in keys]
    g1_bad = find_g1_wrong_subgroup()
    g2_bad = find_g2_wrong_subgroup()
    assert py.g1_decompress(g1_bad) is not None
    assert py.g2_decompress(g2_bad) is not None

    vectors = {
        "comment": "Pinned BLS12-381 conformance vectors; regenerate "
                   "with scripts/gen_bls_vectors.py. Every constructible "
                   "backend must agree with every byte in this file.",
        "ciphersuite": "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_",
        "pop_dst": "BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_",
        "message": _hex(MESSAGE),
        "keys": keys,
        "aggregate_signature": _hex(py.aggregate_signatures(sigs)),
        "aggregate_pubkey": _hex(py.aggregate_pubkeys(pks)),
        "g1_infinity": "c0" + "00" * 47,
        "g2_infinity": "c0" + "00" * 95,
        "g1_wrong_subgroup": _hex(g1_bad),
        "g2_wrong_subgroup": _hex(g2_bad),
        # a Basic-suite signature over the pk bytes: must NOT verify as a
        # proof of possession (the POP_ DST exists precisely so vote
        # signatures can never double as possession proofs)
        "pop_wrong_dst": _hex(py.sign(
            int.from_bytes(bytes.fromhex(keys[0]["sk"]), "big"),
            bytes.fromhex(keys[0]["pk"]))),
    }

    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(vectors, f, indent=1)
        f.write("\n")
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
