"""BLS native-backend robustness fuzz: malformed/garbage/mutated inputs
must never verify and never crash; every native accept must be a
Python-oracle accept (sampled)."""
import os, sys, random, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, __import__("os").path.join(__import__("os").path.dirname(__file__), ".."))
from cometbft_tpu.jaxenv import harden_cpu_pinned_env
harden_cpu_pinned_env()
from cometbft_tpu.crypto import _bls12381_py as B
from cometbft_tpu.crypto import bls12381 as keys

n = keys._NativeBackend()
rng = random.Random(20260731)
sk = rng.randrange(1, B.R)
pk = B.sk_to_pk(sk)
msg = b"fuzz-msg"
sig = B.sign(sk, msg)
assert n.verify(pk, msg, sig)

t0 = time.time()
trials = accepts = 0
checked_cross = 0
N = int(sys.argv[1]) if len(sys.argv) > 1 else 20000
for i in range(N):
    mode = rng.randrange(6)
    p, m, s = pk, msg, sig
    if mode == 0:        # random garbage sig
        s = rng.randbytes(96)
    elif mode == 1:      # random garbage pk
        p = rng.randbytes(48)
    elif mode == 2:      # bitflip sig
        b_ = bytearray(sig); b_[rng.randrange(96)] ^= 1 << rng.randrange(8)
        s = bytes(b_)
    elif mode == 3:      # bitflip pk
        b_ = bytearray(pk); b_[rng.randrange(48)] ^= 1 << rng.randrange(8)
        p = bytes(b_)
    elif mode == 4:      # msg mutation
        m = msg + bytes([rng.randrange(256)])
    else:                # flag-byte adversarial: force comp/inf/sign bits
        b_ = bytearray(sig); b_[0] = rng.randrange(256)
        s = bytes(b_)
    ok = n.verify(p, m, s)
    trials += 1
    if ok:
        accepts += 1
        # any accept of a mutated input must agree with the oracle
        assert B.verify(p, m, s), (i, mode)
        checked_cross += 1
        # the only legitimate accepts are identity mutations
        assert (p, m, s) == (pk, msg, sig), ("non-identity accept!", i, mode)

# ---- aggregate path: mutated aggregates must never fast-verify ----
# a 4-signer cohort on one shared message (the commit-aggregation shape)
sks = [rng.randrange(1, B.R) for _ in range(4)]
pks = [B.sk_to_pk(k) for k in sks]
amsg = b"agg-fuzz-msg"
asigs = [B.sign(k, amsg) for k in sks]
agg_sig = keys.aggregate_signatures(asigs, check=False)
agg_pk = keys.aggregate_pubkeys(pks)
assert keys.fast_aggregate_verify(pks, amsg, agg_sig)
assert n.verify(agg_pk, amsg, agg_sig)

agg_trials = agg_accepts = 0
AN = max(N // 4, 1000)
for i in range(AN):
    mode = rng.randrange(6)
    ps, m, s = list(pks), amsg, agg_sig
    if mode == 0:        # bitflip aggregate sig
        b_ = bytearray(s); b_[rng.randrange(96)] ^= 1 << rng.randrange(8)
        s = bytes(b_)
    elif mode == 1:      # drop a signer from the claimed cohort
        ps.pop(rng.randrange(len(ps)))
    elif mode == 2:      # duplicate a signer (bitmap can't, the API must)
        ps.append(ps[rng.randrange(len(ps))])
    elif mode == 3:      # swap in a fresh non-signer key
        ps[rng.randrange(len(ps))] = B.sk_to_pk(rng.randrange(1, B.R))
    elif mode == 4:      # msg mutation under the real aggregate
        m = amsg + bytes([rng.randrange(256)])
    else:                # substitute one individual sig for the aggregate
        s = asigs[rng.randrange(len(asigs))]
    ok = keys.fast_aggregate_verify(ps, m, s)   # documented never-raises
    agg_trials += 1
    if ok:
        agg_accepts += 1
        assert (ps, m, s) == (pks, amsg, agg_sig), \
            ("non-identity aggregate accept!", i, mode)

print(f"{trials} mutated-input trials: {accepts} accepts "
      f"(all identity + oracle-confirmed), "
      f"{agg_trials} mutated-aggregate trials: {agg_accepts} accepts, "
      f"0 crashes, {time.time()-t0:.0f}s")
