"""AOT compile-bundle smoke: build a tiny plan's bundle on CPU, round-trip
it through save/load, and prove a SECOND process's first dispatch is warm.

What it checks (the r13 acceptance bar, scaled to a CI budget):

1. build: AOT-lower + compile the tiny plan's one merkle-level bucket,
   serialize it into a versioned bundle file (measures the build time —
   that is the cost the bundle saves every later process).
2. staleness guard: a load under a DIFFERENT plan hash must be ignored
   with status "stale" and a `crypto_compile_bundle_stale_total` tick —
   never a crash, never a wrong executable.
3. second process: a fresh interpreter loads the bundle, dispatches the
   bucket through `aotbundle.timed_call` (which records the PR 5
   `crypto_kernel_first_dispatch_seconds` gauge), asserts the output
   matches the hashlib reference, and asserts the first-dispatch gauge
   is warm-dispatch-sized — a fraction of the parent's measured
   trace+compile time — proving cold-start-with-bundle ~= warm.

The merkle-level kernel keeps the smoke inside a CI minute; the bundle
machinery (enumerate -> lower -> serialize -> version-check -> load ->
dispatch) is exactly the path the verify/RLC buckets take on a device
host, where the same load replaces a ~110 s compile (PR 5 measurement).

Runs on CPU (JAX_PLATFORMS=cpu), ~10 s.  Exit 0 = pass.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

LANES = 256


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def ok(msg: str) -> None:
    print(f"ok: {msg}", flush=True)


def tiny_plan():
    from cometbft_tpu.crypto import plan as P

    return dataclasses.replace(P.DevicePlan(), warm_kinds=(),
                               warm_merkle=(LANES,))


def expected_root() -> bytes:
    return hashlib.sha256(b"\x01" + b"\x00" * 64).digest()


def child(path: str, t_build: float) -> None:
    """The 'spun-up verify node': fresh process, prewarmed bundle."""
    import numpy as np

    from cometbft_tpu.crypto import aotbundle
    from cometbft_tpu.libs import metrics

    info = aotbundle.load(path=path, plan=tiny_plan())
    if info["status"] != "loaded":
        fail(f"child expected a loaded bundle, got {info['status']!r}")
    key = f"merkle_level:{LANES}"
    if info["buckets"].get(key) != "warm":
        fail(f"bucket {key} not warm in child: {info['buckets']}")
    left = np.zeros((LANES, 8), np.uint32)
    out = np.asarray(aotbundle.timed_call(key, left, left))
    got = b"".join(int(w).to_bytes(4, "big") for w in out[0])
    if got != expected_root():
        fail("bundled executable computed a wrong inner-node hash")
    g = metrics.gauge("crypto_kernel_first_dispatch_seconds", "")
    first = g.value(kind="merkle_level", lanes=str(LANES))
    # warm bar: a fraction of the parent's trace+compile time, and small
    # in absolute terms (a compile would pay lowering alone >bar)
    bar = max(0.25, t_build / 2)
    if not 0 <= first < bar:
        fail(f"first dispatch {first:.3f}s not warm (bar {bar:.3f}s, "
             f"build was {t_build:.3f}s)")
    warm_n = metrics.gauge("crypto_compile_bundle_info", "").value(
        version=str(info["version"]), status="loaded")
    if warm_n < 1:
        fail("crypto_compile_bundle_info gauge missing the warm bucket")
    print(f"CHILD-OK first_dispatch={first * 1e3:.2f}ms "
          f"build_was={t_build:.2f}s", flush=True)


def main() -> None:
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        child(sys.argv[2], float(sys.argv[3]))
        return

    from cometbft_tpu.crypto import aotbundle
    from cometbft_tpu.libs import metrics

    plan = tiny_plan()
    with tempfile.TemporaryDirectory(prefix="smoke-bundle-") as td:
        path = os.path.join(td, "bundle.aot")
        t0 = time.perf_counter()
        info = aotbundle.build(plan=plan, path=path)
        t_build = time.perf_counter() - t0
        if info["status"] != "built":
            fail(f"build status {info['status']!r}")
        if not os.path.exists(path):
            fail("bundle file missing after build")
        ok(f"built + serialized bundle in {t_build:.2f}s "
           f"({os.path.getsize(path)} bytes, version {info['version']})")

        # staleness guard: a different plan hash must refuse the file
        other = dataclasses.replace(plan, rlc_min_lanes=7)
        ctr = metrics.counter("crypto_compile_bundle_stale_total", "")
        before = ctr.value(reason="version")
        aotbundle.reset()
        sinfo = aotbundle.load(path=path, plan=other)
        if sinfo["status"] != "stale":
            fail(f"stale bundle not refused: {sinfo['status']!r}")
        if ctr.value(reason="version") != before + 1:
            fail("stale refusal did not tick "
                 "crypto_compile_bundle_stale_total{reason=version}")
        if aotbundle.lookup(f"merkle_level:{LANES}") is not None:
            fail("stale bundle leaked an executable into the table")
        ok("version-mismatched bundle ignored with warning + counter")

        # second process: first dispatch must be warm
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", path,
             f"{t_build:.4f}"],
            env=env, timeout=120, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        print(proc.stdout, end="", flush=True)
        if proc.returncode != 0 or "CHILD-OK" not in proc.stdout:
            fail(f"child process rc={proc.returncode}")
        ok("second-process first dispatch served warm from the bundle")
    print("PASS: AOT compile-bundle smoke", flush=True)


if __name__ == "__main__":
    main()
