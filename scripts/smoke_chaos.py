#!/usr/bin/env python
"""CI chaos smoke: a seeded 2-node net runs twice under the same fault
schedule (libs/failures) and must behave identically —

- both runs commit blocks THROUGH the faults (message corruption every
  10th delivered message, one injected scheduler-dispatch failure),
- both runs agree on every block hash (safety) and neither records a
  consensus fatal error (the injected faults are absorbable ones),
- the two runs produce the IDENTICAL fault event log (the
  same-seed-reproduction contract the chaos acceptance suite relies on).

Exit 0 on success, 1 with a reason on any failure.  Used by the lint
workflow next to ``scripts/smoke_rpc.py``; runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_chaos.py
"""

import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TARGET_HEIGHT = 4
CORRUPT_SPEC = "p2p.recv.corrupt:every=10:max=5"
SCHED_SPEC = "sched.dispatch.raise:at=1"
SEED = 20260804


async def one_run() -> tuple[list, list]:
    """Start 2 validators under the seeded schedule, commit to
    TARGET_HEIGHT, return (fault signature, block hashes)."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.libs import failures as F
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    F.reset()
    F.configure(enabled=True, seed=SEED,
                faults=[CORRUPT_SPEC, SCHED_SPEC])
    pvs = [MockPV.from_secret(b"chaos-smoke-%d" % i) for i in range(2)]
    doc = GenesisDoc(chain_id="chaos-smoke-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])
    nodes = []
    for i, pv in enumerate(pvs):
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""
        cfg.base.signature_backend = "cpu"
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pv, config=cfg,
            node_key=NodeKey.from_secret(b"csk%d" % i), name=f"cs{i}")
        nodes.append(node)
        await node.start()
    try:
        await nodes[0].dial_peer(nodes[1].listen_addr, persistent=True)
        # internal deadlines are sized so TWO runs plus interpreter
        # startup fit the workflow's kill budget with margin — a slow
        # CI box must fail with THIS script's diagnostics, never an
        # opaque SIGTERM from the outer timeout
        deadline = time.monotonic() + 18
        while not all(n.height() >= TARGET_HEIGHT for n in nodes):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"stuck below height {TARGET_HEIGHT}: "
                    f"{[n.height() for n in nodes]}")
            await asyncio.sleep(0.1)
        # the corruption schedule must fully drain before we compare
        deadline = time.monotonic() + 6
        while sum(1 for e in F.events()
                  if e["site"] == "p2p.recv.corrupt") < 5:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"schedule never drained: {F.stats()['sites']}")
            await asyncio.sleep(0.1)
        # force one scheduler micro-batch through the armed
        # sched.dispatch.raise site (in-proc nets cache-hit their way
        # around natural batches): the injected dispatch failure must
        # still yield REAL per-item verdicts via the recovery path
        from cometbft_tpu.crypto import scheduler as vsched
        from cometbft_tpu.crypto.keys import gen_priv_key

        sched = vsched.get_scheduler()
        if sched is None:
            raise RuntimeError("no process-wide scheduler running")
        privs = [gen_priv_key() for _ in range(3)]
        msgs = [b"chaos-smoke-%d" % i for i in range(3)]
        sigs = [p.sign(m) for p, m in zip(privs, msgs)]
        sigs[1] = bytes(64)                      # one bad lane
        oks = await asyncio.gather(*[
            sched.verify(p.pub_key(), m, s)
            for p, m, s in zip(privs, msgs, sigs)])
        if oks != [True, False, True]:
            raise RuntimeError(f"bad verdicts through injected dispatch "
                               f"failure: {oks}")
        if not any(e["site"] == "sched.dispatch.raise"
                   for e in F.events()):
            raise RuntimeError("sched.dispatch.raise never fired")
        for n in nodes:
            if n.consensus.fatal_error is not None:
                raise RuntimeError(
                    f"{n.name} went fatal: {n.consensus.fatal_error!r}")
        common = min(n.height() for n in nodes)
        hashes = []
        for h in range(1, common + 1):
            hs = {n.block_store.load_block(h).hash() for n in nodes
                  if n.block_store.load_block(h) is not None}
            if len(hs) != 1:
                raise RuntimeError(f"fork at height {h}: {hs}")
            hashes.append(hs.pop().hex())
        return F.signature(), hashes
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass
        F.reset()


def main() -> int:
    def run(coro):
        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(coro)
        finally:
            loop.close()

    try:
        sig1, hashes1 = run(one_run())
        sig2, hashes2 = run(one_run())
    except RuntimeError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    if sig1 != sig2:
        print(f"FAIL: same seed, different fault logs:\n  run1={sig1}\n"
              f"  run2={sig2}", file=sys.stderr)
        return 1
    corrupts = [s for s in sig1 if s[0] == "p2p.recv.corrupt"]
    if [n for _, n, _ in corrupts] != [10, 20, 30, 40, 50]:
        print(f"FAIL: corruption schedule drifted: {corrupts}",
              file=sys.stderr)
        return 1
    print(f"chaos smoke ok: {len(sig1)} faults reproduced identically "
          f"across 2 runs, {len(hashes1)}+ heights committed fork-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
