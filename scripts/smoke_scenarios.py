#!/usr/bin/env python
"""CI scenario-lab smoke: one seeded 25-node adversarial run on the
virtual clock, executed TWICE —

- an asymmetric one-way partition (requests vanish, replies flow) is
  applied and healed mid-run while one validator equivocates
  (double-signs) throughout,
- both runs must reach the target height FORK-FREE with the
  equivocation committed as DuplicateVoteEvidence in a block and the
  byzantine validator identified — with no honest node banned for
  relaying the (legitimate) evidence,
- the two runs must produce the IDENTICAL chaos ``signature()`` and
  byte-identical verdict JSON — the scenario lab's replay contract.

Exit 0 on success, 1 with a reason on any failure.  Wired into the
lint workflow beside smoke_chaos/smoke_doctor; runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_scenarios.py
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEED = 20260804


def scenario():
    from cometbft_tpu.sim import Scenario

    return Scenario(
        name="smoke-asym-equivocator",
        seed=SEED, n_nodes=25, out_links=3, target_height=5,
        max_virtual_s=600.0,
        byzantine={6: "equivocator"},
        steps=[
            {"at": 0.5, "op": "partition", "one_way": True,
             "groups": [list(range(6)), list(range(6, 25))]},
            # a seeded gray failure so the replay-identity assertion has
            # a non-empty schedule to compare (every=3 on one node's
            # sends exercises per-site call-index determinism)
            {"at": 1.0, "op": "arm",
             "spec": "p2p.send.delay:node=sim010:every=3"
                     ":delay=0.05:max=40"},
            {"at": 2.0, "op": "heal"},
        ])


def one_run():
    from cometbft_tpu.sim.scenario import chaos_signature_of

    return chaos_signature_of(scenario())


def fail(msg: str) -> None:
    print(f"[smoke-scenarios] FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    t0 = time.monotonic()
    v1, sig1 = one_run()
    t1 = time.monotonic() - t0
    v2, sig2 = one_run()
    wall = time.monotonic() - t0
    print(f"[smoke-scenarios] run1 {t1:.1f}s, total {wall:.1f}s real for "
          f"2 x {v1['virtual_duration_s']}s virtual "
          f"({v1['n_nodes']} nodes)")
    if not v1["fork_free"]:
        fail(f"fork detected: {v1['block_hashes']}")
    if not v1["reached_target"]:
        fail(f"stuck at height {v1['common_height']} "
             f"< {v1['target_height']}")
    if v1["time_to_recover_s"] is None:
        fail("partition recovery never observed")
    if v1["evidence"]["committed_total"] < 1:
        fail(f"equivocation evidence never committed: {v1['evidence']}")
    if v1["evidence"]["byzantine_punished"] != ["sim006"]:
        fail(f"wrong byzantine attribution: {v1['evidence']}")
    if "bad_evidence" in v1["misbehavior_events"] or \
            "bad_evidence" in v1["bans"]["by_reason"]:
        fail("honest evidence re-gossip was punished (bad_evidence)")
    if sig1 != sig2:
        fail(f"chaos signature diverged across same-seed runs: "
             f"{len(sig1)} vs {len(sig2)} events")
    j1 = json.dumps(v1, sort_keys=True)
    j2 = json.dumps(v2, sort_keys=True)
    if j1 != j2:
        for k in v1:
            if json.dumps(v1[k], sort_keys=True) != \
                    json.dumps(v2[k], sort_keys=True):
                print(f"  diverged field {k!r}:\n    {v1[k]}\n    {v2[k]}",
                      file=sys.stderr)
        fail("verdict JSON diverged across same-seed runs")
    print(f"[smoke-scenarios] OK: fork-free at {v1['common_height']}, "
          f"evidence committed at {v1['evidence']['heights_with_evidence']}, "
          f"recovery {v1['time_to_recover_s']}s virtual, replay identical "
          f"({len(sig1)} chaos events)")


if __name__ == "__main__":
    main()
