#!/usr/bin/env python
"""CI light-serving smoke: boot one validator, then drive the serving
tier the way a bootstrapping light-client fleet would —

- ``light_blocks`` batch bootstrap over every height in one request,
- ``light_proofs`` over a block that carries txs, each proof verified
  CLIENT-SIDE against the header's data_hash,
- repeated ``light_block`` / ``light_verify`` calls must hit the header
  LRU and the whole-commit verdict memo (cache hits asserted via the
  /status light_serve block),
- a concurrent burst against a tightened admission gate must shed with
  503 + Retry-After while GET /status keeps answering 200.

Exit 0 on success, 1 with a reason on any failure.  Used by the lint
workflow (`.github/workflows/lint.yml`); runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_lightserve.py
"""

import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


async def raw_get(host: str, port: int, path: str):
    """(status, headers, body) over a one-shot connection."""
    r, w = await asyncio.open_connection(host, port)
    w.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n"
            "Connection: close\r\n\r\n".encode())
    await w.drain()
    raw = await r.read()
    w.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    lines = head.decode().split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    return status, headers, body


async def main() -> int:
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.crypto import merkle
    from cometbft_tpu.node import Node
    from cometbft_tpu.rpc import HTTPClient
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.header import tx_hash
    from cometbft_tpu.types.priv_validator import MockPV

    cfg = Config(consensus=test_consensus_config())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    # tight gate so the burst below actually sheds: 2 concurrent slots,
    # no wait queue.  The sequential driving before it never holds more
    # than one slot.
    cfg.rpc.max_concurrent_requests = 2
    cfg.rpc.max_queued_requests = 0
    cfg.rpc.shed_retry_after_s = 1.0

    pv = MockPV.from_secret(b"smoke-lightserve")
    doc = GenesisDoc(chain_id="smoke-ls",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])
    node = await Node.create(doc, KVStoreApplication(), priv_validator=pv,
                             config=cfg, name="smoke-ls")
    await node.start()
    try:
        host, port = node.rpc_addr
        cli = HTTPClient(host, port)

        # a block with several txs for the proof workload
        txs = [b"smk%d=v%d" % (i, i) for i in range(8)]
        for t in txs:
            await cli.call("broadcast_tx_sync", tx=t.hex())
        deadline = time.monotonic() + 30
        tx_height = None
        while time.monotonic() < deadline and tx_height is None:
            await asyncio.sleep(0.05)
            for h in range(1, node.block_store.height() + 1):
                blk = node.block_store.load_block(h)
                if blk is not None and len(blk.data.txs) >= len(txs):
                    tx_height = h
                    break
        if tx_height is None:
            return fail("txs never landed in one block")
        # one more height so tx_height's commit is canonical
        target = node.block_store.height() + 1
        while time.monotonic() < deadline and \
                node.block_store.height() < target:
            await asyncio.sleep(0.05)

        # ---- batched light-block bootstrap --------------------------------
        tip = node.block_store.height()
        heights = list(range(1, min(tip, 64) + 1))
        out = await cli.call("light_blocks", heights=heights)
        bad = [e for e in out["light_blocks"] if "error" in e]
        if bad:
            return fail(f"light_blocks returned errors: {bad[:2]}")
        print(f"[smoke-ls] bootstrap: {len(heights)} light blocks in "
              f"one request (tip {tip})")

        # ---- batched proofs, verified client-side -------------------------
        blk = node.block_store.load_block(tx_height)
        data_hash = blk.header.data_hash
        pr = await cli.call("light_proofs", height=tx_height, kind="tx")
        if pr["total"] != len(blk.data.txs):
            return fail(f"proof total {pr['total']} != {len(blk.data.txs)}")
        if bytes.fromhex(pr["root"]) != data_hash:
            return fail("proof root != header data_hash")
        for p in pr["proofs"]:
            proof = merkle.Proof(
                p["total"], p["index"], bytes.fromhex(p["leaf_hash"]),
                tuple(bytes.fromhex(a) for a in p["aunts"]))
            if not proof.verify(data_hash, tx_hash(blk.data.txs[p["index"]])):
                return fail(f"proof {p['index']} failed verification")
        print(f"[smoke-ls] {len(pr['proofs'])} tx proofs verified against "
              "data_hash")

        # ---- cache hits: header LRU + verdict memo ------------------------
        ent = await cli.call("light_block", height=tx_height)
        anchor = {"height": tx_height,
                  "commit": ent["light_block"]["commit"]}
        v1 = await cli.call("light_verify", anchors=[anchor])
        if v1["ok"] != 1 or v1["results"][0]["cached"]:
            return fail(f"first anchor verify wrong: {v1}")
        v2 = await cli.call("light_verify", anchors=[anchor])
        if not v2["results"][0].get("cached"):
            return fail("second anchor verify missed the verdict memo")
        await cli.call("light_block", height=tx_height)
        st = await cli.call("status")
        ls = st.get("light_serve") or {}
        if not ls.get("header_hits"):
            return fail(f"no header cache hits in /status: {ls}")
        if not ls.get("verify_hits"):
            return fail(f"no verify memo hits in /status: {ls}")
        print(f"[smoke-ls] cache hits: header={ls['header_hits']} "
              f"verify={ls['verify_hits']} proofs_served="
              f"{ls['proofs_served']}")

        # ---- overload: burst sheds 503, /status stays up ------------------
        orig = node.light_serve.proofs

        def slow_proofs(*a, **kw):
            time.sleep(0.5)          # hold the gate slot
            return orig(*a, **kw)

        node.light_serve.proofs = slow_proofs
        try:
            burst = [raw_get(host, port,
                             f"/light_proofs?height={tx_height}&kind=tx")
                     for _ in range(8)]
            status_probe = raw_get(host, port, "/status")
            results = await asyncio.gather(*burst, status_probe)
            codes = [r[0] for r in results[:-1]]
            st_code, _, _ = results[-1]
            sheds = codes.count(503)
            if sheds < 1:
                return fail(f"burst never shed (codes {codes})")
            shed_headers = [r[1] for r in results[:-1] if r[0] == 503]
            if any("retry-after" not in h for h in shed_headers):
                return fail("503 without Retry-After")
            if st_code != 200:
                return fail(f"/status -> {st_code} during the burst")
            print(f"[smoke-ls] burst: {sheds}/8 shed with 503+Retry-After, "
                  "/status stayed 200")
        finally:
            node.light_serve.proofs = orig
        await cli.close()
        print("[smoke-ls] OK")
        return 0
    finally:
        await node.stop()


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
