#!/usr/bin/env python
"""CI doctor smoke: seeded mid-log blockstore corruption on a live
2-validator net, single run —

- commit to a target height, stop the victim (a REAL FilePV validator),
- arm ``db.replay.corrupt`` (seeded bit-flip on the next blockstore
  open, file-selected so the other stores are untouched),
- restart the victim: LogDB salvage quarantines the corrupt span and
  marks the store dirty, the storage doctor's deep hash-chain scan
  gates it (truncating to the last verified height when the flip hit a
  live chain record) and clears the dirty marker,
- blocksync re-fetches, consensus rejoins (the level-triggered step
  re-check + the FilePV's stored-signature replay make the mid-round
  rejoin equivocation-free), both nodes advance,
- every common height is fork-free and the fault log carries exactly
  the seeded injection at call index 1.

Exit 0 on success, 1 with a reason on any failure.  Used by the lint
workflow next to smoke_chaos/smoke_badpeer; runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_doctor.py
"""

import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TARGET_HEIGHT = 5
SEED = 77010
SPEC = "db.replay.corrupt:file=blockstore.db:at=1:frac=0.5"


async def mk_node(doc, pv, home, name, fast_sync=False):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey

    cfg = Config(consensus=test_consensus_config())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = ""
    cfg.base.signature_backend = "cpu"
    cfg.instrumentation.watchdog_stall_threshold_s = 0.0
    node = await Node.create(
        doc, KVStoreApplication(), priv_validator=pv, config=cfg,
        node_key=NodeKey.from_secret(name.encode()), home=home, name=name,
        fast_sync=fast_sync)
    await node.start()
    return node


async def wait_heights(nodes, target, budget, what):
    deadline = time.monotonic() + budget
    while not all(n.height() >= target for n in nodes):
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"{what}: stuck below {target}: "
                f"{[n.height() for n in nodes]}")
        await asyncio.sleep(0.1)


async def main_async(base_dir: str) -> None:
    from cometbft_tpu.libs import failures as F
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    F.reset()
    victim_home = os.path.join(base_dir, "victim")
    key_path = os.path.join(base_dir, "victim_key.json")
    state_path = os.path.join(victim_home, "data",
                              "priv_validator_state.json")
    good_pv = MockPV.from_secret(b"doctor-smoke-good")
    victim_pv = FilePV.generate(key_path, state_path)
    doc = GenesisDoc(chain_id="doctor-smoke-net",
                     validators=[GenesisValidator(good_pv.get_pub_key(), 10),
                                 GenesisValidator(victim_pv.get_pub_key(),
                                                  10)])
    good = await mk_node(doc, good_pv, None, "ds-good")
    victim = await mk_node(doc, victim_pv, victim_home, "ds-victim")
    nodes = [good, victim]
    try:
        await good.dial_peer(victim.listen_addr, persistent=True)
        await wait_heights(nodes, TARGET_HEIGHT, 20, "initial commit")
        h_stop = victim.height()
        await victim.stop()

        F.configure(enabled=True, seed=SEED, faults=[SPEC])
        victim = await mk_node(doc, FilePV.load(key_path, state_path),
                               victim_home, "ds-victim", fast_sync=True)
        nodes[1] = victim
        rep = victim.doctor_report.to_dict()
        salv = rep["salvage"].get("blockstore", {})
        if not salv.get("salvaged_this_open"):
            raise RuntimeError(f"salvage never fired: {rep}")
        if rep["deep_scan"] is None or not rep["ok"]:
            raise RuntimeError(f"doctor did not gate the salvage: {rep}")
        if victim.block_store.is_dirty():
            raise RuntimeError("dirty marker survived a passing deep scan")

        await victim.dial_peer(good.listen_addr, persistent=True)
        await wait_heights(nodes, h_stop + 2, 25, "post-repair catch-up")
        if victim.consensus.fatal_error is not None:
            raise RuntimeError(
                f"victim went fatal: {victim.consensus.fatal_error!r}")

        common = min(n.height() for n in nodes)
        for h in range(1, common + 1):
            hs = {n.block_store.load_block(h).hash() for n in nodes
                  if n.block_store.load_block(h) is not None}
            if len(hs) != 1:
                raise RuntimeError(f"fork at height {h}: {hs}")
        sig = F.signature()
        if sig != [("db.replay.corrupt", 1, 1)]:
            raise RuntimeError(f"fault schedule drifted: {sig}")
        trunc = rep["deep_scan"].get("truncated_to")
        print(f"doctor smoke ok: salvage span {salv.get('spans')}, "
              f"{'truncated to ' + str(trunc) if trunc is not None else 'chain verified intact'}, "
              f"{common} common heights fork-free, seeded injection at "
              f"call index 1")
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass
        F.reset()


def main() -> int:
    import tempfile

    base = tempfile.mkdtemp(prefix="doctor-smoke-")
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(main_async(base))
    except RuntimeError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    finally:
        loop.close()
        import shutil

        shutil.rmtree(base, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
