import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # CPU rehearsal on a box with a wedged relay: plain `import jax`
    # hangs in accelerator discovery unless the factories are dropped
    from cometbft_tpu.jaxenv import harden_cpu_pinned_env

    harden_cpu_pinned_env()
import numpy as np
import jax, jax.numpy as jnp
from cometbft_tpu.ops import fe

print("device:", jax.devices()[0])
if os.environ.get("KERNLAYOUT_REQUIRE_TPU"):
    # a tpu-tagged artifact must never hold silent-CPU-fallback numbers
    assert jax.devices()[0].platform != "cpu", \
        "KERNLAYOUT_REQUIRE_TPU set but jax fell back to CPU"
B = 10240
rng = np.random.default_rng(7)
an = rng.integers(0, 8191, (B, 20), dtype=np.int32)
bn = rng.integers(0, 8191, (B, 20), dtype=np.int32)
a = jnp.asarray(an); b = jnp.asarray(bn)
aT = jnp.asarray(an.T.copy()); bT = jnp.asarray(bn.T.copy())

def bench(name, f, *args, n=5):
    out = f(*args); jax.block_until_ready(out)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter(); jax.block_until_ready(f(*args))
        ts.append(time.perf_counter() - t0)
    print(f"{name:44s} {min(ts)*1e3:9.3f} ms", flush=True)

MASK = fe.MASK; RADIX = fe.RADIX; FOLD = fe.FOLD; NL = fe.NLIMBS; NC = fe.NCOLS

# --- reference: raw elementwise throughput, full-lane shape
c128 = jnp.asarray(rng.integers(0, 2**30, (B, 128), dtype=np.int32))
@jax.jit
def raw100(x):
    return jax.lax.fori_loop(0, 100, lambda _, v: (v * 3 + 7) & 0x7fffffff, x)
bench("100 mul-add elementwise (B,128)", raw100, c128)

# --- 20 chained muls, current einsum layout (B,20)
@jax.jit
def mul20_cur(a, b):
    return jax.lax.fori_loop(0, 20, lambda _, x: fe.mul(x, b), a)
bench("20 fe.mul einsum (B,20)", mul20_cur, a, b)

# --- shifted-accumulation mul, batch-major (B,20)
def mul_shift(a, b):
    out = jnp.zeros(a.shape[:-1] + (NC,), jnp.int32)
    for i in range(NL):
        out = out.at[..., i:i + NL].add(a[..., i:i + 1] * b)
    return fe._reduce_columns(out)
@jax.jit
def mul20_shift(a, b):
    return jax.lax.fori_loop(0, 20, lambda _, x: mul_shift(x, b), a)
bench("20 fe.mul shifted-acc (B,20)", mul20_shift, a, b)

# --- limb-major (20,B): shifted accumulation + carry
def wrap_carry_T(x, passes):
    for _ in range(passes):
        lo = x & MASK
        hi = x >> RADIX
        wrapped = jnp.concatenate([hi[-1:] * FOLD, hi[:-1]], axis=0)
        x = lo + wrapped
    return x

def reduce_cols_T(cols):          # (39,B) -> (20,B)
    lo = cols & MASK
    hi = cols >> RADIX
    limbs40 = jnp.concatenate([lo, jnp.zeros_like(lo[:1])], axis=0
                              ).at[1:].add(hi)
    folded = limbs40[:NL] + FOLD * limbs40[NL:]
    return wrap_carry_T(folded, 3)

def mul_T(a, b):                  # (20,B)x(20,B) -> (20,B)
    out = jnp.zeros((NC,) + a.shape[1:], jnp.int32)
    for i in range(NL):
        out = out.at[i:i + NL].add(a[i:i + 1] * b)
    return reduce_cols_T(out)

@jax.jit
def mul20_T(a, b):
    return jax.lax.fori_loop(0, 20, lambda _, x: mul_T(x, b), a)
out = bench("20 fe.mul shifted-acc (20,B)", mul20_T, aT, bT)

# check correctness of limb-major chain vs batch-major einsum chain
r1 = np.asarray(jax.jit(mul20_cur)(a, b))
r2 = np.asarray(jax.jit(mul20_T)(aT, bT)).T
v1 = [fe.int_from_limbs(r1[i]) % fe.P_INT for i in range(3)]
v2 = [fe.int_from_limbs(r2[i]) % fe.P_INT for i in range(3)]
assert v1 == v2, "limb-major mul diverges!"
print("limb-major chain correct")

# --- einsum formulation in limb-major: cols[k,b] = sum_i a[i,b] * bT_toeplitz
IDX = np.asarray(fe._MUL_IDX); MSK = np.asarray(fe._MUL_MSK)
@jax.jit
def mul20_T_einsum(a, b):
    def one(x, b):
        bmat = b[jnp.asarray(IDX)] * jnp.asarray(MSK)[..., None]   # (20,39,B)
        cols = jnp.einsum("ib,ikb->kb", x, bmat,
                          preferred_element_type=jnp.int32)
        return reduce_cols_T(cols)
    return jax.lax.fori_loop(0, 20, lambda _, x: one(x, b), a)
bench("20 fe.mul einsum (20,B)", mul20_T_einsum, aT, bT)

# --- add / carry costs in both layouts
@jax.jit
def add100(a, b):
    return jax.lax.fori_loop(0, 100, lambda _, x: fe.add(x, b), a)
bench("100 fe.add (B,20)", add100, a, b)
@jax.jit
def add100T(a, b):
    return jax.lax.fori_loop(0, 100, lambda _, x: wrap_carry_T(x + b, 1), a)
bench("100 add+carry (20,B)", add100T, aT, bT)


# ---- full-pipeline timing: production (limb-major) per-lane kernel ----
# (the batch-major full pipeline was deleted when the limb-major layout
# was promoted in round 5; the comparison of record is r04-notes.md)
from cometbft_tpu.ops import ed25519 as _prod_kernel
from cometbft_tpu.testing import dense_signature_batch as _dsb

for B2 in (1024, 4096):
    args, _ = _dsb(B2, msg_len=120, seed=2024)
    args = jax.device_put(args)
    f_prod = jax.jit(_prod_kernel.verify_padded)
    o1 = np.asarray(f_prod(*args))
    assert o1.all(), "production kernel rejected valid batch!"
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(f_prod(*args))
        ts.append(time.perf_counter() - t0)
    print(f"verify_padded straus       B={B2:5d} {min(ts)*1e3:9.2f} ms "
          f"({B2/min(ts):8.0f} sigs/s)", flush=True)

# ---- RLC batch kernel (round-5 structural rework), if present ---------
try:
    from cometbft_tpu.ops import rlc as _rlc
except ImportError:
    _rlc = None
if _rlc is not None:
    for B2 in (1024, 4096):
        args, _ = _dsb(B2, msg_len=120, seed=2024)
        z = _rlc.host_rlc_coeffs(B2, np.ones(B2, bool))
        rargs = jax.device_put(args + (z,))
        f_rlc = jax.jit(_rlc.verify_batch_rlc)
        ok = f_rlc(*rargs)
        assert bool(np.asarray(ok)), "RLC kernel rejected valid batch!"
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(f_rlc(*rargs))
            ts.append(time.perf_counter() - t0)
        print(f"verify_batch rlc           B={B2:5d} {min(ts)*1e3:9.2f} ms "
              f"({B2/min(ts):8.0f} sigs/s)", flush=True)
