#!/usr/bin/env bash
# chip-wake runbook (VERDICT r3 weak 1b): ONE command to run the moment
# the axon TPU relay answers.  Probes first (subprocess + hard timeout —
# never init jax in this shell's process), then runs every BENCH_MODE on
# the chip at the BASELINE shapes, the multichip dryrun, and stages the
# artifacts under docs/bench/ as r${ROUND}-<mode>-tpu.json.
#
#   scripts/chip_wake.sh            # probe + full sweep + git add
#   ROUND=05 scripts/chip_wake.sh   # artifact prefix (default 04)
#   PROBE_ONLY=1 scripts/chip_wake.sh   # just the probe + log line
#   FORCE=1 TAG=cputest MODES=verifycommit scripts/chip_wake.sh
#                                   # CPU rehearsal: skip probe gate, tag
#                                   # artifacts, run a subset of modes
#
# Exit codes: 0 = sweep complete, 2 = chip still wedged (logged),
# 3 = FORCE=1 rehearsal attempted under the canonical tpu TAG.
set -u
cd "$(dirname "$0")/.."
ROUND="${ROUND:-05}"
TAG="${TAG:-tpu}"
MODES="${MODES:-commit verifycommit p50commit light blocksync stress node}"
LOG=docs/bench/tpu_probe_log.txt
STAMP=$(date -u +%Y-%m-%dT%H:%M)

# ---- probe (the ONLY safe way: throwaway subprocess, hard timeout) ----
# Sweep artifacts pin the backend they claim: tpu-tagged files force the
# tpu attempt.  A FORCE=1 rehearsal on a chipless box instead lets
# bench.py pick (the cpu attempt), so the rehearsal measures something.
DEFAULT_BACKEND=tpu
if [ "${FORCE:-}" = "1" ]; then
    if [ "$TAG" = tpu ]; then
        # a rehearsal must never write cpu measurements into the
        # canonical r*-<mode>-tpu.json artifacts
        echo "FORCE=1 requires a custom TAG (e.g. TAG=cputest)" >&2
        exit 3
    fi
    echo "FORCE=1: skipping probe gate (artifacts tagged -$TAG)"
    DEFAULT_BACKEND=
elif timeout 60 python -c 'import jax; assert any(d.platform != "cpu" for d in jax.devices())' 2>/dev/null; then
    echo "$STAMP probe: TPU ALIVE" >> "$LOG"
    echo "chip is awake — running the full sweep"
else
    echo "$STAMP probe: TIMEOUT after 60s (axon relay still wedged)" >> "$LOG"
    echo "chip still wedged (logged to $LOG)"
    exit 2
fi
[ "${PROBE_ONLY:-}" = "1" ] && exit 0

fail=0
run_mode () {  # $1 = mode name, rest = env pairs
    local mode="$1"; shift
    case " $MODES " in (*" $mode "*) ;; (*) return 0;; esac
    # the node mode has no accelerator leg (bench.py always runs its CPU
    # full-stack measurement) — never stamp its artifact with the tpu
    # tag.  Custom TAGs (rehearsals) keep their own name so they cannot
    # clobber the canonical r*-node-cpu.json artifact.
    local tag="$TAG" backend="${BENCH_BACKEND:-$DEFAULT_BACKEND}"
    if [ "$mode" = node ]; then
        backend=cpu
        [ "$tag" = tpu ] && tag=cpu
    fi
    local out="docs/bench/r${ROUND}-${mode}-${tag}.json"
    echo "--- BENCH_MODE=$mode -> $out"
    if env BENCH_MODE="$mode" BENCH_BACKEND="$backend" \
         "$@" timeout 1800 python bench.py \
         > "$out" 2> "/tmp/bench-${mode}.err"; then
        tail -1 "$out"
    else
        echo "MODE $mode FAILED (stderr tail):"; tail -5 "/tmp/bench-${mode}.err"
        fail=1
    fi
}

# the BASELINE modes at BASELINE shapes, plus end-to-end node mode
run_mode commit
run_mode verifycommit BENCH_VALS=150
run_mode p50commit    BENCH_VALS=10000
run_mode light        BENCH_HEADERS=1000 BENCH_VALS=150
run_mode blocksync    BENCH_BLOCKS=500 BENCH_VALS=1000
run_mode stress       BENCH_VALS=10000 BENCH_SECP_PCT=10
run_mode node         BENCH_RATE=2000 BENCH_DURATION=20

# Kernel-layout experiments (fe.mul shifted-accumulation, limb-major
# layout, batch scaling) — the measurements the wedged chip has owed
# since the first alive window; results feed the next fe.mul default.
case " $MODES " in (*" kernlayout "*|*" commit "*)
    klout="docs/bench/r${ROUND}-kernlayout-${TAG}.txt"
    echo "--- kernel layout probe -> $klout"
    # tpu-tagged artifacts must hold tpu measurements (the probe asserts
    # the platform), and a failed run must not clobber a committed one
    kreq=1 kplat=
    if [ "$TAG" != tpu ]; then
        # rehearsal: pin jax to CPU so a wedged relay cannot hang the
        # probe's import in accelerator discovery
        kreq= kplat=cpu
    fi
    if env KERNLAYOUT_REQUIRE_TPU="$kreq" JAX_PLATFORMS="$kplat" timeout 1800 \
         python scripts/kern_layout_probe.py > "$klout.tmp" 2>&1; then
        mv "$klout.tmp" "$klout"
        tail -6 "$klout"
        git add "$klout"
    else
        echo "kernel layout probe FAILED (non-fatal):"; tail -3 "$klout.tmp"
        rm -f "$klout.tmp"
    fi
;; esac

echo "--- dryrun_multichip(8)"
if timeout 900 python -c '
import __graft_entry__ as g
g.dryrun_multichip(8)
print("dryrun_multichip: ok")'; then :; else
    echo "dryrun_multichip FAILED"; fail=1
fi

git add "$LOG"
for f in docs/bench/r${ROUND}-*-${TAG}.json docs/bench/r${ROUND}-node-cpu.json; do
    [ -f "$f" ] && git add "$f"
done
echo "artifacts staged; commit with:"
echo "  git commit -m 'round ${ROUND#0}: TPU bench artifacts (chip awake)'"
exit $fail
