#!/usr/bin/env python
"""CI mempool smoke: a 2-node net (one validator, one observer) driven
through the r16 admission + gossip path end to end:

- a burst of sig-less kvstore txs enters through the RPC broadcast
  routes (sharded admission, coalesced CheckTx),
- the observer learns them over CONTENT-ADDRESSED gossip — its
  fetch-on-miss counters must show announce -> request -> body round
  trips, not full-body re-flooding,
- every tx commits, and block inclusion across heights preserves the
  RPC submission order exactly (merged-shard reap FIFO),
- the validator's RPC admission gate sheds part of a concurrent
  broadcast burst with 503 + Retry-After while /status stays answerable
  (the overload story stays true with the new mempool underneath).

Exit 0 on success, 1 with a reason on any failure.  Used by the lint
workflow beside the other smokes; runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_mempool.py
"""

import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_TXS = 40
DEADLINE_S = 25


async def http_get(host, port, path):
    r, w = await asyncio.open_connection(host, port)
    w.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n"
            "Connection: close\r\n\r\n".encode())
    await w.drain()
    raw = await r.read()
    w.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.decode().split("\r\n")[0].split(" ")[1])
    headers = {}
    for ln in head.decode().split("\r\n")[1:]:
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    return status, headers, body


async def scenario() -> None:
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.rpc.core import Environment, broadcast_tx_sync
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    pv = MockPV.from_secret(b"mp-smoke-val")
    doc = GenesisDoc(chain_id="mempool-smoke",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])

    async def mk(name, pv_, rpc=False):
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = "tcp://127.0.0.1:0" if rpc else ""
        cfg.base.signature_backend = "cpu"
        cfg.instrumentation.watchdog_stall_threshold_s = 0.0
        cfg.mempool.gossip_mode = "announce"
        cfg.mempool.fetch_timeout_s = 0.5
        if rpc:
            # a 1-slot, 0-queue gate so the 503 shed probe is
            # deterministic: any overlap in the burst must shed
            cfg.rpc.max_concurrent_requests = 1
            cfg.rpc.max_queued_requests = 0
            cfg.rpc.shed_retry_after_s = 2.0
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pv_, config=cfg,
            node_key=NodeKey.from_secret(name.encode()), name=name)
        await node.start()
        return node

    val = await mk("mp-val", pv, rpc=True)
    obs = await mk("mp-obs", None)
    try:
        await obs.dial_peer(val.listen_addr, persistent=True)
        deadline = time.monotonic() + 15
        while val.node_key.id not in obs.switch.peers:
            if time.monotonic() > deadline:
                raise RuntimeError("observer never connected")
            await asyncio.sleep(0.05)

        # ---- burst through RPC: sharded admission, FIFO contract ----
        env = Environment(val)
        txs = [b"smoke%03d=v%03d" % (i, i) for i in range(N_TXS)]
        for tx in txs:
            res = await broadcast_tx_sync(env, tx=tx.hex())
            if res["code"] != 0:
                raise RuntimeError(f"tx rejected at admission: {res}")

        # ---- 503 shed probe: concurrent burst vs the 1-slot gate ----
        host, port = val.rpc_addr
        burst = await asyncio.gather(*(
            http_get(host, port,
                     f"/broadcast_tx_sync?tx=%22{(b'b%d=v' % i).hex()}%22")
            for i in range(8)))
        statuses = [st for st, _, _ in burst]
        if 503 not in statuses:
            raise RuntimeError(
                f"1-slot gate never shed 503 under an 8-wide concurrent "
                f"burst: {statuses}")
        if 200 not in statuses:
            raise RuntimeError(f"gate shed EVERYTHING: {statuses}")
        shed_hdr = next(h for st, h, _ in burst if st == 503)
        if shed_hdr.get("retry-after") != "2":
            raise RuntimeError(f"503 missing Retry-After: {shed_hdr}")
        # status stays answerable through the shed (diagnostics exempt)
        st, _, _ = await http_get(host, port, "/status")
        if st != 200:
            raise RuntimeError(f"/status gated: {st}")

        # ---- all txs commit; inclusion order == submission order ----
        want = set(txs)
        deadline = time.monotonic() + DEADLINE_S
        while True:
            committed = []
            h = val.block_store.height()
            for height in range(1, h + 1):
                blk = val.block_store.load_block(height)
                if blk is not None:
                    committed.extend(
                        t for t in blk.data.txs if t in want)
            if want <= set(committed):
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"only {len(set(committed) & want)}/{N_TXS} txs "
                    f"committed by h{h}")
            await asyncio.sleep(0.1)
        if committed[:N_TXS] != txs:
            raise RuntimeError(
                "FIFO violated: block inclusion order != submission "
                f"order (first divergence at "
                f"{next(i for i, (a, b) in enumerate(zip(committed, txs)) if a != b)})")

        # ---- observer fetched bodies on miss (content-addressed) ----
        tallies = obs.mempool_reactor.tallies
        if tallies["fetch_requests"] < 1 or tallies["fetch_fulfilled"] < 1:
            raise RuntimeError(f"observer never fetched-on-miss: {tallies}")
        # the observer caught up fork-free
        deadline = time.monotonic() + 10
        common = 0
        while time.monotonic() < deadline:
            common = min(val.height(), obs.height())
            if common >= 2:
                break
            await asyncio.sleep(0.1)
        for h in range(1, common + 1):
            ha = val.block_store.load_block(h)
            hb = obs.block_store.load_block(h)
            if ha is None or hb is None or ha.hash() != hb.hash():
                raise RuntimeError(f"fork/missing block at h{h}")
        print(f"mempool smoke ok: {N_TXS} txs FIFO across "
              f"{val.block_store.height()} heights, observer fetched "
              f"{tallies['fetch_fulfilled']} bodies on miss "
              f"({tallies['ann_dedup']} dedup), gate shed "
              f"{statuses.count(503)}/8 with Retry-After")
    finally:
        for n in (val, obs):
            try:
                await n.stop()
            except Exception:
                pass


def main() -> int:
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(scenario())
    except RuntimeError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    finally:
        loop.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
