#!/usr/bin/env python
"""CI statesync-fabric smoke: a seeded 2-validator TCP net plus one
fresh bootstrapper, where ONE seed's statesync serving path is armed
with ``statesync.serve.corrupt`` (every served chunk gets a flipped
bit).  Asserts the snapshot fabric's corrupt-chunk discipline end to
end over real sockets:

- the bootstrapper verifies every chunk against the content-addressed
  manifest BEFORE spooling, so the corrupt seed is caught at the first
  bad chunk (``chunk_hash_mismatches`` tally),
- the corrupt seed is banned as a snapshot sender and the poisoned
  chunk is re-requested from the honest seed — the restore NEVER
  resets (``restore_resets == 0``; pre-manifest code paid a full
  whole-restore retry here),
- the sync completes off the honest seed, the restored app state
  answers queries, and the bootstrapper follows the chain fork-free.

Exit 0 on success, 1 with a reason on any failure.  Used by the lint
workflow next to ``scripts/smoke_chaos.py``; runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_statesync.py
"""

import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEED = 20260806
# the BAD seed's serving reactor (node name + ".ss") corrupts every
# chunk it serves; snapshot offers and manifests stay honest, so the
# fetcher trusts its advertised root and catches the bytes
SPEC = "statesync.serve.corrupt:node=ssmk-bad.ss:every=1"
PERIOD = 3600 * 1_000_000_000


async def scenario() -> None:
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.libs import failures as F
    from cometbft_tpu.light import Client, LocalNodeProvider, TrustOptions
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.statesync import StateProvider
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    F.reset()
    F.configure(enabled=True, seed=SEED, faults=[SPEC])
    pvs = [MockPV.from_secret(b"ssmk%d" % i) for i in range(2)]
    doc = GenesisDoc(chain_id="ssmk-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])

    def _config() -> Config:
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""
        cfg.base.signature_backend = "cpu"
        cfg.instrumentation.watchdog_stall_threshold_s = 0.0
        cfg.statesync.discovery_time_s = 0.3
        cfg.statesync.chunk_timeout_s = 3.0
        return cfg

    async def mk(name, pv, provider=None):
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pv,
            config=_config(), state_sync_provider=provider,
            node_key=NodeKey.from_secret(name.encode()), name=name)
        await node.start()
        return node

    good = await mk("ssmk-good", pvs[0])
    bad = await mk("ssmk-bad", pvs[1])
    nodes = [good, bad]
    try:
        await good.dial_peer(bad.listen_addr, persistent=True)

        # app-state ballast: enough bytes that the snapshot spans
        # several chunks, so round-robin hands the corrupt seed at
        # least one of them
        for i in range(8):
            await good.mempool.check_tx(
                b"ssmk%d=" % i + b"v" * 16384)

        deadline = time.monotonic() + 40
        while not all(n.height() >= 6 for n in nodes):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"seed chain stalled: {[n.height() for n in nodes]}")
            await asyncio.sleep(0.1)

        # the joining node trusts a recent header out of band
        trust_h = 2
        trust_hash = good.block_store.load_block(trust_h).hash()
        light = Client("ssmk-net",
                       TrustOptions(PERIOD, trust_h, trust_hash),
                       LocalNodeProvider(good.block_store,
                                         good.state_store),
                       backend="cpu")
        fresh = await mk("ssmk-fresh", None,
                         provider=StateProvider(light, doc))
        nodes.append(fresh)
        for seed in (bad, good):     # bad seed first in the rotation
            await fresh.dial_peer(seed.listen_addr, persistent=True)

        # must state-sync (no history below the snapshot), then follow
        target = max(n.height() for n in nodes[:2]) + 2
        deadline = time.monotonic() + 60
        while fresh.height() < target:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"bootstrapper stalled at {fresh.height()} "
                    f"(statesync_error={fresh.statesync_error}, "
                    f"tallies={fresh.syncer.tallies}, "
                    f"chaos={F.stats()['sites']})")
            await asyncio.sleep(0.1)
        if fresh.block_store.base() <= 1:
            raise RuntimeError(
                "node replayed from genesis instead of state syncing")

        # the corrupt seed was caught on the bytes, banned, and routed
        # around — WITHOUT a whole-restore reset
        t = fresh.syncer.tallies
        fired = F.stats()["sites"].get(
            "statesync.serve.corrupt", {}).get("fired", 0)
        if fired < 1:
            raise RuntimeError("the corrupt seed never served a chunk "
                               "(ballast too small for the rotation?)")
        if t["chunk_hash_mismatches"] < 1:
            raise RuntimeError(
                f"corrupt chunks served ({fired} fired) but never "
                f"caught: {t}")
        if t["restore_resets"] != 0:
            raise RuntimeError(
                f"corrupt chunk caused a whole-restore reset: {t}")
        if bad.node_key.id not in fresh.syncer._banned:
            raise RuntimeError(
                f"corrupt seed not banned: {fresh.syncer._banned}")
        if t["chunks_verified"] < 2:
            raise RuntimeError(f"manifest verification inactive: {t}")

        # restored app state contains pre-snapshot keys
        q = await fresh.app_conns.query.query("/key", b"ssmk0", 0, False)
        if not (q.value or b"").startswith(b"v"):
            raise RuntimeError(f"restored state missing key: {q.value!r}")

        # fork-free at every height all three share
        common = min(n.height() for n in nodes)
        for h in range(trust_h, common + 1):
            hs = {n.block_store.load_block(h).hash() for n in nodes
                  if n.block_store.load_block(h) is not None}
            if len(hs) != 1:
                raise RuntimeError(f"fork at height {h}: {hs}")

        print(f"statesync smoke ok: restored at base "
              f"{fresh.block_store.base()}, {t['chunk_hash_mismatches']} "
              f"corrupt chunks caught pre-spool ({fired} served), "
              f"0 restore resets, corrupt seed banned, "
              f"{common} heights fork-free")
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass
        F.reset()


def main() -> int:
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(scenario())
    except RuntimeError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    finally:
        loop.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
