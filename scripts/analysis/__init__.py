"""bftlint — project-native AST static analysis for cometbft_tpu.

The repo's hard-won concurrency/determinism invariants (clock seam,
lock discipline, task retention, thread-encode, fatal-IO routing,
replay identity) encoded as enforced rules.  Run from ``scripts/``:

    python -m analysis                 # whole tree, exit 1 on NEW findings
    python -m analysis --rules CLK001  # one rule (lint.sh clock gate)
    python -m analysis --json report.json

Stdlib-``ast`` only; no third-party dependencies.
"""

from .engine import main, run_paths, load_baseline  # noqa: F401

__version__ = "1.0"
