"""bftlint engine: file walking, suppressions, baseline, reporting.

Design (mirrors how libs/failures and libs/tracing stay dependency-free):

* one ``ast.parse`` per file, one shared :class:`FileContext` handed to
  every in-scope rule — rules walk the same tree, never re-read disk;
* inline suppressions ``# bftlint: disable=RULE[,RULE2] -- reason`` with
  the reason MANDATORY (a disable without one is itself a finding that
  cannot be suppressed or baselined);
* a triaged ``baseline.json`` so pre-existing, justified findings don't
  block while NEW findings exit non-zero — every entry carries a reason;
* fingerprints hash (rule, path, enclosing scope, normalized source
  line), NOT the line number, so unrelated edits above a finding don't
  invalidate the baseline.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import hashlib
import json
import re
import sys
from pathlib import Path

# scripts/analysis/engine.py -> parents[2] == repo root
REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_TARGETS = ("cometbft_tpu",)
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

# same-line suppression: "# bftlint: disable=RULE[,RULE] -- reason"
_SUPPRESS_RE = re.compile(
    r"#\s*bftlint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:--\s*(\S.*))?$")

# engine-level pseudo-rules (never suppressible, never baselined)
BAD_SUPPRESSION = "BFT000"     # disable comment without a reason
PARSE_ERROR = "BFT001"         # file does not parse


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str              # "high" | "medium"
    path: str                  # repo-relative posix path
    line: int
    col: int
    message: str
    snippet: str = ""
    scope: str = ""            # enclosing Class.func qualname, "" = module
    fingerprint: str = ""
    baselined: bool = False
    baseline_reason: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if not self.baselined:
            d.pop("baseline_reason")
        return d


class FileContext:
    """Everything a rule needs about one file, computed once."""

    def __init__(self, rel: str, source: str, tree: ast.AST):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.imports = _import_map(tree)
        # parent links (ast nodes are single-parent in a parse tree)
        self._parent: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self._parent[child] = node

    # ------------------------------------------------------------ tree nav

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parent.get(node)

    def ancestors(self, node: ast.AST):
        cur = self._parent.get(node)
        while cur is not None:
            yield cur
            cur = self._parent.get(cur)

    def enclosing_stmt(self, node: ast.AST) -> ast.stmt | None:
        """The statement a (possibly nested) expression belongs to."""
        cur: ast.AST | None = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self._parent.get(cur)
        return cur

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing def/async-def/lambda (a scope boundary)."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return anc
        return None

    def in_async_def(self, node: ast.AST) -> bool:
        """True when the nearest function scope is ``async def`` —
        nested sync defs and lambdas (thread/executor targets) are
        sync contexts even inside a coroutine."""
        return isinstance(self.enclosing_function(node),
                          ast.AsyncFunctionDef)

    def scope_qualname(self, node: ast.AST) -> str:
        parts = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(anc.name)
        return ".".join(reversed(parts))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


# --------------------------------------------------------------- resolution

def _import_map(tree: ast.AST) -> dict[str, str]:
    """local name -> dotted origin ("t" -> "time", "mono" ->
    "time.monotonic").  Relative imports keep their leading dots so
    in-package modules never collide with stdlib names."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            mod = "." * node.level + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{mod}.{a.name}" if mod \
                    else a.name
    return out


def attr_chain(node: ast.expr) -> str | None:
    """Textual dotted chain for Name/Attribute trees ("self._lock.acquire");
    None when the root isn't a plain name (e.g. a call result)."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def resolve_call(func: ast.expr, imports: dict[str, str]) -> str | None:
    """Dotted origin of a call target through the file's import aliases:
    ``m()`` after ``from time import monotonic as m`` -> "time.monotonic";
    ``t.time()`` after ``import time as t`` -> "time.time".  None when
    the root is a local object (``self.x.acquire``)."""
    chain = attr_chain(func)
    if chain is None:
        return None
    root, _, rest = chain.partition(".")
    origin = imports.get(root)
    if origin is None:
        # builtins referenced bare (open, ...) resolve to themselves
        return chain if not rest and root in {"open"} else None
    return f"{origin}.{rest}" if rest else origin


# ------------------------------------------------------------- suppressions

class Suppressions:
    """Per-file map of line -> (rules, reason) from bftlint comments.

    Two placements: trailing on the offending line, or a comment-only
    line directly ABOVE it (the comment then covers the next code
    line — long reasons don't fit in 79 columns)."""

    def __init__(self, lines: list[str], rel: str):
        self.by_line: dict[int, set[str]] = {}
        self.bad: list[Finding] = []
        for i, text in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.bad.append(Finding(
                    rule=BAD_SUPPRESSION, severity="high", path=rel,
                    line=i, col=0, snippet=text.strip()[:160],
                    message="bftlint disable without a reason — write "
                            "'# bftlint: disable=RULE -- why'"))
                continue
            self.by_line.setdefault(i, set()).update(rules)
            if text.lstrip().startswith("#"):
                # comment-only line: cover the next code line
                j = i + 1
                while j <= len(lines) and \
                        (not lines[j - 1].strip() or
                         lines[j - 1].lstrip().startswith("#")):
                    j += 1
                if j <= len(lines):
                    self.by_line.setdefault(j, set()).update(rules)

    def covers(self, rule: str, *linenos: int) -> bool:
        return any(rule in self.by_line.get(ln, ())
                   for ln in linenos if ln)


# ----------------------------------------------------------------- baseline

def load_baseline(path: Path) -> dict[str, dict]:
    """fingerprint -> entry.  Raises SystemExit(2) on malformed files or
    entries missing a triage reason (the acceptance bar: every baselined
    finding is a decision somebody wrote down)."""
    if not path.exists():
        return {}
    try:
        doc = json.loads(path.read_text())
        entries = doc["entries"]
    except (ValueError, KeyError, TypeError) as e:
        raise SystemExit(f"bftlint: malformed baseline {path}: {e!r}")
    out: dict[str, dict] = {}
    for ent in entries:
        fp = ent.get("fingerprint")
        reason = (ent.get("reason") or "").strip()
        if not fp or not reason:
            raise SystemExit(
                f"bftlint: baseline entry missing fingerprint/reason: "
                f"{json.dumps(ent)[:200]}")
        out[fp] = ent
    return out


def _fingerprint(rule: str, rel: str, scope: str, line_text: str,
                 seen: dict[str, int]) -> str:
    """Stable across line drift: hash of rule|path|scope|normalized
    source line, with an occurrence counter for identical lines in the
    same scope."""
    norm = " ".join(line_text.split())
    base = f"{rule}|{rel}|{scope}|{norm}"
    n = seen.get(base, 0)
    seen[base] = n + 1
    if n:
        base += f"|#{n}"
    return hashlib.sha1(base.encode()).hexdigest()[:16]


# ------------------------------------------------------------------- runner

def iter_py_files(targets: list[Path]):
    for t in targets:
        if t.is_file() and t.suffix == ".py":
            yield t
        elif t.is_dir():
            for p in sorted(t.rglob("*.py")):
                if "__pycache__" not in p.parts:
                    yield p


def run_paths(targets: list[Path], root: Path,
              rule_ids: set[str] | None = None) -> list[Finding]:
    """All findings (suppressed ones dropped, baseline NOT applied)."""
    from . import rules as rules_mod
    active = [r for r in rules_mod.ALL_RULES
              if rule_ids is None or r.id in rule_ids]
    findings: list[Finding] = []
    for path in iter_py_files(targets):
        try:
            rel = path.resolve().relative_to(root).as_posix()
        except ValueError:
            rel = path.name
        in_scope = [r for r in active if r.applies(rel)]
        try:
            source = path.read_text(encoding="utf-8")
        except OSError as e:
            findings.append(Finding(
                rule=PARSE_ERROR, severity="high", path=rel, line=0,
                col=0, message=f"unreadable: {e!r}"))
            continue
        sup = Suppressions(source.splitlines(), rel)
        findings.extend(sup.bad)
        if not in_scope:
            continue
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            findings.append(Finding(
                rule=PARSE_ERROR, severity="high", path=rel,
                line=e.lineno or 0, col=e.offset or 0,
                message=f"syntax error: {e.msg}"))
            continue
        ctx = FileContext(rel, source, tree)
        seen: dict[str, int] = {}
        file_findings: list[Finding] = []
        for rule in in_scope:
            for f in rule.check(ctx):
                # suppression honored anywhere across the flagged
                # node's (expression-sized) line span
                node_end = max(f.line, getattr(f, "_end_line", f.line))
                if sup.covers(f.rule, *range(f.line, node_end + 1)):
                    continue
                f.snippet = f.snippet or ctx.line_text(f.line).strip()[:160]
                file_findings.append(f)
        # deterministic order, then fingerprint with occurrence counters
        file_findings.sort(key=lambda f: (f.line, f.col, f.rule))
        for f in file_findings:
            f.fingerprint = _fingerprint(
                f.rule, f.path, f.scope, ctx.line_text(f.line), seen)
        findings.extend(file_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def apply_baseline(findings: list[Finding],
                   baseline: dict[str, dict]) -> list[str]:
    """Mark baselined findings in place; return stale fingerprints
    (baseline entries whose finding no longer exists — candidates for
    pruning, reported but never fatal)."""
    live = set()
    for f in findings:
        ent = baseline.get(f.fingerprint)
        # engine pseudo-rules can never be baselined away
        if ent is not None and f.rule not in (BAD_SUPPRESSION, PARSE_ERROR):
            f.baselined = True
            f.baseline_reason = ent.get("reason", "")
            live.add(f.fingerprint)
    return sorted(set(baseline) - live)


# ---------------------------------------------------------------------- CLI

def _write_json(path: str, findings: list[Finding], stale: list[str],
                rule_ids: list[str]) -> None:
    doc = {
        "tool": "bftlint",
        "version": 1,
        "rules": rule_ids,
        "summary": {
            "total": len(findings),
            "new": sum(1 for f in findings if not f.baselined),
            "baselined": sum(1 for f in findings if f.baselined),
            "stale_baseline_entries": len(stale),
        },
        "findings": [f.to_dict() for f in findings],
        "stale_baseline_fingerprints": stale,
    }
    raw = json.dumps(doc, indent=1, sort_keys=True) + "\n"
    if path == "-":
        sys.stdout.write(raw)
    else:
        Path(path).write_text(raw)


def _merge_baseline(path: Path, findings: list[Finding], reason: str,
                    prune_stale: bool) -> int:
    baseline = load_baseline(path)
    if prune_stale:
        live = {f.fingerprint for f in findings}
        baseline = {fp: e for fp, e in baseline.items() if fp in live}
    added = 0
    for f in findings:
        if f.baselined or f.rule in (BAD_SUPPRESSION, PARSE_ERROR):
            continue
        baseline[f.fingerprint] = {
            "fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
            "line": f.line, "scope": f.scope, "snippet": f.snippet,
            "reason": reason,
        }
        added += 1
    doc = {"version": 1,
           "entries": sorted(baseline.values(),
                             key=lambda e: (e.get("path", ""),
                                            e.get("rule", ""),
                                            e.get("line", 0)))}
    path.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    return added


def main(argv: list[str] | None = None) -> int:
    from . import rules as rules_mod
    ap = argparse.ArgumentParser(
        prog="python -m analysis",
        description="bftlint: project-native AST rules for cometbft_tpu")
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to scan (default: {DEFAULT_TARGETS}"
                         " under the repo root)")
    ap.add_argument("--rules", help="comma-separated rule ids to run")
    ap.add_argument("--root", type=Path, default=REPO_ROOT,
                    help="tree root rule scopes are resolved against")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding as new")
    ap.add_argument("--json", dest="json_out", metavar="PATH",
                    help="write the machine-readable report ('-' = stdout)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="merge current NEW findings into the baseline "
                         "(requires --reason)")
    ap.add_argument("--prune-stale", action="store_true",
                    help="with --write-baseline: drop entries whose "
                         "finding no longer exists")
    ap.add_argument("--reason", default="",
                    help="triage reason stored with --write-baseline")
    ap.add_argument("--list-rules", action="store_true")
    ns = ap.parse_args(argv)

    known = {r.id: r for r in rules_mod.ALL_RULES}
    if ns.list_rules:
        for r in rules_mod.ALL_RULES:
            print(f"{r.id}  [{r.severity:6s}]  {r.title}")
            print(f"        scope: {', '.join(r.scopes)}")
        return 0

    rule_ids: set[str] | None = None
    if ns.rules:
        rule_ids = {r.strip().upper() for r in ns.rules.split(",")
                    if r.strip()}
        unknown = rule_ids - set(known)
        if unknown:
            print(f"bftlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    root = ns.root.resolve()
    targets = [Path(p) for p in ns.paths] if ns.paths else \
        [root / t for t in DEFAULT_TARGETS]
    missing = [t for t in targets if not t.exists()]
    if missing:
        print(f"bftlint: no such path: {missing[0]}", file=sys.stderr)
        return 2

    findings = run_paths(targets, root, rule_ids)

    if ns.write_baseline:
        if not ns.reason.strip():
            print("bftlint: --write-baseline requires --reason",
                  file=sys.stderr)
            return 2
        if ns.prune_stale and (rule_ids is not None or ns.paths):
            print("bftlint: --prune-stale needs a full default run "
                  "(--rules/path filters would prune live entries the "
                  "filtered scan can't see)", file=sys.stderr)
            return 2
        apply_baseline(findings, load_baseline(ns.baseline))
        n = _merge_baseline(ns.baseline, findings, ns.reason.strip(),
                            ns.prune_stale)
        print(f"bftlint: baselined {n} finding(s) -> {ns.baseline}")
        return 0

    baseline = {} if ns.no_baseline else load_baseline(ns.baseline)
    if rule_ids is not None:
        # a filtered run can only observe its own rules' findings —
        # other rules' entries are out of scope, not stale
        baseline = {fp: e for fp, e in baseline.items()
                    if e.get("rule") in rule_ids}
    if ns.paths:
        # same for a partial-tree scan: entries outside the scanned
        # paths are invisible here, not stale
        scanned = []
        for t in targets:
            try:
                scanned.append(t.resolve().relative_to(root).as_posix())
            except ValueError:
                pass
        baseline = {fp: e for fp, e in baseline.items()
                    if any(e.get("path", "") == s or
                           e.get("path", "").startswith(s.rstrip("/") + "/")
                           for s in scanned)}
    stale = apply_baseline(findings, baseline)

    ran = sorted(rule_ids) if rule_ids else [r.id for r in
                                            rules_mod.ALL_RULES]
    if ns.json_out:
        _write_json(ns.json_out, findings, stale, ran)

    new = [f for f in findings if not f.baselined]
    if ns.json_out != "-":              # '-' means the report IS stdout
        for f in new:
            print(f"{f.location()}: {f.rule} [{f.severity}] {f.message}")
            if f.snippet:
                print(f"    {f.snippet}")
        n_base = len(findings) - len(new)
        tail = f"{len(new)} new finding(s), {n_base} baselined"
        if stale:
            tail += (f", {len(stale)} stale baseline entr"
                     f"{'y' if len(stale) == 1 else 'ies'} (run "
                     "--write-baseline --prune-stale --reason '...' to "
                     "drop)")
        print(f"bftlint: {tail}")
    return 1 if new else 0
