"""The six bftlint rules — each encodes an invariant this repo already
paid for in review cycles (the war stories live in
docs/explanation/static-analysis.md).

A rule is scope + a ``check(ctx) -> Iterator[Finding]`` over one
:class:`~analysis.engine.FileContext`.  Scopes are repo-relative posix
prefixes so the rules bind to the packages whose discipline they
encode, not to the whole world.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from .engine import Finding, FileContext, attr_chain, resolve_call


def _mk(rule: "Rule", ctx: FileContext, node: ast.AST,
        message: str) -> Finding:
    f = Finding(rule=rule.id, severity=rule.severity, path=ctx.rel,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                message=message, scope=ctx.scope_qualname(node))
    end = getattr(node, "end_lineno", None)
    if end:
        f._end_line = end          # suppression honored on the last line
    return f


class Rule:
    id = "RULE"
    severity = "high"
    title = ""
    scopes: tuple[str, ...] = ()

    def applies(self, rel: str) -> bool:
        return any(rel == s or rel.startswith(s) for s in self.scopes)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


# --------------------------------------------------------------------- CLK001

class ClockSeam(Rule):
    """Real-time reads/sleeps bypassing libs/clock in the clock-managed
    packages.  Scope-aware replacement for the lint.sh grep: resolves
    aliased imports (``from time import monotonic as m``) and catches
    ``loop.time()`` — both invisible to the regex."""

    id = "CLK001"
    severity = "high"
    title = "real-time call bypassing the libs/clock seam"
    scopes = tuple(f"cometbft_tpu/{p}/" for p in (
        "consensus", "p2p", "node", "mempool", "blocksync", "statesync"))

    # COORDINATION clocks only.  time.perf_counter is deliberately NOT
    # banned: it is the repo's duration-METRICS clock (histograms measure
    # real CPU cost even under the virtual clock — the PR 5 flight-
    # recorder discipline), while monotonic/time/sleep order events and
    # so must virtualize.
    BANNED = {
        "time.monotonic", "time.monotonic_ns", "time.time", "time.time_ns",
        "asyncio.sleep",
    }
    SEAM = {"monotonic": "clock.monotonic()", "monotonic_ns":
            "clock.monotonic()", "time": "clock.walltime()", "time_ns":
            "clock.walltime_ns()", "sleep": "clock.sleep()"}

    def _seam_for(self, dotted: str) -> str:
        return self.SEAM.get(dotted.rsplit(".", 1)[-1], "libs/clock")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # the import form itself: catches the function being passed
        # around as a value, which call-site resolution can't see
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.level == 0 and \
                    node.module in ("time", "asyncio"):
                for a in node.names:
                    dotted = f"{node.module}.{a.name}"
                    if dotted in self.BANNED:
                        yield _mk(self, ctx, node,
                                  f"imports {dotted} directly — route "
                                  f"through {self._seam_for(dotted)}")
            elif isinstance(node, ast.Call):
                dotted = resolve_call(node.func, ctx.imports)
                if dotted in self.BANNED:
                    yield _mk(self, ctx, node,
                              f"{dotted}() bypasses the clock seam — use "
                              f"{self._seam_for(dotted)}")
                    continue
                # loop.time(): an event-loop clock read is a real-time
                # read unless the loop IS the virtual driver
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "time" and dotted is None:
                    chain = attr_chain(node.func.value)
                    is_loop_call = (
                        isinstance(node.func.value, ast.Call) and
                        resolve_call(node.func.value.func, ctx.imports)
                        in ("asyncio.get_event_loop",
                            "asyncio.get_running_loop"))
                    if is_loop_call or (chain is not None and
                                        chain.split(".")[-1].lower()
                                        .endswith("loop")):
                        yield _mk(self, ctx, node,
                                  "loop.time() reads the event-loop "
                                  "clock directly — use clock.monotonic()")


# --------------------------------------------------------------------- LCK001

class LockDiscipline(Rule):
    """The PR 14 cancellation-wedge class: a manual ``.acquire()`` whose
    release is not structurally guaranteed (try/finally or the
    with-statement), and ``await`` while holding a SYNCHRONOUS lock
    (blocks the event loop until the awaited thing completes — a
    single-threaded deadlock waiting to happen)."""

    id = "LCK001"
    severity = "high"
    title = "lock acquire without guaranteed release / await under sync lock"
    scopes = ("cometbft_tpu/mempool/", "cometbft_tpu/p2p/",
              "cometbft_tpu/crypto/")

    # context-manager/lock-wrapper implementations acquire here and
    # release in their paired exit — the pattern the rule steers TO
    _CM_FUNCS = {"__aenter__", "__enter__", "__aexit__", "__exit__",
                 "acquire", "_acquire", "release", "_release", "lock",
                 "unlock"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire":
                yield from self._check_acquire(ctx, node)
            elif isinstance(node, ast.With):
                yield from self._check_sync_with(ctx, node)

    # ------------------------------------------------- acquire/finally

    def _check_acquire(self, ctx: FileContext,
                       call: ast.Call) -> Iterator[Finding]:
        fn = ctx.enclosing_function(call)
        if fn is not None and getattr(fn, "name", "") in self._CM_FUNCS:
            return
        owner = attr_chain(call.func.value)
        stmt = ctx.enclosing_stmt(call)
        if stmt is None or owner is None:
            return
        # non-blocking probe (acquire(blocking=False)) manages failure
        # inline; the wedge class is the blocking form
        for kw in call.keywords:
            if kw.arg == "blocking" and \
                    isinstance(kw.value, ast.Constant) and \
                    kw.value.value is False:
                return
        if self._released_in_finally(ctx, stmt, owner):
            return
        yield _mk(self, ctx, call,
                  f"{owner}.acquire() without a try/finally release — "
                  "cancellation between acquire and release wedges every "
                  "later waiter (use 'async with' or release in finally)")

    def _released_in_finally(self, ctx: FileContext, stmt: ast.stmt,
                             owner: str) -> bool:
        # (a) acquire inside a try whose finally releases the same owner
        for anc in ctx.ancestors(stmt):
            if isinstance(anc, ast.Try) and \
                    self._finally_releases(anc, owner):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
        # (b) the canonical form: acquire, then IMMEDIATELY a
        # try/finally releasing it
        parent = ctx.parent(stmt)
        for field in ("body", "orelse", "finalbody", "handlers"):
            block = getattr(parent, field, None)
            if isinstance(block, list) and stmt in block:
                i = block.index(stmt)
                if i + 1 < len(block) and \
                        isinstance(block[i + 1], ast.Try) and \
                        self._finally_releases(block[i + 1], owner):
                    return True
        return False

    @staticmethod
    def _finally_releases(try_node: ast.Try, owner: str) -> bool:
        for node in ast.walk(ast.Module(body=try_node.finalbody,
                                        type_ignores=[])):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "release" and \
                    attr_chain(node.func.value) == owner:
                return True
        return False

    # ---------------------------------------------- await under sync with

    def _check_sync_with(self, ctx: FileContext,
                         node: ast.With) -> Iterator[Finding]:
        if not any(self._lockish(item.context_expr)
                   for item in node.items):
            return
        holder_fn = ctx.enclosing_function(node)
        for inner in ast.walk(node):
            if isinstance(inner, ast.Await) and \
                    ctx.enclosing_function(inner) is holder_fn:
                yield _mk(self, ctx, inner,
                          "await while holding a synchronous lock — the "
                          "held lock blocks every thread (and this "
                          "coroutine's loop) until the await completes")
                return  # one finding per with-block is enough signal

    # word-ish boundaries: a bare substring test would match 'block',
    # which in this codebase names half the world
    _LOCK_NAME = re.compile(r"(^|_)(r|w)?(lock|mutex|mu)(_|$)")

    @classmethod
    def _lockish(cls, expr: ast.expr) -> bool:
        chain = attr_chain(expr.func if isinstance(expr, ast.Call)
                           else expr)
        if chain is None:
            return False
        leaf = chain.split(".")[-1].lower().strip("_")
        return cls._LOCK_NAME.search(leaf) is not None


# --------------------------------------------------------------------- TSK001

class TaskRetention(Rule):
    """The PR 7 'Task was destroyed but it is pending' class: the event
    loop holds only weak refs to tasks, so a spawn whose result is
    dropped can be garbage-collected mid-flight and its exception is
    never retrieved.  libs/aio.spawn is the blessed fire-and-forget."""

    id = "TSK001"
    severity = "high"
    title = "asyncio task spawned without retention"
    scopes = ("cometbft_tpu/",)

    CREATORS = {"asyncio.create_task", "asyncio.ensure_future"}
    _CREATE_ATTRS = {"create_task", "ensure_future"}

    def _is_creator(self, node: ast.Call, ctx: FileContext) -> bool:
        dotted = resolve_call(node.func, ctx.imports)
        if dotted in self.CREATORS:
            return True
        # loop.create_task(...) / self._loop.create_task(...)
        return (dotted is None and
                isinstance(node.func, ast.Attribute) and
                node.func.attr in self._CREATE_ATTRS)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    self._is_creator(node, ctx)):
                continue
            stmt = ctx.enclosing_stmt(node)
            if isinstance(stmt, ast.Expr) and stmt.value is node:
                yield _mk(self, ctx, node,
                          "task result discarded — the loop keeps only a "
                          "weak ref; use libs/aio.spawn (or retain + "
                          "add_done_callback)")
            elif isinstance(stmt, ast.Assign) and stmt.value is node and \
                    len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                if name == "_" or not self._used_later(ctx, stmt, name):
                    yield _mk(self, ctx, node,
                              f"task bound to '{name}' but never used — "
                              "the reference dies with the scope; use "
                              "libs/aio.spawn or retain it")

    @staticmethod
    def _used_later(ctx: FileContext, assign: ast.stmt,
                    name: str) -> bool:
        scope = ctx.enclosing_function(assign) or ctx.tree
        for node in ast.walk(scope):
            if isinstance(node, ast.Name) and node.id == name and \
                    isinstance(node.ctx, ast.Load):
                return True
        return False


# --------------------------------------------------------------------- BLK001

class BlockingInAsync(Rule):
    """Event-loop stalls in the serving paths: the thread-encode
    discipline PRs 9/12 kept re-fixing (multi-MB json.dumps freezes
    /status for every client), plus sleeps, sync file IO, and hashing
    loops inside ``async def``."""

    id = "BLK001"
    severity = "medium"
    title = "blocking call on the event loop"
    scopes = ("cometbft_tpu/rpc/", "cometbft_tpu/p2p/",
              "cometbft_tpu/consensus/")

    SLEEPS = {"time.sleep"}
    CODECS = {"json.dumps", "json.loads", "json.dump", "json.load"}
    HASHES = ("hashlib.",)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    ctx.in_async_def(node)):
                continue
            dotted = resolve_call(node.func, ctx.imports)
            if dotted is None:
                continue
            if dotted in self.SLEEPS:
                yield _mk(self, ctx, node,
                          f"{dotted}() blocks the event loop — "
                          "clock.sleep() (or to_thread for sync work)")
            elif dotted in self.CODECS:
                yield _mk(self, ctx, node,
                          f"{dotted}() on the event loop — response-sized "
                          "payloads freeze every connection; thread-encode "
                          "via asyncio.to_thread (suppress with the "
                          "payload-size argument if provably tiny)")
            elif dotted == "open":
                yield _mk(self, ctx, node,
                          "sync file IO inside async def — use "
                          "asyncio.to_thread for the read/write")
            elif dotted.startswith(self.HASHES) and \
                    self._in_loop(ctx, node):
                yield _mk(self, ctx, node,
                          f"{dotted}() in a loop inside async def — "
                          "hashing loops starve the loop; batch on a "
                          "worker thread")

    @staticmethod
    def _in_loop(ctx: FileContext, node: ast.AST) -> bool:
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
        return False


# --------------------------------------------------------------------- EXC001

class FatalIoSwallow(Rule):
    """The fsyncgate discipline (PRs 8/10): in the storage-critical
    packages a broad ``except Exception/OSError`` that neither re-raises
    nor routes through the fatal-IO machinery can swallow EIO/ENOSPC and
    keep consensus running on a store that silently stopped persisting."""

    id = "EXC001"
    severity = "high"
    title = "broad except swallows fatal IO errors"
    scopes = ("cometbft_tpu/storage/", "cometbft_tpu/privval/",
              "cometbft_tpu/consensus/wal.py")

    BROAD = {"Exception", "BaseException", "OSError", "IOError"}
    # the blessed escape hatches — routing or classifying the failure
    ROUTERS = {"_io_failed", "_is_fatal_io_error"}

    def _broad_names(self, type_node: ast.expr | None,
                     imports: dict[str, str]) -> list[str]:
        if type_node is None:
            return ["bare except"]
        exprs = type_node.elts if isinstance(type_node, ast.Tuple) \
            else [type_node]
        out = []
        for e in exprs:
            chain = attr_chain(e)
            if chain is not None and chain.split(".")[-1] in self.BROAD:
                out.append(chain)
        return out

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = self._broad_names(node.type, ctx.imports)
            if not broad:
                continue
            if self._body_routes(node):
                continue
            f = _mk(self, ctx, node,
                    f"except {', '.join(broad)} neither re-raises nor "
                    "routes through the fatal-IO classifier "
                    "(_io_failed/_is_fatal_io_error) — an EIO here is "
                    "silently swallowed")
            # suppression is honored anywhere on the (possibly
            # multi-line) except CLAUSE, not deep in the handler body
            if node.type is not None and node.type.end_lineno:
                f._end_line = node.type.end_lineno
            else:
                f._end_line = node.lineno
            yield f

    def _body_routes(self, handler: ast.ExceptHandler) -> bool:
        return any(self._routes(n) for n in handler.body)

    def _routes(self, node: ast.AST) -> bool:
        # recursion that actually PRUNES nested function scopes —
        # ast.walk can't: a `raise` inside a callback defined in the
        # handler body runs later (if ever), it does not route THIS
        # exception
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return False
        if isinstance(node, ast.Raise):
            return True
        chain = None
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
        elif isinstance(node, ast.Name):
            chain = node.id
        if chain is not None and chain.split(".")[-1] in self.ROUTERS:
            return True
        return any(self._routes(c) for c in ast.iter_child_nodes(node))


# --------------------------------------------------------------------- DET001

class ReplayDeterminism(Rule):
    """The PR 13 replay-identity discipline: the scenario lab promises
    ``run_scenario(s) == run_scenario(s)`` byte-for-byte, so sim/ and
    the consensus gossip/vote paths must draw randomness only from
    seeded ``random.Random`` instances (keyed like libs/failures) and
    time only from the clock seam — a global-RNG draw's sequence is a
    function of coroutine interleaving, not of the seed."""

    id = "DET001"
    severity = "medium"
    title = "unseeded randomness / real-time value on a replay path"
    scopes = ("cometbft_tpu/sim/", "cometbft_tpu/consensus/")

    GLOBAL_DRAWS = {
        "random.random", "random.randint", "random.randrange",
        "random.choice", "random.choices", "random.shuffle",
        "random.sample", "random.uniform", "random.gauss",
        "random.getrandbits", "random.triangular", "random.expovariate",
        "random.normalvariate", "random.betavariate", "random.vonmisesvariate",
    }
    ENTROPY = {"os.urandom", "uuid.uuid4", "secrets.token_bytes",
               "secrets.token_hex", "secrets.token_urlsafe",
               "secrets.randbits", "secrets.choice", "secrets.randbelow"}
    # real-time reads in sim/ (consensus/ is already CLK001 territory)
    TIME = {"time.time", "time.time_ns", "time.monotonic",
            "time.monotonic_ns", "time.perf_counter"}
    # the virtual driver itself must touch the real loop/clock
    _EXEMPT_FILES = ("cometbft_tpu/sim/vtime.py",)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        in_sim = ctx.rel.startswith("cometbft_tpu/sim/")
        exempt_time = ctx.rel in self._EXEMPT_FILES
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = resolve_call(node.func, ctx.imports)
            if dotted is None:
                continue
            if dotted in self.GLOBAL_DRAWS:
                yield _mk(self, ctx, node,
                          f"{dotted}() draws from the GLOBAL RNG — the "
                          "sequence depends on scheduling interleaving, "
                          "breaking replay identity; draw from a seeded "
                          "random.Random keyed by (seed, site)")
            elif dotted in self.ENTROPY:
                yield _mk(self, ctx, node,
                          f"{dotted}() is OS entropy — unreplayable; "
                          "derive from the scenario seed")
            elif in_sim and not exempt_time and dotted in self.TIME:
                yield _mk(self, ctx, node,
                          f"{dotted}() reads real time on a replay path "
                          "— route through libs/clock")
        # BitArray.pick_random() with no rng falls back to the module
        # RNG — same class, hidden one call away (libs/bits.py)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "pick_random" and \
                    not node.args and not node.keywords:
                yield _mk(self, ctx, node,
                          "pick_random() without an rng draws from the "
                          "GLOBAL RNG — pass a seeded random.Random")


ALL_RULES: tuple[Rule, ...] = (
    ClockSeam(), LockDiscipline(), TaskRetention(),
    BlockingInAsync(), FatalIoSwallow(), ReplayDeterminism(),
)
