#!/usr/bin/env python
"""CI height-timeline smoke: boot a tracing-enabled validator plus a
TCP-connected observer, commit 3 heights, then fetch the waterfall
projection the way an operator would —

- ``GET /consensus_timeline?n=K`` must answer 200 with per-height
  waterfalls for every committed height,
- each complete waterfall's phases must be a prefix-ordered subset of
  the canonical taxonomy (propose -> gossip -> prevote -> precommit ->
  commit) with contiguous, non-negative segments,
- the residual buckets (gossip_wait/verify/app/wal/idle) must sum to
  the measured commit latency — never more,
- ``height=H`` must select exactly height H,
- ``/dump_trace?sub=consensus&height=H`` must serve only records
  stamped with that height (the filter discipline ``libs/timeline``
  keys on).

Exit 0 on success, 1 with a reason on any failure.  Used by the lint
workflow's smoke job (`.github/workflows/lint.yml`); runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_timeline.py
"""

import asyncio
import json
import os
import sys
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TARGET_HEIGHT = 3


def fetch(url: str) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def check_waterfall(wf: dict, phase_order: list) -> str | None:
    """Return a failure reason, or None if the waterfall is sound."""
    phases = [p["phase"] for p in wf["phases"]]
    # present phases must appear in taxonomy order (absent marks — a
    # catch-up commit, an evicted record — drop phases, never reorder)
    idx = [phase_order.index(p) for p in phases if p in phase_order]
    if len(idx) != len(phases) or idx != sorted(idx):
        return f"phases out of order: {phases}"
    if "propose" not in phases:
        return f"missing propose phase: {phases}"
    cursor = 0.0
    for p in wf["phases"]:
        if p["dur_s"] < 0 or p["start_s"] < cursor - 1e-5:
            return f"non-contiguous segment {p} (cursor {cursor})"
        cursor = p["start_s"] + p["dur_s"]
    if cursor > wf["total_s"] + 1e-5:
        return f"phases overrun total: {cursor} > {wf['total_s']}"
    bsum = sum(wf["buckets"].values())
    if bsum > wf["total_s"] + 1e-5:
        return f"buckets exceed commit latency: {bsum} > {wf['total_s']}"
    if any(v < 0 for v in wf["buckets"].values()):
        return f"negative bucket: {wf['buckets']}"
    return None


async def main() -> int:
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    def _cfg() -> Config:
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.instrumentation.tracing = True
        return cfg

    pv = MockPV.from_secret(b"smoke-timeline")
    doc = GenesisDoc(chain_id="smoke-tl-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])
    node = await Node.create(doc, KVStoreApplication(), priv_validator=pv,
                             config=_cfg(), name="tl0")
    await node.start()
    cfg2 = _cfg()
    cfg2.rpc.laddr = ""
    observer = await Node.create(doc, KVStoreApplication(), config=cfg2,
                                 name="tl1")
    await observer.start()
    loop = asyncio.get_running_loop()
    try:
        await observer.dial_peer(node.listen_addr, persistent=False)
        for _ in range(600):
            if node.block_store.height() >= TARGET_HEIGHT:
                break
            await asyncio.sleep(0.05)
        else:
            print(f"FAIL: never reached height {TARGET_HEIGHT}",
                  file=sys.stderr)
            return 1
        host, port = node.rpc_addr
        base = f"http://{host}:{port}"

        status, body = await loop.run_in_executor(
            None, fetch, base + "/consensus_timeline?n=10")
        if status != 200:
            print(f"FAIL: /consensus_timeline -> HTTP {status}",
                  file=sys.stderr)
            return 1
        result = json.loads(body).get("result") or {}
        if not result.get("enabled"):
            print("FAIL: /consensus_timeline reports tracing disabled",
                  file=sys.stderr)
            return 1
        order = result.get("phases") or []
        if order[:2] != ["propose", "gossip"]:
            print(f"FAIL: bad phase taxonomy {order}", file=sys.stderr)
            return 1
        wfs = result.get("waterfalls") or []
        done = [w for w in wfs if w["complete"]]
        if len(done) < TARGET_HEIGHT:
            print(f"FAIL: {len(done)} complete waterfalls, want "
                  f">= {TARGET_HEIGHT} (of {len(wfs)})", file=sys.stderr)
            return 1
        for wf in done:
            reason = check_waterfall(wf, order)
            if reason:
                print(f"FAIL: h{wf['height']}: {reason}", file=sys.stderr)
                return 1
        # the steady-state heights saw the full vote ladder
        full = [w for w in done
                if [p["phase"] for p in w["phases"]] == order]
        if not full:
            print("FAIL: no waterfall shows all five phases",
                  file=sys.stderr)
            return 1

        status, body = await loop.run_in_executor(
            None, fetch, base + "/consensus_timeline?height=2")
        one = (json.loads(body).get("result") or {}).get("waterfalls") or []
        if {w["height"] for w in one} != {2}:
            print(f"FAIL: height=2 filter returned "
                  f"{[w['height'] for w in one]}", file=sys.stderr)
            return 1

        status, body = await loop.run_in_executor(
            None, fetch, base + "/dump_trace?sub=consensus&height=2&limit=500")
        recs = (json.loads(body).get("result") or {}).get("records") or []
        if not recs:
            print("FAIL: filtered /dump_trace returned nothing",
                  file=sys.stderr)
            return 1
        for r in recs:
            if r["sub"] != "consensus":
                print(f"FAIL: sub filter leaked {r['sub']}", file=sys.stderr)
                return 1
            a = r["attrs"]
            h_ok = a.get("height") == 2 or \
                (a.get("h_lo", 99) <= 2 <= a.get("h_hi", -1))
            if not h_ok:
                print(f"FAIL: height filter leaked {a}", file=sys.stderr)
                return 1

        print(f"smoke ok: height={node.block_store.height()} "
              f"waterfalls={len(wfs)} complete={len(done)} "
              f"full_phase={len(full)} "
              f"p50_total={sorted(w['total_s'] for w in done)[len(done)//2]}s")
        return 0
    finally:
        await observer.stop()
        await node.stop()


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
