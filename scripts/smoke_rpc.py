#!/usr/bin/env python
"""CI observability smoke: boot a tracing-enabled validator plus one
connected observer node, then hit the RPC listener the way an operator's
tooling would —

- ``GET /metrics`` must answer 200 with parseable Prometheus text
  exposition (every line a comment, a blank, or ``name{labels} value``)
  — including the new peer-labeled p2p series the telemetry sampler
  writes,
- ``GET /dump_trace?limit=N`` must answer 200 with a JSON-RPC envelope
  whose result carries flight-recorder records (consensus step spans at
  minimum, since the node committed a block),
- ``GET /status`` must carry the enriched ``consensus_info`` block,
- ``GET /net_info`` must carry per-peer per-channel bytes, queue depth,
  flowrate and RTT fields for the connected peer,
- ``GET /dump_incidents`` must answer 200 with a well-formed (here:
  empty — nothing stalled) incident list.

Exit 0 on success, 1 with a reason on any failure.  Used by the lint
workflow's smoke job (`.github/workflows/lint.yml`); runnable locally:

    JAX_PLATFORMS=cpu python scripts/smoke_rpc.py
"""

import asyncio
import json
import os
import re
import sys
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})?$")


def check_exposition(text: str) -> None:
    """Raise on anything the Prometheus text parser would choke on."""
    for ln, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            if not (line.startswith("# HELP ") or line.startswith("# TYPE ")):
                raise ValueError(f"line {ln}: bad comment {line!r}")
            if "\n" in line or line != line.rstrip("\r"):
                raise ValueError(f"line {ln}: unescaped control char")
            continue
        name, _, value = line.rpartition(" ")
        if not name or not _NAME.match(name):
            raise ValueError(f"line {ln}: bad series name {line!r}")
        if value not in ("NaN", "+Inf", "-Inf"):
            float(value)        # raises on garbage


def fetch(url: str) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        # non-2xx raises in urllib; surface it as a status so the
        # callers' FAIL diagnostics actually run
        return e.code, e.read()


async def main() -> int:
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    def _cfg() -> Config:
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.instrumentation.tracing = True
        cfg.p2p.telemetry_flush_interval_s = 0.25
        return cfg

    pv = MockPV.from_secret(b"smoke-node")
    doc = GenesisDoc(chain_id="smoke-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])
    node = await Node.create(doc, KVStoreApplication(), priv_validator=pv,
                             config=_cfg(), name="smoke")
    await node.start()
    # a second, non-validator node so /net_info has a live peer to report
    cfg2 = _cfg()
    cfg2.rpc.laddr = ""
    observer = await Node.create(doc, KVStoreApplication(), config=cfg2,
                                 name="smoke-obs")
    await observer.start()
    loop = asyncio.get_running_loop()
    try:
        await observer.dial_peer(node.listen_addr, persistent=False)
        # a single validator commits on its own; wait for height >= 1
        for _ in range(600):
            if node.block_store.height() >= 1:
                break
            await asyncio.sleep(0.05)
        else:
            print("FAIL: node never committed a block", file=sys.stderr)
            return 1
        host, port = node.rpc_addr
        base = f"http://{host}:{port}"

        status, body = await loop.run_in_executor(
            None, fetch, base + "/metrics")
        if status != 200:
            print(f"FAIL: /metrics -> HTTP {status}", file=sys.stderr)
            return 1
        try:
            check_exposition(body.decode())
        except ValueError as e:
            print(f"FAIL: /metrics exposition unparseable: {e}",
                  file=sys.stderr)
            return 1
        if b"consensus_height" not in body:
            print("FAIL: /metrics missing consensus_height", file=sys.stderr)
            return 1

        status, body = await loop.run_in_executor(
            None, fetch, base + "/dump_trace?limit=500")
        if status != 200:
            print(f"FAIL: /dump_trace -> HTTP {status}", file=sys.stderr)
            return 1
        env = json.loads(body)
        result = env.get("result") or {}
        if not result.get("enabled"):
            print("FAIL: /dump_trace reports tracing disabled",
                  file=sys.stderr)
            return 1
        recs = result.get("records") or []
        steps = [r for r in recs if r["sub"] == "consensus"
                 and r["name"] == "step"]
        if not steps:
            print(f"FAIL: no consensus step spans in {len(recs)} records",
                  file=sys.stderr)
            return 1

        status, body = await loop.run_in_executor(
            None, fetch, base + "/status")
        ci = (json.loads(body).get("result") or {}).get("consensus_info")
        if not ci or "step_age_s" not in ci:
            print("FAIL: /status missing consensus_info", file=sys.stderr)
            return 1

        # ---- /net_info: per-peer telemetry for the connected observer
        status, body = await loop.run_in_executor(
            None, fetch, base + "/net_info")
        if status != 200:
            print(f"FAIL: /net_info -> HTTP {status}", file=sys.stderr)
            return 1
        ni = json.loads(body).get("result") or {}
        if ni.get("n_peers") != 1 or len(ni.get("peers") or []) != 1:
            print(f"FAIL: /net_info reports {ni.get('n_peers')} peers, "
                  "expected the observer", file=sys.stderr)
            return 1
        peer = ni["peers"][0]
        conn = peer.get("connection_status") or {}
        for field in ("send_rate", "recv_rate", "last_rtt_s",
                      "send_bytes_total", "recv_bytes_total", "channels"):
            if field not in conn:
                print(f"FAIL: /net_info peer missing {field}",
                      file=sys.stderr)
                return 1
        if "gossip" not in peer or "useful_votes" not in peer["gossip"]:
            print("FAIL: /net_info peer missing gossip efficiency",
                  file=sys.stderr)
            return 1
        chans = conn["channels"]
        vote = chans.get("vote")
        if not vote:
            print(f"FAIL: /net_info peer channels lack 'vote': "
                  f"{sorted(chans)}", file=sys.stderr)
            return 1
        for field in ("sent_bytes", "recv_bytes", "sent_msgs",
                      "recv_msgs", "send_queue", "send_queue_capacity",
                      "queue_full_drops"):
            if field not in vote:
                print(f"FAIL: /net_info vote channel missing {field}",
                      file=sys.stderr)
                return 1
        if conn["send_bytes_total"] <= 0:
            print("FAIL: /net_info shows no bytes sent to the observer",
                  file=sys.stderr)
            return 1

        # ---- /dump_incidents: 200 + well-formed (empty) list
        status, body = await loop.run_in_executor(
            None, fetch, base + "/dump_incidents")
        if status != 200:
            print(f"FAIL: /dump_incidents -> HTTP {status}",
                  file=sys.stderr)
            return 1
        inc = json.loads(body).get("result") or {}
        if "incidents" not in inc or not isinstance(inc["incidents"],
                                                    list):
            print(f"FAIL: /dump_incidents malformed: {inc}",
                  file=sys.stderr)
            return 1
        if inc["incidents"]:
            print("FAIL: healthy smoke net reported incidents: "
                  f"{inc['incidents']}", file=sys.stderr)
            return 1

        print(f"smoke ok: height={node.block_store.height()} "
              f"trace_records={len(recs)} step_spans={len(steps)} "
              f"peer_channels={len(chans)}")
        return 0
    finally:
        await observer.stop()
        await node.stop()


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
