"""Sharded-mesh smoke: the r19 SPMD path on 4 emulated CPU devices.

What it checks (the multi-device acceptance bar, scaled to CI):

1. sharded build: under ``XLA_FLAGS=--xla_force_host_platform_device_count=4``
   a mesh plan (``mesh_shape=(4,)``) AOT-compiles its merkle bucket as
   ONE sharded program over the 4-device mesh and serializes it with the
   ``@m4`` key tag and the mesh dims in the bundle header.
2. verdict equivalence: the sharded executable's output must be
   bit-identical to the single-device jit of the same kernel (and to the
   hashlib reference).
3. mesh staleness guard: loading the 4-device bundle under an 8-device
   plan must be refused with status "stale" and a
   ``crypto_compile_bundle_stale_total{reason=mesh}`` tick — a sharded
   executable on the wrong mesh would be WRONG, not just slow.
4. fresh process: a second interpreter (same 4-device emulation) loads
   the bundle and its FIRST sharded dispatch lands warm on the PR 5
   ``crypto_kernel_first_dispatch_seconds`` gauge (< 1s absolute, and a
   fraction of the parent's build time).

The merkle-level kernel keeps the smoke inside a CI minute; the sharded
bundle machinery (mesh plan -> sharded_kernel -> serialize -> mesh
guard -> load -> one dispatch over the mesh) is exactly the path the
verify/RLC buckets take on a TPU host.

Runs on CPU (JAX_PLATFORMS=cpu), ~30 s.  Exit 0 = pass.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# BEFORE any jax import: the whole point is a multi-device mesh on CPU
_FLAGS = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _FLAGS:
    os.environ["XLA_FLAGS"] = (
        _FLAGS + " --xla_force_host_platform_device_count=4").strip()

MESH = 4
LANES = 256
KEY = f"merkle_level:{LANES}@m{MESH}"


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def ok(msg: str) -> None:
    print(f"ok: {msg}", flush=True)


def mesh_plan(nd: int = MESH):
    from cometbft_tpu.crypto import plan as P

    return dataclasses.replace(P.DevicePlan(), warm_kinds=(),
                               warm_merkle=(LANES,), mesh_shape=(nd,))


def expected_root() -> bytes:
    return hashlib.sha256(b"\x01" + b"\x00" * 64).digest()


def child(path: str, t_build: float) -> None:
    """The 'spun-up verify node': fresh process, prewarmed SHARDED bundle."""
    import jax
    import numpy as np

    from cometbft_tpu.crypto import aotbundle
    from cometbft_tpu.libs import metrics

    if len(jax.devices()) < MESH:
        fail(f"child sees {len(jax.devices())} devices, wanted {MESH}")
    info = aotbundle.load(path=path, plan=mesh_plan())
    if info["status"] != "loaded":
        fail(f"child expected a loaded bundle, got {info['status']!r}")
    if info["buckets"].get(KEY) != "warm":
        fail(f"bucket {KEY} not warm in child: {info['buckets']}")
    left = np.zeros((LANES, 8), np.uint32)
    out = np.asarray(aotbundle.timed_call(KEY, left, left))
    got = b"".join(int(w).to_bytes(4, "big") for w in out[0])
    if got != expected_root():
        fail("sharded executable computed a wrong inner-node hash")
    g = metrics.gauge("crypto_kernel_first_dispatch_seconds", "")
    first = g.value(kind="merkle_level", lanes=str(LANES))
    # the r19 acceptance bar: fresh-process first SHARDED dispatch < 1s
    # (vs the multi-second trace+lower+compile a cold process pays), and
    # a fraction of the parent's measured build time
    bar = min(1.0, max(0.25, t_build / 2))
    if not 0 <= first < bar:
        fail(f"first sharded dispatch {first:.3f}s not warm "
             f"(bar {bar:.3f}s, build was {t_build:.3f}s)")
    print(f"CHILD-OK first_dispatch={first * 1e3:.2f}ms "
          f"build_was={t_build:.2f}s", flush=True)


def main() -> None:
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        child(sys.argv[2], float(sys.argv[3]))
        return

    import jax
    import numpy as np

    from cometbft_tpu.crypto import aotbundle
    from cometbft_tpu.libs import metrics
    from cometbft_tpu.ops import sha256 as _sha

    if len(jax.devices()) < MESH:
        fail(f"host emulation gave {len(jax.devices())} devices, "
             f"wanted {MESH} (XLA_FLAGS not honored?)")
    plan = mesh_plan()
    with tempfile.TemporaryDirectory(prefix="smoke-mesh-") as td:
        path = os.path.join(td, "bundle-m4.aot")
        t0 = time.perf_counter()
        info = aotbundle.build(plan=plan, path=path)
        t_build = time.perf_counter() - t0
        if info["status"] != "built":
            fail(f"build status {info['status']!r}")
        if info["buckets"].get(KEY) != "warm":
            fail(f"sharded bucket missing its @m{MESH} key: "
                 f"{info['buckets']}")
        ok(f"sharded bundle built in {t_build:.2f}s "
           f"({os.path.getsize(path)} bytes, key {KEY})")

        # verdict equivalence: sharded == single-device jit, bit for bit
        left = np.zeros((LANES, 8), np.uint32)
        sharded = np.asarray(aotbundle.timed_call(KEY, left, left))
        single = np.asarray(jax.jit(_sha.merkle_inner_level)(left, left))
        if not (sharded == single).all():
            fail("sharded and single-device outputs differ")
        got = b"".join(int(w).to_bytes(4, "big") for w in sharded[0])
        if got != expected_root():
            fail("sharded output does not match the hashlib reference")
        ok("sharded output bit-identical to single-device + hashlib")

        # mesh staleness guard: same bundle_version, different mesh
        wider = mesh_plan(nd=8)
        ctr = metrics.counter("crypto_compile_bundle_stale_total", "")
        before = ctr.value(reason="mesh")
        aotbundle.reset()
        sinfo = aotbundle.load(path=path, plan=wider)
        if sinfo["status"] != "stale":
            fail(f"mesh-mismatched bundle not refused: {sinfo['status']!r}")
        if ctr.value(reason="mesh") != before + 1:
            fail("mesh refusal did not tick "
                 "crypto_compile_bundle_stale_total{reason=mesh}")
        if aotbundle.lookup(KEY) is not None:
            fail("mesh-mismatched bundle leaked an executable")
        ok("4-device bundle refused on an 8-device plan (reason=mesh)")

        # fresh process: first sharded dispatch must be warm
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", path,
             f"{t_build:.4f}"],
            env=env, timeout=120, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        print(proc.stdout, end="", flush=True)
        if proc.returncode != 0 or "CHILD-OK" not in proc.stdout:
            fail(f"child process rc={proc.returncode}")
        ok("fresh-process first SHARDED dispatch served warm")
    print("PASS: sharded-mesh smoke", flush=True)


if __name__ == "__main__":
    main()
