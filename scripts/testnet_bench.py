"""Multi-node throughput artifact (VERDICT r3 missing 2): an N-validator
testnet ON ONE BOX driven with timestamped load, reported the way the
reference's QA method does (tx/s, latency percentiles, blocks/min —
docs/references/qa/CometBFT-QA-v1.md:152-171 + test/loadtime/).

Honesty: the reference's headline (~400 tx/s saturation) comes from a
200-node multi-region DO testnet; this artifact is 4 validators sharing
ONE CPU core with emulated p2p latency — same methodology, not the same
hardware.  The JSON records both.

  python scripts/testnet_bench.py [--nodes 4] [--rate 1000] [--duration 30]
        [--latency-ms 50] [--out docs/bench/r04-testnet.json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_P2P = 29100
BASE_RPC = 29200


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--rate", type=float, default=1000.0)
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--latency-ms", type=float, default=50.0)
    ap.add_argument("--tx-size", type=int, default=256)
    ap.add_argument("--out", default="docs/bench/r04-testnet.json")
    args = ap.parse_args()

    from cometbft_tpu.e2e.gen import HomeSpec, generate_homes

    base = tempfile.mkdtemp(prefix="testnet-bench-")
    chain_id = f"testnet-bench-{os.getpid()}"
    specs = [HomeSpec(name=f"n{i}", p2p_port=BASE_P2P + i,
                      rpc_port=BASE_RPC + i, power=10)
             for i in range(args.nodes)]

    def tweak(spec, cfg):
        from cometbft_tpu.config import MS, ConsensusConfig

        cfg.base.signature_backend = "cpu"
        # QA-representative timeouts scaled for one shared core: long
        # enough that a CheckTx burst cannot starve a proposal round
        # into churn (the stock test config's 80ms propose collapses
        # under saturation load on this box), short enough for useful
        # block cadence
        cfg.consensus = ConsensusConfig(
            timeout_propose=1000 * MS, timeout_propose_delta=500 * MS,
            timeout_prevote=500 * MS, timeout_prevote_delta=250 * MS,
            timeout_precommit=500 * MS, timeout_precommit_delta=250 * MS,
            timeout_commit=500 * MS, peer_gossip_sleep_duration=20 * MS)
        cfg.mempool.size = 20000
        cfg.p2p.emulated_latency_ms = args.latency_ms

    generate_homes(base, specs, chain_id, tweak=tweak)

    procs = []
    ttl = int(args.duration) + 240
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    try:
        for spec in specs:
            lf = open(os.path.join(base, f"{spec.name}.log"), "ab")
            procs.append(subprocess.Popen(
                ["timeout", str(ttl), sys.executable, "-m", "cometbft_tpu",
                 "--home", os.path.join(base, spec.name), "start"],
                stdout=lf, stderr=subprocess.STDOUT, env=env, cwd=REPO))
        result = asyncio.run(_drive(args, specs, chain_id))
        result["nodes"] = args.nodes
        result["emulated_latency_ms"] = args.latency_ms
        result["note"] = (
            f"{args.nodes} validators sharing one CPU core on one box, "
            f"{args.latency_ms}ms emulated p2p latency; QA-method load/"
            "report (loadtime), NOT the reference's 200-node multi-region "
            "testnet hardware")
        out = json.dumps(result)
        print(out, flush=True)
        if args.out:
            with open(os.path.join(REPO, args.out), "w") as f:
                f.write(out + "\n")
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        # keep logs on failure for diagnosis; remove on success
        if "result" in dir():
            shutil.rmtree(base, ignore_errors=True)
        else:
            print(f"[testnet-bench] logs kept under {base}",
                  file=sys.stderr)


async def _drive(args, specs, chain_id) -> dict:
    from cometbft_tpu import loadtime
    from cometbft_tpu.rpc import HTTPClient

    ports = [s.rpc_port for s in specs]
    clis = [HTTPClient("127.0.0.1", p) for p in ports]

    def note(msg):
        print(f"[testnet-bench] {msg}", file=sys.stderr, flush=True)

    note(f"waiting for {len(ports)} nodes + full mesh")
    deadline = time.monotonic() + 120
    while True:
        try:
            sts = [await c.call("status") for c in clis]
            if all(s["node_info"]["network"] == chain_id for s in sts):
                nets = [await c.call("net_info") for c in clis]
                if all(n["n_peers"] >= len(ports) - 1 for n in nets):
                    break
        except Exception:
            pass
        if time.monotonic() > deadline:
            raise RuntimeError("testnet failed to form a full mesh")
        await asyncio.sleep(1.0)

    note("mesh up; waiting for first committed blocks")
    while (await clis[0].call("status"))["sync_info"][
            "latest_block_height"] < 2:
        await asyncio.sleep(0.5)

    h0 = (await clis[0].call("status"))["sync_info"]["latest_block_height"]
    t_load0 = time.time()
    note(f"driving {args.rate} tx/s for {args.duration}s at node 0")
    gen = await loadtime.generate(clis[0], args.rate, args.duration,
                                  tx_size=args.tx_size, connections=6,
                                  batch=8)

    # drain-poll on a cheap signal (tip height + block tx counts would
    # still rescan; num_unconfirmed_txs is O(1)) and run the full
    # chain-scan report ONCE afterwards — re-reporting from genesis every
    # poll is O(blocks^2) RPC load against the node being measured
    note(f"sent {gen['sent']} txs; waiting for drain")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            unc = await clis[0].call("num_unconfirmed_txs")
            if int(unc.get("n_txs", unc.get("total", 0))) == 0:
                break
        except Exception:
            pass
        await asyncio.sleep(1.0)
    load_wall_s = time.time() - t_load0

    rep = await loadtime.report(clis[0], run_id=gen["run_id"],
                                min_height=max(1, h0))
    sts = [await c.call("status") for c in clis]
    heights = [s["sync_info"]["latest_block_height"] for s in sts]
    h1 = max(heights)

    # liveness: every node within a couple of blocks of the max
    assert h1 - min(heights) <= 3, f"node fell behind: {heights}"

    blocks = h1 - h0
    return {
        "metric": f"{len(ports)}-validator testnet throughput "
                  f"({args.tx_size}B txs, kvstore)",
        "value": rep.get("throughput_tx_s") or round(
            rep.get("txs", 0) / max(load_wall_s, 1e-9), 2),
        "unit": "tx/s",
        "vs_baseline": round((rep.get("throughput_tx_s") or 0.0) / 400.0,
                             2),
        "sent": gen["sent"],
        "committed": rep.get("txs", 0),
        "send_errors": gen.get("errors", 0),
        "p50_latency_s": rep.get("p50_s"),
        "p90_latency_s": rep.get("p90_s"),
        "p99_latency_s": rep.get("p99_s"),
        "blocks": blocks,
        "blocks_per_min": round(blocks / max(load_wall_s / 60, 1e-9), 1),
        "heights": heights,
        "backend": "cpu",
    }


if __name__ == "__main__":
    main()
