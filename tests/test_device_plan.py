"""The declarative device plan (crypto/plan.py) and the AOT
compile-bundle cache (crypto/aotbundle.py): bucket math unification,
compile-bucket enumeration, bundle save/load round-trip, and the
staleness guard (a mismatched or corrupt bundle is ignored with a
counter, never a crash or a wrong executable)."""

import dataclasses
import hashlib
import os

import numpy as np
import pytest

from cometbft_tpu.crypto import aotbundle
from cometbft_tpu.crypto import batch as B
from cometbft_tpu.crypto import plan as P

pytestmark = pytest.mark.timeout(120)


@pytest.fixture(autouse=True)
def clean_plan():
    saved = P.active()
    yield
    P.set_plan(saved, push_min_lanes=False)
    aotbundle.reset()


# ------------------------------------------------------------------ plan


def test_plan_defaults_match_legacy_tables():
    plan = P.DevicePlan()
    assert plan.lane_buckets == B._LANE_BUCKETS
    assert plan.table_buckets == B._TABLE_BUCKETS
    assert plan.block_buckets == B._BLOCK_BUCKETS
    assert plan.lane_buckets[-1] == 4096


def test_bucket_math_reads_active_plan():
    assert P.bucket_for_lanes(300) == 1024
    assert P.buckets_for_batch(9000) == (1024, 4096)
    assert P.snap_lane_cap(300) == 256
    P.set_plan(dataclasses.replace(P.active(), lane_buckets=(4, 8)),
               push_min_lanes=False)
    assert P.bucket_for_lanes(300) == 8          # clamped to the new cap
    assert P.snap_lane_cap(300) == 8
    # batch's re-exports follow the plan too
    assert B.bucket_for_lanes(300) == 8


def test_chunk_bucket_rounds_to_mesh():
    assert P.chunk_bucket(100, ()) == 256
    # 4 fake devices: bucket already divides power-of-two meshes
    assert P.chunk_bucket(100, (1, 2, 3, 4)) == 256
    # odd mesh: round up so each chip takes an equal slab
    assert P.chunk_bucket(100, (1, 2, 3)) == 258


def test_mesh_occupancy():
    assert P.mesh_occupancy(0) == 0.0
    assert P.mesh_occupancy(4096) == 1.0
    assert P.mesh_occupancy(2048) == 1.0         # exact bucket
    assert abs(P.mesh_occupancy(3000) - 3000 / 4096) < 1e-9
    # chunked past the cap: 5000 -> 4096 + 1024-bucket remainder
    assert abs(P.mesh_occupancy(5000) - 5000 / (4096 + 1024)) < 1e-9


def test_configure_and_legacy_hooks_are_one_layer():
    B.set_rlc_min_lanes(77)
    assert P.active().rlc_min_lanes == 77
    P.configure(rlc_min_lanes=128)
    assert P.active().rlc_min_lanes == 128
    # min_device_lanes pushes the live class register only when named
    saved = B.TpuBatchVerifier.MIN_DEVICE_LANES
    try:
        P.configure(min_device_lanes=9)
        assert B.TpuBatchVerifier.MIN_DEVICE_LANES == 9
        B.TpuBatchVerifier.MIN_DEVICE_LANES = 3      # direct poke
        P.configure(rlc_min_lanes=50)                # unrelated change
        assert B.TpuBatchVerifier.MIN_DEVICE_LANES == 3   # untouched
    finally:
        B.TpuBatchVerifier.MIN_DEVICE_LANES = saved


def test_enumerate_buckets_and_keys():
    keys = [b.key for b in P.enumerate_buckets()]
    assert "verify:4096x2" in keys and "rlc:256x2" in keys
    assert all(":" in k for k in keys)
    tiny = dataclasses.replace(P.active(), warm_kinds=(),
                               warm_merkle=(64,))
    mk = [b.key for b in P.enumerate_buckets(tiny)]
    assert mk == ["merkle_level:64"]
    only = [b.key for b in P.enumerate_buckets(kinds=("merkle_level",))]
    assert all(k.startswith("merkle_level:") for k in only)


def test_plan_hash_sensitivity():
    h0 = P.plan_hash()
    assert h0 == P.plan_hash()                   # stable
    changed = dataclasses.replace(P.active(), rlc_min_lanes=1)
    assert P.plan_hash(changed) != h0
    changed = dataclasses.replace(P.active(), warm_lanes=(16,))
    assert P.plan_hash(changed) != h0


def test_describe_shape():
    d = P.describe()
    for k in ("hash", "lane_buckets", "table_buckets", "rlc_min_lanes",
              "min_device_lanes", "warm_buckets", "mesh_axis"):
        assert k in d
    assert d["hash"] == P.plan_hash()


# ---------------------------------------------------------------- bundle


def _tiny_plan():
    """A plan whose warm set is one cheap merkle bucket (compiles in
    well under a second on CPU) — the bundle machinery under test is
    kernel-agnostic."""
    return dataclasses.replace(
        P.active(), warm_kinds=(), warm_merkle=(16,))


def _stale_counter():
    from cometbft_tpu.libs import metrics

    return metrics.counter("crypto_compile_bundle_stale_total", "")


def test_bundle_build_save_load_roundtrip(tmp_path):
    plan = _tiny_plan()
    path = str(tmp_path / "bundle.aot")
    info = aotbundle.build(plan=plan, path=path)
    assert info["status"] == "built"
    assert info["buckets"] == {"merkle_level:16": "warm"}
    assert os.path.exists(path)

    # a fresh "process": drop the live table, load from disk
    aotbundle.reset()
    assert aotbundle.lookup("merkle_level:16") is None
    info = aotbundle.load(path=path, plan=plan)
    assert info["status"] == "loaded"
    assert info["buckets"]["merkle_level:16"] == "warm"
    assert info["version"] == aotbundle.bundle_version(plan)

    # the deserialized executable computes the real inner-node hash
    left = np.zeros((16, 8), np.uint32)
    out = np.asarray(aotbundle.timed_call("merkle_level:16", left, left))
    expect = hashlib.sha256(b"\x01" + b"\x00" * 64).digest()
    got = b"".join(int(w).to_bytes(4, "big") for w in out[0])
    assert got == expect
    # first-dispatch gauge recorded a warm (sub-compile) time
    from cometbft_tpu.libs import metrics

    g = metrics.gauge("crypto_kernel_first_dispatch_seconds", "")
    assert 0 <= g.value(kind="merkle_level", lanes="16") < 1.0


def test_bundle_version_mismatch_ignored_with_counter(tmp_path):
    plan = _tiny_plan()
    path = str(tmp_path / "bundle.aot")
    aotbundle.build(plan=plan, path=path)
    aotbundle.reset()
    # a different plan (different hash) must refuse the same file
    other = dataclasses.replace(plan, rlc_min_lanes=1)
    before = _stale_counter().value(reason="version")
    info = aotbundle.load(path=path, plan=other)
    assert info["status"] == "stale"
    assert aotbundle.lookup("merkle_level:16") is None
    assert _stale_counter().value(reason="version") == before + 1


def test_bundle_corrupt_file_ignored_with_counter(tmp_path):
    path = str(tmp_path / "bundle.aot")
    with open(path, "wb") as f:
        f.write(b"\x00garbage" * 100)
    before = _stale_counter().value(reason="corrupt")
    info = aotbundle.load(path=path, plan=_tiny_plan())
    assert info["status"] == "corrupt"
    assert _stale_counter().value(reason="corrupt") == before + 1


def test_bundle_absent_is_absent(tmp_path):
    info = aotbundle.load(path=str(tmp_path / "nope.aot"),
                          plan=_tiny_plan())
    assert info["status"] == "absent"
    assert aotbundle.info()["status"] == "absent"


def test_bundle_bad_bucket_payload_skipped(tmp_path):
    import msgpack

    plan = _tiny_plan()
    path = str(tmp_path / "bundle.aot")
    aotbundle.build(plan=plan, path=path)
    with open(path, "rb") as f:
        doc = msgpack.unpackb(f.read(), raw=False)
    doc["buckets"]["merkle_level:16"]["trees"] = b"not a pickle"
    with open(path, "wb") as f:
        f.write(msgpack.packb(doc, use_bin_type=True))
    aotbundle.reset()
    before = _stale_counter().value(reason="bucket")
    info = aotbundle.load(path=path, plan=plan)
    assert info["status"] == "loaded"            # header was fine
    assert info["buckets"]["merkle_level:16"] == "degraded:deserialize"
    assert aotbundle.lookup("merkle_level:16") is None
    assert _stale_counter().value(reason="bucket") == before + 1


def test_merkle_level_dispatch_consults_bundle(tmp_path):
    """The merkle kernel loop picks the bundled executable for a loaded
    width (the warm-boot path the smoke proves cross-process)."""
    plan = dataclasses.replace(P.active(), warm_kinds=(),
                               warm_merkle=(16,), merkle_buckets=(16,))
    path = str(tmp_path / "bundle.aot")
    aotbundle.build(plan=plan, path=path)
    aotbundle.reset()
    aotbundle.load(path=path, plan=plan)
    assert aotbundle.lookup("merkle_level:16") is not None
    P.set_plan(plan, push_min_lanes=False)
    from cometbft_tpu.crypto import merkle as M

    words = np.arange(4 * 8, dtype=np.uint32).reshape(4, 8)
    jits = (aotbundle.lookup("merkle_level:16"), None, __import__(
        "cometbft_tpu.ops.sha256", fromlist=["x"]))
    out = M._kernel_levels_from_words(words.copy(), jits,
                                      keep_levels=False)
    # reference: hash pairs with hashlib down to the root
    def h(l_, r_):
        return hashlib.sha256(b"\x01" + l_ + r_).digest()

    rows = [b"".join(int(w).to_bytes(4, "big") for w in row)
            for row in words]
    expect = h(h(rows[0], rows[1]), h(rows[2], rows[3]))
    got = b"".join(int(w).to_bytes(4, "big") for w in np.asarray(out)[0])
    assert got == expect


def test_block_buckets_honored_by_padding():
    """The plan's block_buckets steer dispatch padding (a configured
    plan must never be a dead knob that only invalidates bundles)."""
    P.set_plan(dataclasses.replace(P.active(), block_buckets=(4, 8)),
               push_min_lanes=False)
    z = np.zeros((4, 32), np.uint8)
    msgs = np.zeros((4, 120), np.uint8)
    lens = np.full((4,), 120, np.int64)
    args = B._padded_lane_args(z, z, z, msgs, lens, 4)
    assert args[3].shape[1] == 4          # 2 needed -> 4-block bucket


def test_patient_wait_scales_with_lanes():
    """The patient device wait grows with the submitted window (a deep
    accumulated window must not be misread as a wedge) and stays
    bounded so a real wedge still falls back."""
    small = B.patient_wait_s(256)
    big = B.patient_wait_s(50_000)
    assert small >= 2 * B._DEVICE_WAIT_S
    assert big > small
    # the work term is capped on top of the configured fail-fast wait
    assert B.patient_wait_s(10_000_000) <= 2 * B._DEVICE_WAIT_S + 56.0


def test_enumerate_gather_buckets_and_sample_shapes():
    """warm_tables adds the cached-valset route (tables + gather +
    rlc_gather) to the bundle, and the gather sample args match the
    runtime dispatch protocol (tab/ok avals straight from the
    table-build kernel)."""
    plan = dataclasses.replace(P.active(), warm_lanes=(16,),
                               warm_blocks=(2,), warm_tables=(64,))
    keys = [b.key for b in P.enumerate_buckets(plan)]
    assert "tables:64" in keys
    assert "gather:64:16x2" in keys and "rlc_gather:64:16x2" in keys
    gb = next(b for b in P.enumerate_buckets(plan)
              if b.key == "gather:64:16x2")
    args = aotbundle.sample_args(gb)
    tab, ok, idx, r32, s32, blocks, active = args
    # tab is the ops.group Cached pytree: (16, 20, rows) components
    assert all(leaf.shape[-1] == 64 for leaf in tab)
    assert ok.shape == (64,)
    assert idx.shape == (16,) and idx.dtype == np.int32
    assert r32.shape == (16, 32) and blocks.shape[:2] == (16, 2)
    tb = next(b for b in P.enumerate_buckets(plan)
              if b.key == "tables:64")
    (pad,) = aotbundle.sample_args(tb)
    assert pad.shape == (64, 32) and pad.dtype == np.int32
    # warm_tables changes the plan hash (bundle re-keyed per valset
    # bucket)
    assert P.plan_hash(plan) != P.plan_hash()
