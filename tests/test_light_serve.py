"""Light-client serving tier (light/serve.py + the light_* RPC routes):
merkle TreeCache equivalence, header-LRU hit/miss/evict semantics under
valset churn and trust-period expiry, trusted-store pruning, batched
anchor verification (memo, dedup-cache seeding, bad-commit demux), and
one live-node end-to-end pass over the new routes."""

import asyncio
import copy
from types import SimpleNamespace

import pytest

from cometbft_tpu.crypto import merkle
from cometbft_tpu.light.serve import (LightServeError,
                                      LightServeRequestError,
                                      LightServeTier)
from cometbft_tpu.light.store import TrustedStore
from cometbft_tpu.rpc.json import jsonable
from cometbft_tpu.testing import make_light_chain

pytestmark = pytest.mark.timeout(120)

CHAIN = "light-chain"
NS = 1_000_000_000


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# --------------------------------------------------------- stub stores

class StubBlockStore:
    """Minimal blockstore view over a make_light_chain chain; per-height
    tx lists are synthesized so the tx proof kind has leaves."""

    def __init__(self, chain, txs_per_block=0):
        self.by_height = {lb.height: lb for lb in chain}
        self.txs = {
            lb.height: [b"tx-%d-%d" % (lb.height, i)
                        for i in range(txs_per_block)]
            for lb in chain}
        self.loads = 0

    def base(self):
        return min(self.by_height)

    def height(self):
        return max(self.by_height)

    def load_block(self, h):
        lb = self.by_height.get(h)
        if lb is None:
            return None
        self.loads += 1
        return SimpleNamespace(header=lb.header,
                               data=SimpleNamespace(txs=self.txs[h]))

    def load_block_commit(self, h):
        lb = self.by_height.get(h)
        return lb.commit if lb is not None else None

    def load_block_meta(self, h):
        lb = self.by_height.get(h)
        if lb is None:
            return None
        return SimpleNamespace(block_id=lb.commit.block_id)

    def load_seen_commit(self):
        return None


class StubStateStore:
    def __init__(self, chain):
        self.by_height = {lb.height: lb.validators for lb in chain}

    def load_validators(self, h):
        return self.by_height.get(h)


def _tier(chain, *, txs_per_block=0, now_ns=None, **kw):
    bs = StubBlockStore(chain, txs_per_block=txs_per_block)
    ss = StubStateStore(chain)
    kw.setdefault("backend", "cpu")
    if now_ns is None:
        def now_ns():
            return chain[-1].header.time_ns + 60 * NS
    return LightServeTier(bs, ss, CHAIN, now_ns=now_ns, **kw), bs


# ------------------------------------------------------------ TreeCache

def test_tree_cache_matches_reference_builder():
    for n in (1, 2, 3, 5, 8, 9, 63, 64, 65, 100, 130):
        items = [b"leaf%d" % i for i in range(n)]
        root, ref = merkle.proofs_from_byte_slices_reference(items)
        tc = merkle.TreeCache.build(items)
        assert tc.root == root
        assert tc.total == n
        for i in (0, n // 2, n - 1):
            assert tc.proof(i) == ref[i]
            assert tc.proof(i).verify(root, items[i])
        assert tc.proofs(range(n)) == ref
    with pytest.raises(IndexError):
        merkle.TreeCache.build([b"x"]).proof(1)


# ------------------------------------------------- header LRU semantics

def test_light_block_cache_hit_miss_and_lru_eviction():
    chain = make_light_chain(10, n_vals=4)
    tier, bs = _tier(chain, header_cache_size=4)
    for h in range(1, 11):
        res = tier.light_block(h)
        assert res["height"] == h and res["canonical"]
        assert res["light_block"]["total_voting_power"] == 40
    st = tier.stats()
    assert st["header_misses"] == 10 and st["header_hits"] == 0
    assert st["evictions_lru"] == 6          # 10 inserts into 4 slots
    assert st["header_cache_entries"] == 4
    loads = bs.loads
    tier.light_block(10)                     # newest: cached
    assert tier.stats()["header_hits"] == 1
    assert bs.loads == loads                 # no store touch
    tier.light_block(1)                      # oldest: evicted -> miss
    assert tier.stats()["header_misses"] == 11


def test_header_cache_byte_budget_evicts():
    """The header LRU is byte-bounded too: commit JSON dominates at
    large validator counts, so counting entries alone would let the
    cache eat gigabytes."""
    chain = make_light_chain(6, n_vals=4)
    # each entry estimates 2048 + 200*4 bytes; budget for ~2 entries
    tier, _bs = _tier(chain, header_cache_size=100,
                      header_cache_bytes=6000)
    for h in range(1, 7):
        tier.light_block(h)
    st = tier.stats()
    assert st["header_cache_entries"] == 2
    assert st["header_cache_bytes"] <= 6000
    assert st["evictions_lru"] == 4


def test_light_block_under_valset_churn():
    """Rotating validator sets: every height's entry carries ITS OWN
    valset (hash-checked against the header), and eviction under churn
    re-loads the right one."""
    chain = make_light_chain(8, n_vals=4, rotate_every=2)
    tier, _bs = _tier(chain, header_cache_size=2)
    from cometbft_tpu.rpc.json import from_jsonable

    for h in (1, 4, 7, 1, 4, 7):             # churn through 2 slots
        res = tier.light_block(h)
        vals = from_jsonable(res["light_block"]["validators"])
        assert vals.hash() == chain[h - 1].header.validators_hash
    st = tier.stats()
    assert st["header_misses"] >= 5          # slot churn forced reloads
    assert st["evictions_lru"] >= 3


def test_trust_period_window_evicts_expired_entries():
    chain = make_light_chain(3, n_vals=4)
    now = {"ns": chain[-1].header.time_ns + 60 * NS}
    tier, _bs = _tier(chain, trust_period_ns=3600 * NS,
                      now_ns=lambda: now["ns"])
    tier.light_block(2)
    assert tier.stats()["header_cache_entries"] == 1
    tier.light_block(2)
    assert tier.stats()["header_hits"] == 1
    # the header leaves the trusting period: evicted on sight, still
    # served (historic queries work), NOT re-cached
    now["ns"] = chain[1].header.time_ns + 3601 * NS
    res = tier.light_block(2)
    assert res["height"] == 2
    st = tier.stats()
    assert st["evictions_trust_period"] == 1
    assert st["header_cache_entries"] == 0


def test_light_blocks_batch_and_per_item_errors():
    chain = make_light_chain(5, n_vals=4)
    tier, _bs = _tier(chain, max_batch=8)
    res = tier.light_blocks([1, 3, 99])
    assert res["latest"] == 5 and res["base"] == 1
    ok = [e for e in res["light_blocks"] if "light_block" in e]
    bad = [e for e in res["light_blocks"] if "error" in e]
    assert [e["height"] for e in ok] == [1, 3]
    assert bad[0]["height"] == 99 and "not available" in bad[0]["error"]
    # comma-string heights (URI-style GET)
    res2 = tier.light_blocks("1,2")
    assert [e["height"] for e in res2["light_blocks"]] == [1, 2]
    with pytest.raises(LightServeRequestError):
        tier.light_blocks(list(range(1, 11)))      # > max_batch
    with pytest.raises(LightServeRequestError):
        tier.light_blocks([])


# ------------------------------------------------------------- proofs

def test_proofs_served_from_one_tree_build():
    chain = make_light_chain(3, n_vals=4)
    tier, _bs = _tier(chain, txs_per_block=40)
    res = tier.proofs(2, "tx", [0, 7, 39])
    leaves = [b"tx-2-%d" % i for i in range(40)]
    from cometbft_tpu.types.header import tx_hash

    root = merkle.hash_from_byte_slices([tx_hash(t) for t in leaves])
    assert bytes.fromhex(res["root"]) == root
    assert res["total"] == 40
    for p, i in zip(res["proofs"], (0, 7, 39)):
        proof = merkle.Proof(p["total"], p["index"],
                             bytes.fromhex(p["leaf_hash"]),
                             tuple(bytes.fromhex(a) for a in p["aunts"]))
        assert proof.verify(root, tx_hash(leaves[i]))
    # second request hits the cached tree
    tier.proofs(2, "tx", "1,2,3")
    st = tier.stats()
    assert st["proof_misses"] == 1 and st["proof_hits"] == 1
    assert st["proofs_served"] == 6


def test_validator_proofs_anchor_to_validators_hash():
    chain = make_light_chain(2, n_vals=7)
    tier, _bs = _tier(chain)
    res = tier.proofs(1, "validator")
    lb = chain[0]
    assert bytes.fromhex(res["root"]) == lb.header.validators_hash
    assert res["total"] == 7
    v3 = lb.validators.validators[3]
    p = res["proofs"][3]
    proof = merkle.Proof(p["total"], p["index"],
                         bytes.fromhex(p["leaf_hash"]),
                         tuple(bytes.fromhex(a) for a in p["aunts"]))
    assert proof.verify(lb.header.validators_hash, v3.simple_encode())


def test_proof_tree_lru_eviction():
    chain = make_light_chain(4, n_vals=4)
    tier, _bs = _tier(chain, txs_per_block=8, proof_cache_blocks=2)
    for h in (1, 2, 3):
        tier.proofs(h, "tx", [0])
    assert tier.stats()["proof_cache_entries"] == 2
    tier.proofs(1, "tx", [0])                 # evicted: rebuilt
    st = tier.stats()
    assert st["proof_misses"] == 4 and st["evictions_lru"] >= 1
    tier.proofs(1, "tx", [1])                 # fresh again: hit
    assert tier.stats()["proof_hits"] == 1


def test_proofs_request_validation():
    chain = make_light_chain(2, n_vals=4)
    tier, _bs = _tier(chain, txs_per_block=4, max_proofs=3)
    with pytest.raises(LightServeRequestError):
        tier.proofs(1, "bogus", [0])
    with pytest.raises(LightServeRequestError):
        tier.proofs(1, "tx", [4])             # out of range
    with pytest.raises(LightServeRequestError):
        tier.proofs(1, "tx", None)            # 4 leaves > max_proofs=3
    with pytest.raises(LightServeRequestError):
        tier.proofs(1, "tx", [0, 1, 2, 3])    # > max_proofs
    with pytest.raises(LightServeError):
        tier.proofs(77, "tx", [0])            # height unavailable


# ------------------------------------------------- anchor verification

def _anchor(lb):
    return {"height": lb.height, "commit": jsonable(lb.commit)}


def _tampered(lb):
    bad = copy.deepcopy(lb.commit)
    sig = bytearray(bad.signatures[0].signature)
    sig[0] ^= 0xFF
    bad.signatures[0].signature = bytes(sig)
    return {"height": lb.height, "commit": jsonable(bad)}


def test_verify_commits_batched_memo_and_demux():
    chain = make_light_chain(4, n_vals=4)
    tier, _bs = _tier(chain)
    anchors = [_anchor(chain[0]), _tampered(chain[1]), _anchor(chain[2])]
    res = tier.verify_commits(anchors)
    assert res["ok"] == 2 and res["failed"] == 1
    r1, r2, r3 = res["results"]
    assert r1 == {"height": 1, "ok": True, "cached": False}
    assert r2["ok"] is False and "signature" in r2["error"]
    assert r3 == {"height": 3, "ok": True, "cached": False}
    # second pass: good anchors hit the whole-commit verdict memo, the
    # bad one re-verifies (negative verdicts are never cached)
    res2 = tier.verify_commits(anchors)
    assert res2["results"][0]["cached"] is True
    assert res2["results"][2]["cached"] is True
    assert res2["results"][1]["ok"] is False
    st = tier.stats()
    assert st["verify_hits"] == 2
    assert st["anchors_ok"] == 4 and st["anchors_bad"] == 2


def test_verify_commits_rejects_foreign_fork_commit():
    chain = make_light_chain(4, n_vals=4)
    fork = make_light_chain(4, n_vals=4, fork_at=2, fork_skew_ns=7 * NS)
    tier, _bs = _tier(chain)
    res = tier.verify_commits([_anchor(fork[3])])
    assert res["failed"] == 1
    assert "different block" in res["results"][0]["error"]
    # and a commit claiming the wrong height is caught pre-dispatch
    wrong = {"height": 2, "commit": jsonable(chain[2].commit)}
    res2 = tier.verify_commits([wrong])
    assert res2["failed"] == 1 and "height" in res2["results"][0]["error"]
    # a non-Commit codec payload is refused per-anchor, not a crash
    from cometbft_tpu.types.vote import PRECOMMIT_TYPE, Vote

    vote = Vote(type=PRECOMMIT_TYPE, height=2, round=0,
                block_id=chain[1].commit.block_id, timestamp_ns=1,
                validator_address=b"\x01" * 20, validator_index=0)
    res3 = tier.verify_commits([{"height": 2, "commit": jsonable(vote)},
                                _anchor(chain[0])])
    assert res3["failed"] == 1 and res3["ok"] == 1
    assert "not a Commit" in res3["results"][0]["error"]


def test_verify_commits_mixed_valsets_group_and_verify():
    chain = make_light_chain(8, n_vals=4, rotate_every=2)
    tier, _bs = _tier(chain)
    anchors = [_anchor(chain[i]) for i in (0, 2, 3, 6)]
    res = tier.verify_commits(anchors)
    assert res["ok"] == 4 and res["failed"] == 0


def test_batched_use_cache_consults_and_seeds_dedup_cache():
    from cometbft_tpu.crypto import scheduler as vsched
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.types.validation import verify_commits_light_batched

    chain = make_light_chain(3, n_vals=8)
    items = [(lb.commit.block_id, lb.height, lb.commit) for lb in chain]
    vals = chain[0].validators
    sched = vsched.VerificationScheduler(backend="cpu", cache_size=4096)
    vsched.set_scheduler(sched)
    try:
        hits = m.counter("crypto_sched_cache_hits_total")
        before = hits.value(source="commit")
        n1 = verify_commits_light_batched(CHAIN, vals, items,
                                          backend="cpu", use_cache=True)
        assert n1 > 0 and len(sched.cache) >= n1
        assert hits.value(source="commit") == before   # cold: no hits
        n2 = verify_commits_light_batched(CHAIN, vals, items,
                                          backend="cpu", use_cache=True)
        assert n2 == n1                   # proven count, hit or dispatched
        assert hits.value(source="commit") >= before + n1
        # a tampered commit still fails WITH the cache on
        bad = copy.deepcopy(chain[1].commit)
        sig = bytearray(bad.signatures[0].signature)
        sig[0] ^= 0xFF
        bad.signatures[0].signature = bytes(sig)
        from cometbft_tpu.types.validation import ErrBatchItemInvalid

        with pytest.raises(ErrBatchItemInvalid) as ei:
            verify_commits_light_batched(
                CHAIN, vals,
                [items[0], (bad.block_id, bad.height, bad), items[2]],
                backend="cpu", use_cache=True)
        assert ei.value.item == 1
    finally:
        vsched.set_scheduler(None)


# ------------------------------------------------- trusted-store pruning

def test_trusted_store_prunes_oldest_first():
    chain = make_light_chain(10, n_vals=4)
    store = TrustedStore()
    for lb in chain:
        store.save(lb)
    store.prune(3)
    assert store.first().height == 8
    assert store.latest().height == 10
    assert store.get(7) is None and store.get(9) is not None
    store.prune(0)
    assert store.latest() is None and store.first() is None


# ------------------------------------------------------- live-node pass

def test_light_serve_routes_on_live_node():
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.rpc import HTTPClient
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.header import tx_hash
    from cometbft_tpu.types.priv_validator import MockPV

    async def main():
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        pv = MockPV.from_secret(b"lightserve-node")
        doc = GenesisDoc(chain_id="ls-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)])
        node = await Node.create(doc, KVStoreApplication(),
                                 priv_validator=pv, config=cfg, name="ls0")
        await node.start()
        try:
            cli = HTTPClient(*node.rpc_addr)
            res = await cli.call("broadcast_tx_commit", tx=b"lk=lv".hex())
            h = res["height"]
            # wait one MORE height so h's commit is canonical
            for _ in range(600):
                if node.block_store.height() > h:
                    break
                await asyncio.sleep(0.02)

            # batched bootstrap
            out = await cli.call("light_blocks", heights=[1, h])
            entries = out["light_blocks"]
            assert all("light_block" in e for e in entries)

            # anchor verification against the served commit (the exact
            # round trip a bootstrapping fleet performs), twice: the
            # second hit must come from the verdict memo
            anchor = {"height": h,
                      "commit": entries[1]["light_block"]["commit"]}
            v1 = await cli.call("light_verify", anchors=[anchor])
            assert v1["ok"] == 1 and v1["results"][0]["cached"] is False
            v2 = await cli.call("light_verify", anchors=[anchor])
            assert v2["results"][0]["cached"] is True

            # batched tx proofs verified client-side against the real
            # header's data_hash
            blk = await cli.call("block", height=h)
            data_hash = bytes.fromhex(blk["block"]["hdr"]["dh"]["~b"])
            pr = await cli.call("light_proofs", height=h, kind="tx")
            assert pr["total"] == 1
            assert bytes.fromhex(pr["root"]) == data_hash
            p = pr["proofs"][0]
            proof = merkle.Proof(
                p["total"], p["index"], bytes.fromhex(p["leaf_hash"]),
                tuple(bytes.fromhex(a) for a in p["aunts"]))
            assert proof.verify(data_hash, tx_hash(b"lk=lv"))

            # the RPC provider consumes the serving tier in ONE round
            # trip and falls back cleanly elsewhere
            from cometbft_tpu.light.rpc_provider import RPCProvider

            prov = RPCProvider(*node.rpc_addr)
            lb = await prov.light_block(h)
            assert prov._has_light_block is True
            assert lb.height == h
            assert lb.validators.hash() == lb.header.validators_hash
            await prov.client.close()

            # stats surfaced via /status
            st = await cli.call("status")
            ls = st["light_serve"]
            assert ls["blocks_served"] >= 3
            assert ls["proofs_served"] >= 1
            assert ls["anchors_ok"] >= 1 and ls["verify_hits"] >= 1
            await cli.close()
        finally:
            await node.stop()

    run(main())
