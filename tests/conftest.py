"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh *before* jax is imported anywhere:
multi-chip sharding (parallel/) is exercised on host CPU exactly the way the
driver's dryrun does, and tests never contend for the real TPU.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The image's sitecustomize imports jax (axon TPU plugin) at interpreter
# start, so jax latched JAX_PLATFORMS=axon before this file ran — the env
# vars above don't reach jax.config anymore.  Force CPU through the config
# API and deregister the axon/tpu factories so backend discovery can never
# dial the TPU relay (tests are CPU-only by design; a wedged relay would
# otherwise hang the first jit forever).
import jax  # noqa: E402  (registers factories, does not init backends)
from jax._src import xla_bridge as _xb  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent compile cache: kernel compiles dominate suite time on 1 CPU core
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
try:
    _xb._backend_factories.pop("axon", None)
    _xb._backend_factories.pop("tpu", None)
except AttributeError:  # private symbol moved in a jax upgrade
    pass
