"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh *before* jax is imported anywhere:
multi-chip sharding (parallel/) is exercised on host CPU exactly the way the
driver's dryrun does, and tests never contend for the real TPU.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
