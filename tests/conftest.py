"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh *before* any backend init:
multi-chip sharding (parallel/) is exercised on host CPU exactly the way the
driver's dryrun does, and tests never contend for (or hang on) the real TPU.
The force-CPU + compile-cache defenses live in cometbft_tpu.jaxenv.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.jaxenv import enable_compile_cache, force_cpu_backend  # noqa: E402

force_cpu_backend(min_devices=8)
enable_compile_cache()

# kernel tests must exercise the device code path even when a cold compile
# outlasts the production watchdog (which would silently host-fallback)
from cometbft_tpu.crypto import batch as _batch  # noqa: E402

_batch.set_device_wait(900)


# ---------------------------------------------------------------------------
# Real per-test timeout enforcement. ``pytest-timeout`` is not installed in
# this image, so ``pytest.mark.timeout(N)`` marks would silently be no-ops;
# this hook honors them (default 180 s) via SIGALRM, which interrupts even a
# stuck asyncio loop on the main thread.
# ---------------------------------------------------------------------------

import signal  # noqa: E402

import pytest  # noqa: E402

_DEFAULT_TEST_TIMEOUT = 180


class TestTimeoutExit(SystemExit):
    """Raised by the SIGALRM watchdog.  MUST derive from SystemExit: an
    alarm that fires while the main thread is inside an asyncio callback
    lands in ``Handle._run`` / ``Task.__step``, which swallow every
    ordinary exception (they log-and-continue), so a ``TimeoutError``
    there never fails the test and a stuck event loop eats the whole
    tier-1 budget.  SystemExit (and KeyboardInterrupt) are the only
    classes those frames re-raise; pytest records SystemExit as a plain
    test failure and moves on to the next test."""


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("timeout")
    seconds = int(marker.args[0]) if marker and marker.args \
        else _DEFAULT_TEST_TIMEOUT

    def _on_alarm(signum, frame):
        raise TestTimeoutExit(
            f"test exceeded {seconds}s timeout (conftest SIGALRM)")

    old = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(seconds)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "timeout(seconds): per-test wall-clock limit "
        "(enforced by conftest SIGALRM)")
