"""Blocksync: cross-block batched commit verification (the flagship
cross-block TPU batching, BASELINE configs[4]) and fast-sync over real TCP
(reference: ``internal/blocksync/{pool,reactor}_test.go``)."""

import asyncio

import pytest

from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.validation import (ErrBatchItemInvalid,
                                           verify_commits_light_batched)
from cometbft_tpu.types.validator_set import Validator, ValidatorSet

from test_types import CHAIN_ID, make_commit

pytestmark = pytest.mark.timeout(150)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _vals(powers):
    privs = [Ed25519PrivKey.from_secret(b"bsv%d" % i)
             for i in range(len(powers))]
    vals = ValidatorSet([Validator(p.pub_key(), pw)
                         for p, pw in zip(privs, powers)])
    by_addr = {p.pub_key().address(): p for p in privs}
    return vals, by_addr


def _bid(h):
    return BlockID(bytes([h]) * 32, PartSetHeader(1, bytes([h ^ 0xFF]) * 32))


def test_batched_multi_commit_verify_ok():
    vals, by_addr = _vals([10] * 4)
    items = []
    for h in range(1, 6):
        commit = make_commit(vals, by_addr, height=h, round_=0, bid=_bid(h))
        items.append((commit.block_id, h, commit))
    n = verify_commits_light_batched(CHAIN_ID, vals, items, backend="cpu")
    assert n > 0


def test_batched_multi_commit_flags_offending_item():
    vals, by_addr = _vals([10] * 4)
    items = []
    for h in range(1, 6):
        bad = {0} if h == 3 else set()
        commit = make_commit(vals, by_addr, height=h, round_=0, bid=_bid(h),
                             bad_at=bad)
        items.append((commit.block_id, h, commit))
    with pytest.raises(ErrBatchItemInvalid) as exc:
        verify_commits_light_batched(CHAIN_ID, vals, items, backend="cpu")
    assert exc.value.item == 2 and exc.value.height == 3


def test_batched_multi_commit_flags_wrong_block_id():
    vals, by_addr = _vals([10] * 4)
    commit = make_commit(vals, by_addr, height=1, round_=0, bid=_bid(1))
    with pytest.raises(ErrBatchItemInvalid) as exc:
        verify_commits_light_batched(
            CHAIN_ID, vals, [(_bid(2), 1, commit)], backend="cpu")
    assert exc.value.item == 0


def test_fast_sync_over_tcp():
    """A late full node block-syncs a committed chain from 3 validators
    over real TCP, then follows via consensus (reactor.go:421-431
    SwitchToConsensus; VERDICT round-1 item 4's bar)."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    def cfg():
        c = Config(consensus=test_consensus_config())
        c.p2p.laddr = "tcp://127.0.0.1:0"
        c.rpc.laddr = "tcp://127.0.0.1:0"
        return c

    async def main():
        pvs = [MockPV.from_secret(b"bsnode%d" % i) for i in range(3)]
        doc = GenesisDoc(chain_id="bs-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            node = await Node.create(
                doc, KVStoreApplication(), priv_validator=pv, config=cfg(),
                node_key=NodeKey.from_secret(b"bsk%d" % i), name=f"bs{i}")
            nodes.append(node)
        try:
            for n in nodes:
                await n.start()
            for i, a in enumerate(nodes):
                for b in nodes[i + 1:]:
                    await a.dial_peer(b.listen_addr, persistent=True)
            for i in range(4):
                await nodes[0].mempool.check_tx(b"bs%d=x%d" % (i, i))

            async def reach(h, who):
                while not all(n.height() >= h for n in who):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(6, nodes), 60)

            # late joiner: full node (no validator key), fast-sync mode
            late = await Node.create(
                doc, KVStoreApplication(), priv_validator=None, config=cfg(),
                node_key=NodeKey.from_secret(b"bsk9"), fast_sync=True,
                name="bslate")
            nodes.append(late)
            await late.start()
            for a in nodes[:3]:
                await late.dial_peer(a.listen_addr, persistent=True)

            # must blocksync to (near) tip, switch to consensus, and follow
            target = max(n.height() for n in nodes[:3]) + 3
            await asyncio.wait_for(reach(target, nodes), 90)
            assert late.blocksync_reactor.synced.is_set()
            for h in range(1, target + 1):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"fork at height {h}"
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())
