"""Blocksync: cross-block batched commit verification (the flagship
cross-block TPU batching, BASELINE configs[4]) and fast-sync over real TCP
(reference: ``internal/blocksync/{pool,reactor}_test.go``)."""

import asyncio

import pytest

from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.validation import (ErrBatchItemInvalid,
                                           verify_commits_light_batched)
from cometbft_tpu.types.validator_set import Validator, ValidatorSet

from test_types import CHAIN_ID, make_commit

pytestmark = pytest.mark.timeout(150)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _vals(powers):
    privs = [Ed25519PrivKey.from_secret(b"bsv%d" % i)
             for i in range(len(powers))]
    vals = ValidatorSet([Validator(p.pub_key(), pw)
                         for p, pw in zip(privs, powers)])
    by_addr = {p.pub_key().address(): p for p in privs}
    return vals, by_addr


def _bid(h):
    return BlockID(bytes([h]) * 32, PartSetHeader(1, bytes([h ^ 0xFF]) * 32))


def test_batched_multi_commit_verify_ok():
    vals, by_addr = _vals([10] * 4)
    items = []
    for h in range(1, 6):
        commit = make_commit(vals, by_addr, height=h, round_=0, bid=_bid(h))
        items.append((commit.block_id, h, commit))
    n = verify_commits_light_batched(CHAIN_ID, vals, items, backend="cpu")
    assert n > 0


def test_batched_multi_commit_flags_offending_item():
    vals, by_addr = _vals([10] * 4)
    items = []
    for h in range(1, 6):
        bad = {0} if h == 3 else set()
        commit = make_commit(vals, by_addr, height=h, round_=0, bid=_bid(h),
                             bad_at=bad)
        items.append((commit.block_id, h, commit))
    with pytest.raises(ErrBatchItemInvalid) as exc:
        verify_commits_light_batched(CHAIN_ID, vals, items, backend="cpu")
    assert exc.value.item == 2 and exc.value.height == 3


def test_batched_multi_commit_flags_wrong_block_id():
    vals, by_addr = _vals([10] * 4)
    commit = make_commit(vals, by_addr, height=1, round_=0, bid=_bid(1))
    with pytest.raises(ErrBatchItemInvalid) as exc:
        verify_commits_light_batched(
            CHAIN_ID, vals, [(_bid(2), 1, commit)], backend="cpu")
    assert exc.value.item == 0


def test_fast_sync_over_tcp():
    """A late full node block-syncs a committed chain from 3 validators
    over real TCP, then follows via consensus (reactor.go:421-431
    SwitchToConsensus; VERDICT round-1 item 4's bar)."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    def cfg():
        c = Config(consensus=test_consensus_config())
        c.p2p.laddr = "tcp://127.0.0.1:0"
        c.rpc.laddr = "tcp://127.0.0.1:0"
        return c

    async def main():
        pvs = [MockPV.from_secret(b"bsnode%d" % i) for i in range(3)]
        doc = GenesisDoc(chain_id="bs-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            node = await Node.create(
                doc, KVStoreApplication(), priv_validator=pv, config=cfg(),
                node_key=NodeKey.from_secret(b"bsk%d" % i), name=f"bs{i}")
            nodes.append(node)
        try:
            for n in nodes:
                await n.start()
            for i, a in enumerate(nodes):
                for b in nodes[i + 1:]:
                    await a.dial_peer(b.listen_addr, persistent=True)
            for i in range(4):
                await nodes[0].mempool.check_tx(b"bs%d=x%d" % (i, i))

            async def reach(h, who):
                while not all(n.height() >= h for n in who):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(6, nodes), 60)

            # late joiner: full node (no validator key), fast-sync mode
            late = await Node.create(
                doc, KVStoreApplication(), priv_validator=None, config=cfg(),
                node_key=NodeKey.from_secret(b"bsk9"), fast_sync=True,
                name="bslate")
            nodes.append(late)
            await late.start()
            for a in nodes[:3]:
                await late.dial_peer(a.listen_addr, persistent=True)

            # must blocksync to (near) tip, switch to consensus, and follow
            target = max(n.height() for n in nodes[:3]) + 3
            await asyncio.wait_for(reach(target, nodes), 90)
            assert late.blocksync_reactor.synced.is_set()
            for h in range(1, target + 1):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"fork at height {h}"
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())


# ---------------------------------------------------------------------------
# r13 cross-block accumulator: pipelined windows, per-item demux, edges
# ---------------------------------------------------------------------------

from types import SimpleNamespace

from cometbft_tpu.blocksync import reactor as reactor_mod
from cometbft_tpu.blocksync.reactor import BlocksyncReactor


class _Blk:
    """Stub block: just enough surface for the accumulator (header,
    last_commit, hash, evidence); codec/PartSet are monkeypatched so the
    packed parts header matches the _bid() the commits signed."""

    def __init__(self, h, vals_hash, last_commit):
        self.header = SimpleNamespace(height=h, validators_hash=vals_hash)
        self.last_commit = last_commit
        self.evidence = []

    def hash(self):
        return bytes([self.header.height]) * 32


class _Parts:
    def __init__(self, blk):
        self._hdr = PartSetHeader(
            1, bytes([blk.header.height ^ 0xFF]) * 32)

    def header(self):
        return self._hdr


class _FakePool:
    """Deterministic in-memory BlockPool facade: serves a pre-built
    chain, mirrors redo_request's score-and-refetch semantics (the real
    pool reports ``bad_block`` for the serving peer and refetches)."""

    def __init__(self, start_h, blocks, on_peer_error=None,
                 good_blocks=None):
        self.height = start_h
        self.blocks = {b.header.height: b for b in blocks}
        self.good = {b.header.height: b for b in (good_blocks or [])}
        self.on_peer_error = on_peer_error or (lambda p, r, e: None)
        self.peers = {"p1": object()}
        self.redone = []
        self.max_h = max(self.blocks)

    def peek_window(self, n):
        out, h = [], self.height
        while len(out) < n and h in self.blocks:
            out.append((self.blocks[h], None))
            h += 1
        return out

    def pop_request(self):
        self.height += 1

    def redo_request(self, h):
        self.redone.append(h)
        self.on_peer_error(f"peer-of-{h}", f"bad block at {h}",
                           "bad_block")
        if h in self.good:          # the refetch serves an honest copy
            self.blocks[h] = self.good[h]
        return f"peer-of-{h}"

    def is_caught_up(self):
        # the real pool is caught up at the best peer height; the final
        # block (no voucher yet) is consensus's to finish
        return self.height >= self.max_h

    async def stop(self):
        pass


def _chain(vals, by_addr, first_h, last_h, *, bad_commit_for=(),
           wrong_bid_for=()):
    """Blocks first_h..last_h whose last_commit certifies the previous
    height with REAL signatures (the accumulator's items).  The first
    block's own last_commit is irrelevant (never verified)."""
    blocks = []
    vh = vals.hash()
    for h in range(first_h, last_h + 1):
        if h == first_h:
            lc = None
        else:
            prev = h - 1
            bid = _bid(prev + 2) if prev in wrong_bid_for else _bid(prev)
            lc = make_commit(vals, by_addr, height=prev, round_=0,
                             bid=bid,
                             bad_at={0} if prev in bad_commit_for
                             else set())
        blocks.append(_Blk(h, vh, lc))
    return blocks


def _mk_reactor(monkeypatch, vals, pool, verify_window=4,
                valset_after=None):
    """Reactor wired to stubs: real commit verification, no-op
    structural validation/storage, report_peer recorder."""
    monkeypatch.setattr(reactor_mod, "codec",
                        SimpleNamespace(pack=lambda b: b))
    monkeypatch.setattr(reactor_mod, "PartSet",
                        SimpleNamespace(from_data=lambda b: _Parts(b)))
    monkeypatch.setattr(reactor_mod, "validate_block",
                        lambda *a, **k: None)
    state = SimpleNamespace(
        chain_id=CHAIN_ID, validators=vals,
        consensus_params=SimpleNamespace(feature=SimpleNamespace(
            vote_extensions_enabled=lambda h: False)))
    applied = []

    async def apply_block(st, fid, blk, verified=False):
        applied.append(blk.header.height)
        if valset_after and blk.header.height in valset_after:
            return SimpleNamespace(
                chain_id=st.chain_id,
                validators=valset_after[blk.header.height],
                consensus_params=st.consensus_params)
        return st

    block_exec = SimpleNamespace(
        apply_block=apply_block,
        evidence_pool=SimpleNamespace(check_evidence=lambda ev: None))
    block_store = SimpleNamespace(
        save_block=lambda *a: None,
        save_block_with_extended_commit=lambda *a: None,
        height=lambda: pool.height - 1, base=lambda: 0)
    r = BlocksyncReactor(block_exec, block_store, state,
                         backend="cpu", verify_window=verify_window)
    r.pool = pool
    reports = []
    r.switch = SimpleNamespace(
        report_peer=lambda pid, ev, detail=None, disconnect=False:
        reports.append((pid, ev)),
        peers={})
    pool.on_peer_error = r._on_pool_peer_error
    return r, applied, reports


async def _drain(r):
    await asyncio.wait_for(r._apply_routine(), 30)


def test_accumulator_applies_full_chain(monkeypatch):
    """Windows deeper than one dispatch pipeline through: every block
    whose commit a successor vouches for applies."""
    vals, by_addr = _vals([10] * 4)
    blocks = _chain(vals, by_addr, 1, 9)
    pool = _FakePool(1, blocks)
    r, applied, _ = _mk_reactor(monkeypatch, vals, pool, verify_window=3)

    run(_drain(r))
    # block 9 has no voucher in the pool; 1..8 apply in order
    assert applied == list(range(1, 9))
    assert r.synced.is_set()


def test_accumulator_partial_window_flush(monkeypatch):
    """Pool drain: fewer blocks than the window dispatch immediately
    (no waiting for a full buffer)."""
    vals, by_addr = _vals([10] * 4)
    pool = _FakePool(1, _chain(vals, by_addr, 1, 3))
    r, applied, _ = _mk_reactor(monkeypatch, vals, pool,
                                verify_window=32)
    run(_drain(r))
    assert applied == [1, 2]


def test_accumulator_valset_change_mid_window(monkeypatch):
    """A rotation inside the peeked window: the same-valset prefix
    verifies and applies, then the loop re-stages the suffix against
    the post-apply validator set."""
    vals_a, by_a = _vals([10] * 4)
    privs_b = [Ed25519PrivKey.from_secret(b"bsw%d" % i) for i in range(4)]
    vals_b = ValidatorSet([Validator(p.pub_key(), 10) for p in privs_b])
    by_b = {p.pub_key().address(): p for p in privs_b}

    chain_a = _chain(vals_a, by_a, 1, 4)           # blocks 1..4, set A
    chain_b = _chain(vals_b, by_b, 4, 7)[1:]       # blocks 5..7, set B
    for b in chain_b:
        b.header.validators_hash = vals_b.hash()
    # block 5 vouches for 4 with a commit signed by A (the set that
    # committed height 4)
    chain_b[0].last_commit = make_commit(vals_a, by_a, height=4,
                                         round_=0, bid=_bid(4))
    pool = _FakePool(1, chain_a + chain_b)
    r, applied, _ = _mk_reactor(monkeypatch, vals_a, pool,
                                verify_window=16,
                                valset_after={4: vals_b})
    run(_drain(r))
    assert applied == [1, 2, 3, 4, 5, 6]


def test_accumulator_statesync_anchor_window(monkeypatch):
    """A window starting right after the statesync anchor: the anchor
    block itself is never applied, the first fetched block's commit is
    vouched by its successor as usual."""
    vals, by_addr = _vals([10] * 4)
    pool = _FakePool(101, _chain(vals, by_addr, 101, 106))
    r, applied, _ = _mk_reactor(monkeypatch, vals, pool, verify_window=4)
    run(_drain(r))
    assert applied == list(range(101, 106))


def test_accumulator_bad_commit_demux(monkeypatch):
    """One lying peer's block: the proven prefix still applies, exactly
    the bad height (+ its voucher) redoes, the serving peer is scored
    bad_block through Switch.report_peer, and after the honest refetch
    the chain completes."""
    vals, by_addr = _vals([10] * 4)
    bad = _chain(vals, by_addr, 1, 7, bad_commit_for={4})
    good = _chain(vals, by_addr, 1, 7)
    pool = _FakePool(1, bad, good_blocks=good)
    r, applied, reports = _mk_reactor(monkeypatch, vals, pool,
                                      verify_window=8)
    run(_drain(r))
    # neighbors 1..3 applied BEFORE the redo; the refetched 4.. follow
    assert applied == [1, 2, 3, 4, 5, 6]
    assert pool.redone[:2] == [4, 5]
    assert ("peer-of-4", "bad_block") in reports


def test_accumulator_basics_failure_demux(monkeypatch):
    """A pre-dispatch failure (wrong block ID in a voucher commit) must
    not let unproven neighbors ride along: the prefix is re-proven
    separately, applies, and only the offending height redoes."""
    vals, by_addr = _vals([10] * 4)
    bad = _chain(vals, by_addr, 1, 6, wrong_bid_for={3})
    good = _chain(vals, by_addr, 1, 6)
    pool = _FakePool(1, bad, good_blocks=good)
    r, applied, reports = _mk_reactor(monkeypatch, vals, pool,
                                      verify_window=8)
    run(_drain(r))
    assert applied == [1, 2, 3, 4, 5]
    assert pool.redone[:2] == [3, 4]
    assert ("peer-of-3", "bad_block") in reports


def test_stage_window_double_buffers_disjoint_heights(monkeypatch):
    """The second buffer stages the blocks BEHIND the in-flight window —
    disjoint heights, no overlap, packed while the first verifies."""
    vals, by_addr = _vals([10] * 4)
    pool = _FakePool(1, _chain(vals, by_addr, 1, 12))
    r, _, _ = _mk_reactor(monkeypatch, vals, pool, verify_window=4)

    async def main():
        a = r._stage_window(0)
        b = r._stage_window(a.n_blocks)
        assert a.first_height == 1 and a.n_blocks == 4
        assert b.first_height == 5 and b.n_blocks == 4
        pa, ea = await a.task
        pb, eb = await b.task
        assert ea is None and eb is None
        assert [p[0].header.height for p in pa] == [1, 2, 3, 4]
        assert [p[0].header.height for p in pb] == [5, 6, 7, 8]
        return True

    assert run(main())


def test_verify_window_config_knob():
    from cometbft_tpu.config import Config, ConfigError

    cfg = Config()
    assert cfg.blocksync.verify_window == 32
    cfg.blocksync.verify_window = 1
    with pytest.raises(ConfigError):
        cfg.validate()
    cfg.blocksync.verify_window = 8192
    with pytest.raises(ConfigError):
        cfg.validate()
    cfg.blocksync.verify_window = 256
    cfg.validate()
