"""Config-driven statesync across OS processes: a late validator
bootstraps from a snapshot via statesync.enable + rpc_servers + trust
anchor, all through the CLI (reference: statesync config in
``config/config.toml`` + ``node/setup.go`` state provider wiring)."""

import asyncio
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.timeout(240)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 29060


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "cometbft_tpu", *args],
        capture_output=True, text=True, env=env, cwd=REPO)


def _spawn(base, i):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu",
         "--home", f"{base}/node{i}", "start"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO)


def test_statesync_via_cli_config(tmp_path):
    from cometbft_tpu.config import Config

    base = str(tmp_path / "net")
    res = _run_cli("testnet", "--v", "4", "--output-dir", base,
                   "--base-port", str(BASE_PORT), "--chain-id", "ss-cli")
    assert res.returncode == 0, res.stderr
    for i in range(4):
        cfgp = f"{base}/node{i}/config/config.toml"
        cfg = Config.load(cfgp)
        cfg.consensus.timeout_propose = 300_000_000
        cfg.consensus.timeout_prevote = 150_000_000
        cfg.consensus.timeout_precommit = 150_000_000
        cfg.consensus.timeout_commit = 100_000_000
        cfg.base.signature_backend = "cpu"
        cfg.save(cfgp)

    procs = {}
    try:
        for i in range(3):                      # node3 stays down
            procs[i] = _spawn(base, i)

        async def scenario():
            from cometbft_tpu.rpc import HTTPClient, RPCError

            cli0 = HTTPClient("127.0.0.1", BASE_PORT + 1)

            async def call(cli, method, timeout=60.0, **kw):
                deadline = time.monotonic() + timeout
                while True:
                    try:
                        return await cli.call(method, **kw)
                    except (OSError, RPCError, asyncio.TimeoutError):
                        if time.monotonic() > deadline:
                            raise
                        await asyncio.sleep(0.3)

            # history + app state on the running 3
            await call(cli0, "status")
            for i in range(3):
                await call(cli0, "broadcast_tx_sync",
                           tx=(b"ssk%d=ssv%d" % (i, i)).hex())
            deadline0 = time.monotonic() + 120
            while True:
                st = await call(cli0, "status")
                if st["sync_info"]["latest_block_height"] >= 8:
                    break
                assert time.monotonic() < deadline0, "chain stalled"
                await asyncio.sleep(0.3)

            # trust anchor out-of-band (operators do this via a block
            # explorer; here: the RPC of a node we already trust)
            blk = await call(cli0, "block", height=2)
            trust_hash = blk["block_id"]["hash"]["~b"]

            cfgp = f"{base}/node3/config/config.toml"
            cfg = Config.load(cfgp)
            cfg.statesync.enable = True
            cfg.statesync.rpc_servers = [
                f"tcp://127.0.0.1:{BASE_PORT + 1}"]
            cfg.statesync.trust_height = 2
            cfg.statesync.trust_hash = trust_hash
            cfg.save(cfgp)
            procs[3] = _spawn(base, 3)

            cli3 = HTTPClient("127.0.0.1", BASE_PORT + 7)
            st = await call(cli0, "status")
            target = st["sync_info"]["latest_block_height"] + 2
            deadline = time.monotonic() + 120
            while True:
                st3 = await call(cli3, "status", timeout=90)
                if st3["sync_info"]["latest_block_height"] >= target:
                    break
                assert time.monotonic() < deadline, \
                    f"statesync node stuck at {st3['sync_info']}"
                await asyncio.sleep(0.5)

            # compare a block node3 committed itself, post-snapshot (its
            # store has no blocks at/below the snapshot height — that is
            # the point of statesync).  h_check must sit ABOVE node3's
            # store base: when the restored snapshot is near the tip,
            # latest-1 can land on the snapshot height itself, which its
            # store never has by design (this raced as a rare flake).
            st3 = await call(cli3, "status")
            base3 = st3["sync_info"]["earliest_block_height"]
            deadline = time.monotonic() + 60
            while st3["sync_info"]["latest_block_height"] - 1 <= base3:
                assert time.monotonic() < deadline, \
                    f"node3 stopped committing past its statesync " \
                    f"base: {st3['sync_info']}"
                await asyncio.sleep(0.3)
                st3 = await call(cli3, "status")
            h_check = st3["sync_info"]["latest_block_height"] - 1
            b0 = await call(cli0, "block", height=h_check)
            b3 = await call(cli3, "block", height=h_check)
            assert b0["block_id"]["hash"] == b3["block_id"]["hash"]
            # and the snapshot-restored app serves state from history it
            # never executed
            q = await call(cli3, "abci_query", path="/key",
                           data=b"ssk0".hex())
            assert bytes.fromhex(q["response"]["value"]) == b"ssv0"

        asyncio.run(scenario())
    finally:
        for p in procs.values():
            p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
