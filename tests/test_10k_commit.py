"""10k-validator consensus-path test (VERDICT r4 next 9): one REAL
commit over a synthetic 10,000-validator set driven through the
production VerifyCommit dense path on the device route — the
cached-table gather + RLC dispatch (`crypto/batch.py`
device_verify_ed25519_cached) — capturing the p50 latency end to end,
not just in bench.py.  On the CPU-pinned test mesh the "device" is a
virtual CPU device, so this pins the code path and the latency
plumbing; the hardware number comes from ``BENCH_MODE=p50commit``."""

import json
import os
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.timeout(1800), pytest.mark.slow]

N_VALS = 10_000


@pytest.fixture(scope="module")
def big_chain():
    from cometbft_tpu.testing import make_light_chain

    t0 = time.perf_counter()
    chain = make_light_chain(1, n_vals=N_VALS, chain_id="big-chain")
    print(f"built {N_VALS}-val chain in {time.perf_counter() - t0:.1f}s")
    return chain[0]


def test_10k_validator_commit_verifies_on_device_route(big_chain):
    """The full 10k-signature commit verifies through the device
    dispatch (cached valset tables + RLC fast path), and a tampered
    signature is caught with its lane localized."""
    from cometbft_tpu.crypto import batch as cb
    from cometbft_tpu.types import validation as V

    lanes_before = _route_count(cb, "device_rlc")
    t0 = time.perf_counter()
    V.VerifyCommitLightAllSignatures(
        "big-chain", big_chain.validators, big_chain.commit.block_id,
        big_chain.height, big_chain.commit, backend="jax")
    cold_s = time.perf_counter() - t0

    # the RLC fast path carried lanes (the batch is all-valid)
    assert _route_count(cb, "device_rlc") > lanes_before

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        V.VerifyCommitLightAllSignatures(
            "big-chain", big_chain.validators, big_chain.commit.block_id,
            big_chain.height, big_chain.commit, backend="jax")
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[len(times) // 2]
    print(f"p50 VerifyCommit @{N_VALS} vals (virtual device route): "
          f"{p50 * 1e3:.1f} ms (cold {cold_s:.1f}s)")

    if os.environ.get("RECORD_ARTIFACTS"):
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "bench",
            "r05-p50commit-10k-virtual.json")
        with open(path, "w") as f:
            json.dump({"metric": "p50 VerifyCommit @10k vals, virtual "
                                 "CPU device route (code-path pin, not "
                                 "a hardware number)",
                       "p50_ms": round(p50 * 1e3, 2),
                       "cold_s": round(cold_s, 2)}, f, indent=1)


def test_10k_validator_commit_tampered_lane_localized(big_chain):
    import copy

    from cometbft_tpu.types import validation as V

    commit = copy.deepcopy(big_chain.commit)
    bad = 7777
    commit.signatures[bad].signature = bytes(64)
    with pytest.raises(V.ErrInvalidSignature) as exc:
        V.VerifyCommitLightAllSignatures(
            "big-chain", big_chain.validators, commit.block_id,
            big_chain.height, commit, backend="jax")
    assert exc.value.idx == bad


def _route_count(cb, route: str) -> float:
    """Sum of the crypto_batch_lanes_total counter for one route label."""
    _, lanes, _ = cb._metrics()
    total = 0.0
    for key, val in getattr(lanes, "_values", {}).items():
        if route in str(key):
            total += val
    return total
