"""E2E perturbations + latency emulation (reference:
``test/e2e/runner/perturb.go`` — disconnect/kill/pause/restart — and
``test/e2e/runner/latency_emulation.go``).

The pause perturbation uses real SIGSTOP/SIGCONT on a node OS process
(the in-one-machine analogue of ``docker pause``); the disconnect
perturbation drops every peer connection of a live in-proc node and
relies on persistent-peer reconnection.  After every perturbation the
network must stabilize: all nodes advance and agree on block hashes.
"""

import asyncio
import os
import signal
import subprocess
import sys
import time

import pytest

# Multi-node nets with live perturbations: minutes of wall clock on a
# small CPU box and timing-sensitive under load — tier-2 (the tier-1
# `-m 'not slow'` gate keeps the single-node + unit consensus coverage).
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 28860


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ------------------------------------------------- multi-process: pause

def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "cometbft_tpu", *args],
        capture_output=True, text=True, env=env, cwd=REPO)


def _patch_configs(base, n=4):
    from cometbft_tpu.config import Config

    for i in range(n):
        cfgp = f"{base}/node{i}/config/config.toml"
        cfg = Config.load(cfgp)
        cfg.consensus.timeout_propose = 300_000_000
        cfg.consensus.timeout_propose_delta = 100_000_000
        cfg.consensus.timeout_prevote = 150_000_000
        cfg.consensus.timeout_prevote_delta = 50_000_000
        cfg.consensus.timeout_precommit = 150_000_000
        cfg.consensus.timeout_precommit_delta = 50_000_000
        cfg.consensus.timeout_commit = 100_000_000
        cfg.base.signature_backend = "cpu"
        cfg.save(cfgp)


def _spawn(base, i):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu",
         "--home", f"{base}/node{i}", "start"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO)


async def _rpc_clients(n):
    from cometbft_tpu.rpc import HTTPClient, RPCError

    clients = [HTTPClient("127.0.0.1", BASE_PORT + 2 * i + 1)
               for i in range(n)]

    async def wait_rpc(cli, timeout=90.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                return await cli.call("status")
            except (OSError, RPCError, asyncio.TimeoutError):
                await asyncio.sleep(0.3)
        raise TimeoutError("rpc never came up")

    for cli in clients:
        await wait_rpc(cli)
    return clients


async def _wait_all_beyond(clients, h, timeout=90.0):
    from cometbft_tpu.rpc import RPCError

    deadline = time.monotonic() + timeout
    for cli in clients:
        while True:
            try:
                st = await cli.call("status")
                if st["sync_info"]["latest_block_height"] >= h:
                    break
            except (OSError, RPCError, asyncio.TimeoutError):
                pass
            assert time.monotonic() < deadline, f"stuck below {h}"
            await asyncio.sleep(0.3)


async def _assert_agreement(clients, h):
    hashes = set()
    for cli in clients:
        blk = await cli.call("block", height=h)
        hashes.add(blk["block_id"]["hash"]["~b"])
    assert len(hashes) == 1, f"fork at {h}: {hashes}"


def test_pause_resume_node_sigstop(tmp_path):
    """SIGSTOP a validator for several blocks; the other 3 keep the chain
    live (>2/3), and after SIGCONT the paused node catches up and agrees."""
    base = str(tmp_path / "net")
    res = _run_cli("testnet", "--v", "4", "--output-dir", base,
                   "--base-port", str(BASE_PORT), "--chain-id", "pause-net")
    assert res.returncode == 0, res.stderr
    _patch_configs(base)
    procs = [_spawn(base, i) for i in range(4)]
    try:
        async def scenario():
            clients = await _rpc_clients(4)
            await _wait_all_beyond(clients, 3)

            # pause node3 (docker-pause analogue)
            procs[3].send_signal(signal.SIGSTOP)
            st = await clients[0].call("status")
            h0 = st["sync_info"]["latest_block_height"]
            # chain stays live without it
            await _wait_all_beyond(clients[:3], h0 + 4)

            procs[3].send_signal(signal.SIGCONT)
            st = await clients[0].call("status")
            target = st["sync_info"]["latest_block_height"] + 2
            # resumed node catches up to the moving tip
            await _wait_all_beyond(clients, target, timeout=120)
            await _assert_agreement(clients, target)

        run(scenario())
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGCONT)
            except ProcessLookupError:
                pass
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


# ----------------------------------------- in-proc: disconnect + latency

def _genesis(n, chain_id):
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    pvs = [MockPV.from_secret(b"pert%d" % i) for i in range(n)]
    doc = GenesisDoc(chain_id=chain_id,
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])
    return doc, pvs


async def _make_net(n, chain_id, latency_ms=0.0):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey

    doc, pvs = _genesis(n, chain_id)
    nodes = []
    for i in range(n):
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""
        cfg.p2p.emulated_latency_ms = latency_ms
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pvs[i], config=cfg,
            node_key=NodeKey.from_secret(b"pk%d" % i), name=f"pert{i}")
        nodes.append(node)
    for node in nodes:
        await node.start()
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            await a.dial_peer(b.listen_addr, persistent=True)
    return nodes


async def _wait_height(nodes, h, timeout=120.0):
    deadline = time.monotonic() + timeout
    while not all(n.height() >= h for n in nodes):
        assert time.monotonic() < deadline, \
            f"heights {[n.height() for n in nodes]} stuck below {h}"
        await asyncio.sleep(0.05)


def test_disconnect_perturbation():
    """Dropping every peer connection of one node mid-run: persistent-peer
    reconnection restores it and the chain continues fork-free."""

    async def main():
        nodes = await _make_net(4, "disc-net")
        try:
            await _wait_height(nodes, 3)
            victim = nodes[2]
            for peer in list(victim.switch.peers.values()):
                await victim.switch.stop_peer_for_error(
                    peer, RuntimeError("perturbation: disconnect"))
            h0 = max(n.height() for n in nodes)
            await _wait_height(nodes, h0 + 5)
            for h in range(1, h0 + 5):
                hashes = {n.block_store.load_block(h).hash() for n in nodes
                          if n.block_store.load_block(h) is not None}
                assert len(hashes) == 1, f"fork at {h}"
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())


def test_latency_emulation_liveness():
    """With 60 ms emulated one-way latency (WAN-ish), a 4-node net keeps
    committing; latency shows up as slower blocks, not forks — the
    reference QA observes the same (rounds rise, liveness holds)."""

    async def main():
        nodes = await _make_net(4, "lat-net", latency_ms=60.0)
        try:
            t0 = time.monotonic()
            await _wait_height(nodes, 5)
            elapsed = time.monotonic() - t0
            for h in range(1, 6):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"fork at {h}"
            # sanity: latency actually took effect on the wire
            assert all(
                any(getattr(p.mconn, "emulated_latency", 0) == 0.06
                    for p in n.switch.peers.values())
                for n in nodes if n.switch.peers)
            return elapsed
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass

    elapsed = run(main())
    assert elapsed is not None
