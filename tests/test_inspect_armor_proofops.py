"""Inspect mode, key armor, merkle proof operators (reference:
``internal/inspect/inspect_test.go``, ``crypto/armor/armor_test.go``,
``crypto/merkle/proof_op.go`` tests)."""

import asyncio

import pytest

from cometbft_tpu.crypto.armor import (ArmorError, armor_priv_key,
                                       decode_armor, encode_armor,
                                       unarmor_priv_key)
from cometbft_tpu.crypto.merkle import (Proof, ProofOp, ProofOpError,
                                        ProofOperators, ValueOp, kv_leaf,
                                        proofs_from_byte_slices)

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ------------------------------------------------------------------ armor

def test_armor_roundtrip():
    data = bytes(range(256)) * 3
    text = encode_armor("TEST BLOCK", {"Version": "1", "kdf": "none"}, data)
    assert text.startswith("-----BEGIN TEST BLOCK-----")
    bt, headers, out = decode_armor(text)
    assert bt == "TEST BLOCK" and out == data
    assert headers["Version"] == "1"


def test_armor_detects_corruption():
    text = encode_armor("T", {}, b"payload-bytes-here")
    # flip a character inside the base64 body
    lines = text.splitlines()
    body_idx = next(i for i, ln in enumerate(lines)
                    if ln and not ln.startswith("-") and ":" not in ln)
    ch = "A" if lines[body_idx][0] != "A" else "B"
    lines[body_idx] = ch + lines[body_idx][1:]
    with pytest.raises(ArmorError):
        decode_armor("\n".join(lines))


def test_priv_key_armor():
    from cometbft_tpu.crypto.keys import Ed25519PrivKey

    sk = Ed25519PrivKey.from_secret(b"armored")
    text = armor_priv_key(sk.bytes(), "ed25519")
    raw, typ = unarmor_priv_key(text)
    assert raw == sk.bytes() and typ == "ed25519"


# -------------------------------------------------------------- proof ops

def test_value_op_proves_item_under_root():
    # a provable KV store hashes kv_leaf(key, value) entries
    kvs = [(b"a-key", b"alpha"), (b"b-key", b"beta"),
           (b"c-key", b"gamma"), (b"d-key", b"delta")]
    root, proofs = proofs_from_byte_slices(
        [kv_leaf(k, v) for k, v in kvs])
    op = ValueOp(b"b-key", proofs[1])
    ops = ProofOperators([op])
    ops.verify(root, [b"b-key"], b"beta")
    with pytest.raises(ProofOpError):
        ops.verify(root, [b"b-key"], b"gamma")          # wrong value
    with pytest.raises(ProofOpError):
        ops.verify(b"\x00" * 32, [b"b-key"], b"beta")   # wrong root
    with pytest.raises(ProofOpError):
        ops.verify(root, [b"other-key"], b"beta")       # wrong keypath


def test_value_op_key_is_bound_into_leaf():
    """A prover cannot relabel a proven value under a different key: the
    key participates in the leaf hash."""
    kvs = [(b"user", b"42"), (b"admin", b"1")]
    root, proofs = proofs_from_byte_slices(
        [kv_leaf(k, v) for k, v in kvs])
    forged = ValueOp(b"admin", proofs[0])    # user's proof, admin's key
    with pytest.raises(ProofOpError):
        ProofOperators([forged]).verify(root, [b"admin"], b"42")


def test_proof_op_wire_roundtrip_and_registry():
    root, proofs = proofs_from_byte_slices(
        [kv_leaf(b"k", b"x"), kv_leaf(b"j", b"y")])
    wire: ProofOp = ValueOp(b"k", proofs[0]).proof_op()
    assert wire.type == ValueOp.TYPE
    ops = ProofOperators.decode([wire])
    ops.verify(root, [b"k"], b"x")
    bad = ProofOp("unknown:op", b"", b"")
    with pytest.raises(ProofOpError):
        ProofOperators.decode([bad])
    with pytest.raises(ProofOpError):
        ProofOperators([]).verify(root, [], root)       # empty chain


# -------------------------------------------------------------- inspect

def test_inspect_serves_chain_data_from_dead_node_dir(tmp_path):
    """Run a node, stop it ('crash'), then read its blocks/validators/txs
    through the inspect RPC server."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config
    from cometbft_tpu.config import test_consensus_config as _tcc
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.rpc import HTTPClient, RPCError
    from cometbft_tpu.rpc.inspect import run_inspect
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    def cfg():
        c = Config(consensus=_tcc())
        c.p2p.laddr = "tcp://127.0.0.1:0"
        c.rpc.laddr = "tcp://127.0.0.1:0"
        return c

    async def main():
        pvs = [MockPV.from_secret(b"ins%d" % i) for i in range(3)]
        doc = GenesisDoc(chain_id="ins-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            n = await Node.create(doc, KVStoreApplication(),
                                  priv_validator=pv, config=cfg(),
                                  node_key=NodeKey.from_secret(b"ik%d" % i),
                                  home=str(tmp_path / f"n{i}"),
                                  name=f"ins{i}")
            nodes.append(n)
            await n.start()
        for i, a in enumerate(nodes):
            for b in nodes[i + 1:]:
                await a.dial_peer(b.listen_addr, persistent=True)
        cli0 = HTTPClient(*nodes[0].rpc_addr)
        res = await cli0.call("broadcast_tx_commit", tx=b"ik=iv".hex())
        committed_h = res["height"]
        txh = res["hash"]
        while nodes[0].height() < committed_h + 1:
            await asyncio.sleep(0.05)
        for n in nodes:
            await n.stop()

        # node is dead: inspect its data dir
        server, addr = await run_inspect(str(tmp_path / "n0"), cfg(), doc)
        try:
            cli = HTTPClient(*addr)
            st = await cli.call("status")
            assert st["sync_info"]["latest_block_height"] >= committed_h
            blk = await cli.call("block", height=committed_h)
            assert blk["block"]["hdr"]["h"] == committed_h
            vals = await cli.call("validators")
            assert vals["total"] == 3
            tx = await cli.call("tx", hash=txh)
            assert tx["height"] == committed_h
            # live-only routes answer with an error, not a hang
            with pytest.raises(RPCError):
                await cli.call("broadcast_tx_sync", tx=b"zz".hex())
        finally:
            await server.close()
        return True

    assert run(main())


def test_kvstore_proof_cache_invalidated_on_value_change():
    """A proven query after a same-key value update must prove the NEW
    value against the NEW app hash: the proof cache is invalidated on
    every state mutation (it used to be keyed only on key PRESENCE, so a
    changed value could in principle have served a stale proof)."""
    import asyncio

    from cometbft_tpu.abci import types as t
    from cometbft_tpu.abci.kvstore import KVStoreApplication

    async def main():
        app = KVStoreApplication()

        async def commit_kv(height, k, v):
            await app.finalize_block(t.FinalizeBlockRequest(
                txs=[k + b"=" + v], height=height, time_ns=0))

        await commit_kv(1, b"alpha", b"one")
        r1 = await app.query("", b"alpha", 0, True)
        op1 = ProofOperators.decode([ProofOp(**r1.proof_ops[0])])
        op1.verify(app.app_hash, [b"alpha"], b"one")
        hash1 = app.app_hash

        await commit_kv(2, b"alpha", b"two")     # same key, new value
        assert app.app_hash != hash1
        r2 = await app.query("", b"alpha", 0, True)
        assert r2.value == b"two"
        op2 = ProofOperators.decode([ProofOp(**r2.proof_ops[0])])
        op2.verify(app.app_hash, [b"alpha"], b"two")
        with pytest.raises(ProofOpError):       # stale proof must fail
            op1.verify(app.app_hash, [b"alpha"], b"one")
        return True

    assert asyncio.run(main())
