"""ChaCha20-Poly1305 fallback engines: RFC 8439 vectors + native parity.

The p2p SecretConnection's no-`cryptography` fallback has two engines
(native C via the on-demand g++ build, pure Python as last resort);
both must produce RFC 8439 output bit-exactly, and the class must
route through the native one when it builds.
"""

import random

import pytest

from cometbft_tpu.crypto import _sc_fallback as sc

KEY = bytes(range(0x80, 0xA0))
NONCE = bytes.fromhex("070000004041424344454647")
AAD = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
PT = (b"Ladies and Gentlemen of the class of '99: If I could offer you "
      b"only one tip for the future, sunscreen would be it.")
CT = bytes.fromhex(
    "d31a8d34648e60db7b86afbc53ef7ec2a4aded51296e08fea9e2b5a736ee62d6"
    "3dbea45e8ca9671282fafb69da92728b1a71de0a9e060b2905d6a5b67ecd3b36"
    "92ddbd7f2d778b8c9803aee328091b58fab324e4fad675945585808b4831d7bc"
    "3ff4def08e4b7a9de576d26586cec64b6116"
    "1ae10b594f09e26a7e902ecbd0600691")           # ciphertext || tag


def _py_only(key):
    """The pure-Python engine regardless of the native build."""
    a = sc.ChaCha20Poly1305(key)
    a._lib = None
    return a


def test_rfc8439_vector_both_engines():
    for aead in (sc.ChaCha20Poly1305(KEY), _py_only(KEY)):
        assert aead.encrypt(NONCE, PT, AAD) == CT
        assert aead.decrypt(NONCE, CT, AAD) == PT
        bad = bytearray(CT)
        bad[5] ^= 1
        with pytest.raises(sc.InvalidTag):
            aead.decrypt(NONCE, bytes(bad), AAD)


def test_native_engine_builds_and_is_preferred():
    assert sc._native_aead() is not None, \
        "on-demand g++ AEAD build must work on this image"
    assert sc.ChaCha20Poly1305(KEY)._lib is not None


def test_native_matches_python_across_sizes():
    rng = random.Random(7)
    nat, py = sc.ChaCha20Poly1305(KEY), _py_only(KEY)
    if nat._lib is None:
        pytest.skip("native AEAD unavailable")
    for n in [0, 1, 15, 16, 17, 63, 64, 65, 255, 1024, 1040]:
        msg = bytes(rng.randrange(256) for _ in range(n))
        nonce = bytes(rng.randrange(256) for _ in range(12))
        aad = bytes(rng.randrange(256)
                    for _ in range(rng.choice([0, 5, 16, 33])))
        ct = nat.encrypt(nonce, msg, aad)
        assert ct == py.encrypt(nonce, msg, aad), n
        assert nat.decrypt(nonce, ct, aad) == msg
        assert py.decrypt(nonce, ct, aad) == msg
        # aad participates in the tag
        if aad:
            with pytest.raises(sc.InvalidTag):
                nat.decrypt(nonce, ct, aad[:-1])
