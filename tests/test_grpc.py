"""gRPC transports: ABCI over gRPC (reference ``abci/client/grpc_client.go``)
and the node gRPC services (reference ``rpc/grpc/server/services/``)."""

import asyncio

import pytest

from cometbft_tpu.abci import FinalizeBlockRequest
from cometbft_tpu.abci.grpc import GRPCABCIServer, GRPCClient
from cometbft_tpu.abci.kvstore import KVStoreApplication


def run(coro):
    loop = asyncio.get_event_loop_policy().new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_abci_grpc_roundtrip():
    async def main():
        app = KVStoreApplication()
        server = GRPCABCIServer(app, port=0)
        await server.start()
        client = await GRPCClient.connect(port=server.port)
        assert (await client.echo("hello")) == "hello"
        assert (await client.info()).data == "kvstore"
        fin = await client.finalize_block(FinalizeBlockRequest(
            txs=[b"k=v"], height=1, time_ns=0, misbehavior=[]))
        assert fin.tx_results[0].is_ok and fin.app_hash == app.app_hash
        # HTTP/2 multiplexing: concurrent calls resolve correctly
        results = await asyncio.gather(*[client.query("/k", b"k", 0, False)
                                         for _ in range(10)])
        assert all(r.value == b"v" for r in results)
        await client.close()
        await server.stop()
        return True

    assert run(main())


def test_abci_grpc_app_error_propagates():
    from cometbft_tpu.abci.client import ABCIClientError

    class Exploding(KVStoreApplication):
        async def info(self):
            raise RuntimeError("boom")

    async def main():
        server = GRPCABCIServer(Exploding(), port=0)
        await server.start()
        client = await GRPCClient.connect(port=server.port)
        with pytest.raises(ABCIClientError, match="boom"):
            await client.info()
        await client.close()
        await server.stop()
        return True

    assert run(main())


def _one_node_config():
    from cometbft_tpu.config import Config, test_consensus_config

    cfg = Config(consensus=test_consensus_config())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = ""
    return cfg


async def _start_single_node(cfg=None, app=...):
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    pv = MockPV.from_secret(b"grpcnode0")
    doc = GenesisDoc(chain_id="grpc-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])
    node = await Node.create(
        doc, KVStoreApplication() if app is ... else app,
        priv_validator=pv, config=cfg or _one_node_config(),
        node_key=NodeKey.from_secret(b"gnk0"), name="gnode0")
    await node.start()
    return node


async def _wait_height(node, h, timeout=60.0):
    while node.height() < h:
        await asyncio.sleep(0.02)


def test_node_grpc_services():
    """Version/block/block-results/pruning services + the latest-height
    stream against a live single-validator node."""
    from cometbft_tpu.rpc.grpc import GRPCServer, GRPCServicesClient

    async def main():
        cfg = _one_node_config()
        cfg.rpc.grpc_laddr = "tcp://127.0.0.1:0"
        node = await _start_single_node(cfg)
        try:
            gs = node.grpc_server
            assert gs is not None
            client = await GRPCServicesClient.connect("127.0.0.1", gs.port)
            await asyncio.wait_for(_wait_height(node, 2), 60)
            ver = await client.get_version()
            assert ver["abci"] == "2.0.0"
            blk = await client.get_block_by_height()
            assert blk["block"]["hdr"]["h"] >= 1
            res = await client.get_block_results(height=1)
            assert res["height"] == 1
            out = await client.set_block_retain_height(1)
            assert out["data_companion_retain_height"] == 1
            got = await client.get_block_retain_height()
            assert got["pruning_service_retain_height"] == 1

            heights = []

            async def consume():
                async for h in client.latest_height_stream():
                    heights.append(h["height"])
                    if len(heights) >= 2:
                        return

            await asyncio.wait_for(consume(), timeout=30)
            assert heights[0] >= 1
            await client.close()
        finally:
            await node.stop()
        return True

    assert run(main())


def test_node_over_socket_app():
    """Same full-node flow over the ABCI socket transport — exercises the
    Commit/ExtendedCommit trees through the shared frame codec (these ride
    in PrepareProposal.local_last_commit every height > 1)."""
    from cometbft_tpu.abci.server import ABCIServer

    async def main():
        app = KVStoreApplication()
        server = ABCIServer(app, port=0)
        await server.start()
        cfg = _one_node_config()
        cfg.base.abci = "socket"
        cfg.base.proxy_app = f"tcp://127.0.0.1:{server.port}"
        node = await _start_single_node(cfg, app=None)
        try:
            await asyncio.wait_for(_wait_height(node, 3), 60)
        finally:
            await node.stop()
        await server.stop()
        return True

    assert run(main())


def test_node_over_grpc_app():
    """A node driven by an out-of-process app over the gRPC ABCI
    transport commits blocks (reference e2e grpc manifest config)."""

    async def main():
        app = KVStoreApplication()
        server = GRPCABCIServer(app, port=0)
        await server.start()
        cfg = _one_node_config()
        cfg.base.abci = "grpc"
        cfg.base.proxy_app = f"tcp://127.0.0.1:{server.port}"
        node = await _start_single_node(cfg, app=None)
        try:
            await asyncio.wait_for(_wait_height(node, 2), 60)
        finally:
            await node.stop()
        await server.stop()
        return True

    assert run(main())
