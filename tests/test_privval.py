"""privval: FilePV double-sign protection + remote signer
(reference: ``privval/file_test.go``, ``privval/signer_client_test.go``)."""

import asyncio

import pytest

from cometbft_tpu.privval import (DoubleSignError, FilePV, RemoteSignerError,
                                  SignerClient, SignerServer)
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.vote import (PRECOMMIT_TYPE, PREVOTE_TYPE, Proposal,
                                     Vote)

pytestmark = pytest.mark.timeout(60)

CHAIN = "pv-chain"


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _vote(pv, typ=PREVOTE_TYPE, height=5, round_=0, bid=None, ts=1_000):
    return Vote(type=typ, height=height, round=round_,
                block_id=bid if bid is not None else
                BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xbb" * 32)),
                timestamp_ns=ts,
                validator_address=pv.get_pub_key().address(),
                validator_index=0)


def _pv(tmp_path):
    return FilePV.generate(str(tmp_path / "key.json"),
                           str(tmp_path / "state.json"))


def test_filepv_signs_and_persists(tmp_path):
    pv = _pv(tmp_path)
    v = _vote(pv)

    async def main():
        await pv.sign_vote(CHAIN, v, sign_extension=False)
        assert pv.get_pub_key().verify_signature(v.sign_bytes(CHAIN),
                                                 v.signature)
        # reload from disk: state survives
        pv2 = FilePV.load(str(tmp_path / "key.json"),
                          str(tmp_path / "state.json"))
        assert (pv2.height, pv2.round, pv2.step) == (5, 0, 2)
        assert pv2.signature == v.signature
        return True

    assert run(main())


def test_filepv_same_vote_returns_same_signature(tmp_path):
    pv = _pv(tmp_path)

    async def main():
        v1 = _vote(pv)
        await pv.sign_vote(CHAIN, v1, sign_extension=False)
        v2 = _vote(pv)
        await pv.sign_vote(CHAIN, v2, sign_extension=False)
        assert v2.signature == v1.signature
        return True

    assert run(main())


def test_filepv_timestamp_only_change_reuses_signature(tmp_path):
    pv = _pv(tmp_path)

    async def main():
        v1 = _vote(pv, ts=1_000)
        await pv.sign_vote(CHAIN, v1, sign_extension=False)
        v2 = _vote(pv, ts=9_999)
        await pv.sign_vote(CHAIN, v2, sign_extension=False)
        # stored timestamp + stored signature come back
        assert v2.timestamp_ns == 1_000
        assert v2.signature == v1.signature
        return True

    assert run(main())


def test_filepv_refuses_conflicting_vote(tmp_path):
    pv = _pv(tmp_path)

    async def main():
        await pv.sign_vote(CHAIN, _vote(pv), sign_extension=False)
        other = _vote(pv, bid=BlockID(b"\xcc" * 32,
                                      PartSetHeader(1, b"\xdd" * 32)))
        with pytest.raises(DoubleSignError):
            await pv.sign_vote(CHAIN, other, sign_extension=False)
        return True

    assert run(main())


def test_filepv_refuses_hrs_regression(tmp_path):
    pv = _pv(tmp_path)

    async def main():
        await pv.sign_vote(CHAIN, _vote(pv, typ=PRECOMMIT_TYPE, height=5,
                                        round_=2), sign_extension=False)
        # lower height
        with pytest.raises(DoubleSignError):
            await pv.sign_vote(CHAIN, _vote(pv, height=4),
                               sign_extension=False)
        # same height, lower round
        with pytest.raises(DoubleSignError):
            await pv.sign_vote(CHAIN, _vote(pv, height=5, round_=1),
                               sign_extension=False)
        # same height+round, earlier step (prevote after precommit)
        with pytest.raises(DoubleSignError):
            await pv.sign_vote(CHAIN, _vote(pv, typ=PREVOTE_TYPE, height=5,
                                            round_=2), sign_extension=False)
        return True

    assert run(main())


def test_filepv_survives_restart_no_double_sign(tmp_path):
    """Crash after signing: the restarted signer refuses to equivocate
    (VERDICT item 6's bar)."""
    pv = _pv(tmp_path)

    async def main():
        await pv.sign_vote(CHAIN, _vote(pv, typ=PRECOMMIT_TYPE),
                           sign_extension=False)
        # "crash" - reload from disk
        pv2 = FilePV.load(str(tmp_path / "key.json"),
                          str(tmp_path / "state.json"))
        conflicting = _vote(pv2, typ=PRECOMMIT_TYPE,
                            bid=BlockID(b"\xcc" * 32,
                                        PartSetHeader(1, b"\xdd" * 32)))
        with pytest.raises(DoubleSignError):
            await pv2.sign_vote(CHAIN, conflicting, sign_extension=False)
        return True

    assert run(main())


def test_filepv_proposal(tmp_path):
    pv = _pv(tmp_path)

    async def main():
        p = Proposal(height=7, round=0, pol_round=-1,
                     block_id=BlockID(b"\xaa" * 32,
                                      PartSetHeader(1, b"\xbb" * 32)),
                     timestamp_ns=123)
        await pv.sign_proposal(CHAIN, p)
        assert pv.get_pub_key().verify_signature(p.sign_bytes(CHAIN),
                                                 p.signature)
        # signing a vote at the same height/round is fine (step forward)
        await pv.sign_vote(CHAIN, _vote(pv, height=7), sign_extension=False)
        # but another different proposal at the same HRS is refused
        p2 = Proposal(height=7, round=0, pol_round=-1,
                      block_id=BlockID(b"\xcc" * 32,
                                       PartSetHeader(1, b"\xdd" * 32)),
                      timestamp_ns=123)
        with pytest.raises(DoubleSignError):
            await pv.sign_proposal(CHAIN, p2)
        return True

    assert run(main())


def test_remote_signer_roundtrip(tmp_path):
    """SignerServer serves a FilePV over TCP; SignerClient signs through it
    and double-sign refusals surface as RemoteSignerError."""
    pv = _pv(tmp_path)

    async def main():
        server = SignerServer(pv)
        host, port = await server.listen()
        client = await SignerClient.connect(host, port)
        try:
            assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()
            await client.ping()
            v = _vote(client)
            await client.sign_vote(CHAIN, v, sign_extension=False)
            assert client.get_pub_key().verify_signature(
                v.sign_bytes(CHAIN), v.signature)
            conflicting = _vote(client,
                                bid=BlockID(b"\xcc" * 32,
                                            PartSetHeader(1, b"\xdd" * 32)))
            with pytest.raises(RemoteSignerError):
                await client.sign_vote(CHAIN, conflicting,
                                       sign_extension=False)
        finally:
            await client.close()
            await server.close()
        return True

    assert run(main())


def test_signer_listener_dialer_topology(tmp_path):
    """Reference direction (privval/signer_listener_endpoint.go): the node
    listens on priv_validator_laddr, the remote signer dials in and serves
    the key over the dialed connection."""
    from cometbft_tpu.privval.signer import SignerListener, serve_dialer

    pv = _pv(tmp_path)

    async def main():
        listener = SignerListener()
        host, port = await listener.listen()
        dial_task = asyncio.create_task(
            serve_dialer(pv, host, port, max_retries=5))
        try:
            await listener.wait_for_signer(timeout=10)
            assert listener.get_pub_key().bytes() == pv.get_pub_key().bytes()
            await listener.ping()
            v = _vote(listener)
            await listener.sign_vote(CHAIN, v, sign_extension=False)
            assert listener.get_pub_key().verify_signature(
                v.sign_bytes(CHAIN), v.signature)

            # signer restart: the node re-accepts the redial and keeps
            # signing (privval/signer_listener_endpoint.go semantics)
            dial_task.cancel()
            await asyncio.sleep(0)
            dial_task = asyncio.create_task(
                serve_dialer(pv, host, port, max_retries=5))
            v2 = _vote(listener, height=6)
            await listener.sign_vote(CHAIN, v2, sign_extension=False)
            assert listener.get_pub_key().verify_signature(
                v2.sign_bytes(CHAIN), v2.signature)
        finally:
            await listener.close()
            dial_task.cancel()
        return True

    assert run(main())


def test_consensus_runs_on_filepv(tmp_path):
    """The in-proc network commits with FilePV signers: double-sign
    protection is compatible with the live state machine."""
    from cometbft_tpu.testing import make_inproc_network

    async def main():
        def pv_factory(i):
            return FilePV.generate(str(tmp_path / f"k{i}.json"),
                                   str(tmp_path / f"s{i}.json"))

        net = await make_inproc_network(4, pv_factory=pv_factory)
        try:
            await net.start()
            await net.wait_for_height(3, timeout=60)
            hashes = {n.block_store.load_block(3).hash() for n in net.nodes}
            assert len(hashes) == 1
        finally:
            await net.stop()
        return True

    assert run(main())


def test_filepv_secp256k1_key_type(tmp_path):
    """FilePV with a secp256k1 validator key round-trips through the key
    file and signs votes (reference gen-validator --key-type)."""
    from cometbft_tpu.privval import FilePV

    kp, sp = str(tmp_path / "key.json"), str(tmp_path / "state.json")
    pv = FilePV.generate(kp, sp, key_type="secp256k1")
    assert pv.get_pub_key().type() == "secp256k1"
    pv2 = FilePV.load(kp, sp)
    assert pv2.get_pub_key() == pv.get_pub_key()
    # legacy key files without a type field still load as ed25519
    import json as _json

    pv3 = FilePV.generate(str(tmp_path / "k3.json"),
                          str(tmp_path / "s3.json"))
    with open(str(tmp_path / "k3.json")) as f:
        kd = _json.load(f)
    kd.pop("type")
    with open(str(tmp_path / "k3.json"), "w") as f:
        _json.dump(kd, f)
    pv4 = FilePV.load(str(tmp_path / "k3.json"), str(tmp_path / "s3.json"))
    assert pv4.get_pub_key().type() == "ed25519"


def test_filepv_bls_key_roundtrip_and_pop(tmp_path):
    """FilePV with a bls12_381 key persists the proof of possession
    beside the key (the rogue-key gate the aggregate fast path rests
    on) and round-trips both through the key file."""
    import json as _json

    from cometbft_tpu.crypto import bls12381 as _bls
    from cometbft_tpu.privval import FilePV

    kp, sp = str(tmp_path / "key.json"), str(tmp_path / "state.json")
    pv = FilePV.generate(kp, sp, key_type="bls12_381")
    pub = pv.get_pub_key()
    assert pub.type() == "bls12_381"
    assert len(pub.bytes()) == 48

    with open(kp) as f:
        kd = _json.load(f)
    assert kd["type"] == "bls12_381"
    stored_pop = bytes.fromhex(kd["pop"])
    assert _bls.pop_verify(pub.bytes(), stored_pop)
    # the proof is bound to THIS key, not transferable to another
    other = FilePV.generate(str(tmp_path / "k2.json"),
                            str(tmp_path / "s2.json"),
                            key_type="bls12_381")
    assert not _bls.pop_verify(other.get_pub_key().bytes(), stored_pop)

    pv2 = FilePV.load(kp, sp)
    assert pv2.get_pub_key() == pub
    assert pv2.pop() == stored_pop


def test_filepv_bls_signs_aggregation_domain(tmp_path):
    """A BLS FilePV signs votes in the zero-timestamp aggregation domain
    (Vote.sign_bytes_for) — NOT the reference timestamped encoding — so
    its precommits can fold into an aggregate commit."""
    from cometbft_tpu.privval import FilePV

    pv = FilePV.generate(str(tmp_path / "key.json"),
                         str(tmp_path / "state.json"),
                         key_type="bls12_381")
    v = _vote(pv, typ=PRECOMMIT_TYPE, ts=1_000)

    async def main():
        await pv.sign_vote(CHAIN, v, sign_extension=False)
        pub = pv.get_pub_key()
        assert len(v.signature) == 96
        assert pub.verify_signature(
            v.sign_bytes_for(CHAIN, "bls12_381"), v.signature)
        # the timestamped reference encoding is a DIFFERENT message —
        # the signature must not transfer across the domain split
        assert v.sign_bytes(CHAIN) != v.sign_bytes_for(CHAIN, "bls12_381")
        assert not pub.verify_signature(v.sign_bytes(CHAIN), v.signature)
        # double-sign protection still holds in the BLS domain
        other = _vote(pv, typ=PRECOMMIT_TYPE,
                      bid=BlockID(b"\xcc" * 32,
                                  PartSetHeader(1, b"\xdd" * 32)))
        with pytest.raises(DoubleSignError):
            await pv.sign_vote(CHAIN, other, sign_extension=False)
        return True

    assert run(main())


# ----------------------------------------------- sign-state hardening


def test_filepv_corrupt_state_file_raises_typed_error(tmp_path):
    """A corrupt/truncated last-sign-state file must be a typed
    SignStateError carrying the never-auto-reset warning, not a raw
    JSONDecodeError an operator might "fix" with a reset."""
    from cometbft_tpu.privval import SignStateError

    pv = _pv(tmp_path)
    run(pv.sign_vote(CHAIN, _vote(pv), sign_extension=False))
    sp = str(tmp_path / "state.json")
    for payload in ("{not json", "", '{"height": 5, "round": 0}',
                    '{"height": "nan", "round": 0, "step": 2}'):
        with open(sp, "w") as f:
            f.write(payload)
        if payload == "":
            # empty file is still "exists": must refuse, not silently
            # start from a zeroed state
            pass
        with pytest.raises(SignStateError) as ei:
            FilePV.load(str(tmp_path / "key.json"), sp)
        assert "double-sign" in str(ei.value)


def test_privval_state_fsync_eio_withholds_signature(tmp_path):
    """The privval.state.fsync.eio chaos site: a failed sign-state
    persist must NOT release the signature, and the handle goes dead
    (every further sign refuses) — the privval fsyncgate."""
    import errno

    from cometbft_tpu.libs import failures as F
    from cometbft_tpu.privval import SignStateError

    pv = _pv(tmp_path)
    F.configure(enabled=True, seed=3,
                faults=["privval.state.fsync.eio:at=1"])
    try:
        v = _vote(pv)
        with pytest.raises(OSError) as ei:
            run(pv.sign_vote(CHAIN, v, sign_extension=False))
        assert ei.value.errno == errno.EIO
        assert v.signature == b""          # never released
        # dead handle: even with the fault disarmed, no further signing
        F.reset()
        with pytest.raises(SignStateError):
            run(pv.sign_vote(CHAIN, _vote(pv, height=6),
                             sign_extension=False))
        # restart (reload from disk) recovers; the pre-failure state
        # file is intact, so double-sign protection still holds
        pv2 = FilePV.load(str(tmp_path / "key.json"),
                          str(tmp_path / "state.json"))
        v2 = _vote(pv2, height=6)
        run(pv2.sign_vote(CHAIN, v2, sign_extension=False))
        assert v2.signature
    finally:
        F.reset()


# ------------------------------------------------- signer liveness


def test_signer_client_round_trip_times_out(tmp_path):
    """signer.round_trip.hang chaos site: a wedged signer trips the
    deadline with a typed SignerTimeoutError + counter instead of
    blocking forever."""
    from cometbft_tpu.libs import failures as F
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.privval import SignerTimeoutError

    pv = _pv(tmp_path)

    async def main():
        F.configure(enabled=True, seed=7,
                    faults=["signer.round_trip.hang:at=1:delay=30"])
        server = SignerServer(pv)
        host, port = await server.listen()
        client = await SignerClient.connect(host, port, timeout_s=0.3)
        before = m.counter("privval_signer_timeouts_total").value()
        try:
            with pytest.raises(SignerTimeoutError):
                await client.sign_vote(CHAIN, _vote(client),
                                       sign_extension=False)
            assert m.counter("privval_signer_timeouts_total").value() \
                == before + 1
            # at=1 exhausted: the next round trip answers (the stream
            # is in an undefined frame state after an abandoned
            # request, so reconnect first like the listener does)
            client2 = await SignerClient.connect(host, port, timeout_s=5)
            v = _vote(client2)
            await client2.sign_vote(CHAIN, v, sign_extension=False)
            assert client2.get_pub_key().verify_signature(
                v.sign_bytes(CHAIN), v.signature)
            await client2.close()
        finally:
            await client.close()
            await server.close()
            F.reset()
        return True

    assert run(main())


def test_signer_listener_timeout_reconnects_and_retries(tmp_path):
    """A hung round trip through the SignerListener behaves exactly
    like a dropped connection: close + re-accept the signer's redial +
    retry once — consensus sees a signed vote, not a wedge."""
    from cometbft_tpu.libs import failures as F
    from cometbft_tpu.privval.signer import SignerListener, serve_dialer

    pv = _pv(tmp_path)

    async def main():
        F.configure(enabled=True, seed=7,
                    faults=["signer.round_trip.hang:at=1:delay=30"])
        listener = SignerListener(timeout_s=0.3)
        host, port = await listener.listen()
        dial_task = asyncio.create_task(
            serve_dialer(pv, host, port, max_retries=50,
                         retry_interval=0.05))
        try:
            await listener.wait_for_signer(timeout=10)
            v = _vote(listener)
            # first attempt hangs -> timeout -> reconnect -> retry OK
            await listener.sign_vote(CHAIN, v, sign_extension=False)
            assert listener.get_pub_key().verify_signature(
                v.sign_bytes(CHAIN), v.signature)
            assert any(e["site"] == "signer.round_trip.hang"
                       for e in F.events())
        finally:
            await listener.close()
            dial_task.cancel()
            F.reset()
        return True

    assert run(main())
