"""Measured backend auto-routing (VERDICT r4 weak 3 / next 3): under
backend="auto" the dispatcher must never keep verifying on a device the
router has measured slower than the native host path — with periodic
exploration so a recovered device gets re-measured."""

import numpy as np
import pytest

from cometbft_tpu.crypto import batch as B
from cometbft_tpu.crypto.keys import Ed25519PrivKey


class _FakeDevice:
    platform = "tpu"


@pytest.fixture(autouse=True)
def clean_router():
    # node-spawning tests earlier in the suite raise the process-wide
    # device-lane threshold (node.py applies config.base.min_device_lanes,
    # default 64) and can leave an abandoned in-flight device future; both
    # would silently force these 8-lane batches onto the host path
    saved_min = B.TpuBatchVerifier.MIN_DEVICE_LANES
    saved_inflight = B._DEVICE_INFLIGHT
    B.TpuBatchVerifier.MIN_DEVICE_LANES = 1
    B._DEVICE_INFLIGHT = None
    B._ROUTER.reset()
    yield
    B._ROUTER.reset()
    B.TpuBatchVerifier.MIN_DEVICE_LANES = saved_min
    B._DEVICE_INFLIGHT = saved_inflight


def test_router_optimistic_until_measured():
    r = B._ThroughputRouter()
    assert r.prefer_device(1024)           # no samples: try the device
    r.observe("host", 1024, 0.01)
    assert r.prefer_device(1024)           # still no device sample


def test_router_prefers_measured_faster_host():
    r = B._ThroughputRouter()
    r.observe("device", 1024, 1.0)         # 1024 sigs/s
    r.observe("host", 1024, 0.01)          # 102400 sigs/s
    assert not r.prefer_device(1024)
    # flip: device gets dramatically faster on re-measure
    for _ in range(8):
        r.observe("device", 1024, 0.001)
    assert r.prefer_device(1024)


def test_router_hysteresis_keeps_device_near_parity():
    r = B._ThroughputRouter()
    r.observe("device", 512, 1.0)
    r.observe("host", 512, 1.05)           # host barely slower than 90% rule
    assert r.prefer_device(512)


def test_router_periodic_exploration():
    r = B._ThroughputRouter()
    r.observe("device", 256, 1.0)
    r.observe("host", 256, 0.01)
    decisions = [r.prefer_device(256) for _ in range(130)]
    assert not decisions[0]
    assert any(decisions), "exploration never re-tried the device"
    assert decisions.count(True) <= 3      # rare, not flapping


def test_router_buckets_are_independent():
    r = B._ThroughputRouter()
    r.observe("device", 2000, 1.0)
    r.observe("host", 2000, 0.001)
    assert not r.prefer_device(2000)
    assert r.prefer_device(16)             # small bucket: unmeasured


def _items(n):
    out = []
    for i in range(n):
        pv = Ed25519PrivKey.from_secret(b"route%d" % i)
        msg = b"m%d" % i
        out.append((pv.pub_key(), msg, pv.sign(msg)))
    return out


def test_auto_backend_routes_slow_device_to_host(monkeypatch):
    """A present-but-slow device must not capture the hot path: with the
    router seeded from measurements, backend=auto serves from the native
    host batch and never dispatches to the device."""
    monkeypatch.setattr(B, "_accelerator_device", lambda: _FakeDevice())
    monkeypatch.setattr(B, "_PROBE_RESULT", [True])
    B._ROUTER.observe("device", 8, 10.0)   # measured: painfully slow
    B._ROUTER.observe("host", 8, 0.001)

    def boom(*a, **k):
        raise AssertionError("device dispatch must not run")

    monkeypatch.setattr(B, "device_verify_ed25519", boom)
    monkeypatch.setattr(B, "device_verify_ed25519_cached", boom)

    bv = B.create_batch_verifier("auto")
    assert isinstance(bv, B.TpuBatchVerifier) and bv._routed
    for pub, msg, sig in _items(8):
        bv.add(pub, msg, sig)
    ok, oks = bv.verify()
    assert ok and all(oks)


def test_explicit_tpu_backend_skips_router(monkeypatch):
    """backend="tpu" is an operator override: the router must not keep
    it off the device."""
    monkeypatch.setattr(B, "_accelerator_device", lambda: _FakeDevice())
    B._ROUTER.observe("device", 8, 10.0)
    B._ROUTER.observe("host", 8, 0.001)
    assert B._backend_wants_device("tpu", None, lanes=8)
    assert B._backend_wants_device("jax", None, lanes=8)
    assert not B._backend_wants_device("auto", None, lanes=8)


def test_device_timeout_feeds_pessimistic_sample(monkeypatch):
    """A bounded-wait abandonment charges the router the full wait, so
    subsequent auto batches route to host until the device answers."""
    monkeypatch.setattr(B, "_accelerator_device", lambda: _FakeDevice())
    monkeypatch.setattr(B, "_PROBE_RESULT", [True])
    monkeypatch.setattr(B, "_device_call", lambda fn: None)  # wedged

    bv = B.TpuBatchVerifier(routed=True)
    for pub, msg, sig in _items(8):
        bv.add(pub, msg, sig)
    ok, oks = bv.verify()                  # host fallback still verifies
    assert ok and all(oks)
    assert ("device", B.bucket_for_lanes(8)) in B._ROUTER._ewma
    # the pessimistic sample must now lose to any healthy host number
    B._ROUTER.observe("host", 8, 0.001)
    assert not B._ROUTER.prefer_device(8)
