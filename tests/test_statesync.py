"""Statesync: a fresh node restores an application snapshot from peers —
verified against the light client — instead of replaying the chain, then
follows via blocksync + consensus (reference: ``statesync/syncer_test.go``
and the node-startup handoff)."""

import asyncio

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.config import test_consensus_config as _tcc
from cometbft_tpu.light import Client, LocalNodeProvider, TrustOptions
from cometbft_tpu.node import Node
from cometbft_tpu.p2p import NodeKey
from cometbft_tpu.statesync import StateProvider
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import MockPV

pytestmark = pytest.mark.timeout(150)

PERIOD = 3600 * 1_000_000_000


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _config() -> Config:
    cfg = Config(consensus=_tcc())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    return cfg


def test_statesync_bootstraps_fresh_node():
    async def main():
        pvs = [MockPV.from_secret(b"ssnode%d" % i) for i in range(3)]
        doc = GenesisDoc(chain_id="ss-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            n = await Node.create(
                doc, KVStoreApplication(), priv_validator=pv,
                config=_config(),
                node_key=NodeKey.from_secret(b"ssk%d" % i), name=f"ss{i}")
            nodes.append(n)
            await n.start()
        for i, a in enumerate(nodes):
            for b in nodes[i + 1:]:
                await a.dial_peer(b.listen_addr, persistent=True)

        async def reach(h, who):
            while not all(n.height() >= h for n in who):
                await asyncio.sleep(0.02)

        try:
            # build history with some app state
            for i in range(4):
                await nodes[0].mempool.check_tx(b"sk%d=sv%d" % (i, i))
            await asyncio.wait_for(reach(8, nodes), 60)

            # the joining node trusts a recent header out of band
            trust_h = 2
            trust_hash = nodes[0].block_store.load_block(trust_h).hash()
            light = Client(
                "ss-net", TrustOptions(PERIOD, trust_h, trust_hash),
                LocalNodeProvider(nodes[0].block_store,
                                  nodes[0].state_store),
                backend="cpu")
            provider = StateProvider(light, doc)

            fresh = await Node.create(
                doc, KVStoreApplication(), config=_config(),
                node_key=NodeKey.from_secret(b"ssk9"),
                state_sync_provider=provider, name="ssfresh")
            nodes.append(fresh)
            await fresh.start()
            for a in nodes[:3]:
                await fresh.dial_peer(a.listen_addr, persistent=True)

            # must state-sync (no history below the snapshot), then follow
            target = max(n.height() for n in nodes[:3]) + 3
            await asyncio.wait_for(reach(target, [fresh]), 90)
            assert fresh.block_store.base() > 1, \
                "node replayed from genesis instead of state syncing"
            # restored app state contains pre-snapshot keys
            q = await fresh.app_conns.query.query("/key", b"sk0", 0, False)
            assert q.value == b"sv0"
            # chain agreement at the target height
            hashes = {n.block_store.load_block(target).hash()
                      for n in nodes if n.block_store.load_block(target)}
            assert len(hashes) == 1
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())


def test_syncer_honors_reject_senders_and_refetch():
    """The full ApplySnapshotChunkResponse shape (abci
    ApplySnapshotChunkResponse): an app naming a bad sender gets that
    peer banned and the chunk refetched from the remaining peer; restore
    completes from the honest data."""
    from cometbft_tpu.abci import types as abci_t
    from cometbft_tpu.abci.types import Snapshot
    from cometbft_tpu.statesync.syncer import Syncer

    class StubSnapshotConn:
        def __init__(self):
            self.applied = {}
            self.banned = False

        async def offer_snapshot(self, snapshot, app_hash):
            return abci_t.OFFER_SNAPSHOT_ACCEPT

        async def apply_snapshot_chunk(self, index, chunk, sender):
            if chunk.startswith(b"EVIL"):
                self.banned = True
                return abci_t.ApplySnapshotChunkResponse(
                    result=abci_t.APPLY_CHUNK_ACCEPT,   # result ignored:
                    refetch_chunks=[index],             # chunk re-pulled
                    reject_senders=["evil"])
            self.applied[index] = chunk
            return abci_t.APPLY_CHUNK_ACCEPT            # bare-int form

    class StubQueryConn:
        def __init__(self, h, app_hash):
            self._h, self._hash = h, app_hash

        async def info(self):
            from cometbft_tpu.abci.types import InfoResponse

            return InfoResponse(last_block_height=self._h,
                                last_block_app_hash=self._hash)

    class StubProvider:
        async def app_hash(self, h):
            return b"\xab" * 32

        async def state(self, h):
            return "STATE"

        async def commit(self, h):
            return "COMMIT"

    class StubReactor:
        def __init__(self, syncer_ref):
            self.syncer_ref = syncer_ref
            self.requests = []

        def request_chunk(self, peer, height, format_, index, h):
            self.requests.append((peer, index))
            # deliver async like the network would
            data = (b"EVIL-%d" % index) if peer == "evil" \
                else (b"GOOD-%d" % index)

            async def deliver():
                self.syncer_ref[0].add_chunk(peer, height, format_,
                                             index, data, h)

            asyncio.get_event_loop().create_task(deliver())

    async def main():
        class Conns:
            pass

        conns = Conns()
        snap_conn = StubSnapshotConn()
        conns.snapshot = snap_conn
        conns.query = StubQueryConn(5, b"\xab" * 32)
        ref = [None]
        reactor = StubReactor(ref)
        syncer = Syncer(conns, StubProvider(), reactor=reactor)
        ref[0] = syncer
        snapshot = Snapshot(height=5, format=1, chunks=3,
                            hash=b"\xcd" * 32, metadata=b"")
        # the EVIL peer is first in the rotation, so chunk 0 comes bad
        syncer.add_snapshot("evil", snapshot)
        syncer.add_snapshot("good", snapshot)

        state, commit = await syncer._restore(
            syncer._snapshots[(5, 1, b"\xcd" * 32)])
        assert state == "STATE" and commit == "COMMIT"
        assert snap_conn.banned
        # all three chunks ultimately applied from the honest peer
        assert set(snap_conn.applied) == {0, 1, 2}
        assert all(v.startswith(b"GOOD") for v in snap_conn.applied.values())
        # the banned peer got no further requests after the rejection:
        # its only request is the initial round-robin one for chunk 0
        evil_req_positions = [k for k, (p, _) in
                              enumerate(reactor.requests) if p == "evil"]
        good_req_positions = [k for k, (p, _) in
                              enumerate(reactor.requests) if p == "good"]
        assert len(good_req_positions) >= 3
        assert evil_req_positions, "evil never even asked once"
        # evil can appear only in the initial round-robin pass over the
        # 3 chunks (at most 2 of 3 with 2 peers); everything after the
        # ban goes to good
        assert len(evil_req_positions) <= 2, \
            "banned peer kept receiving requests"
        return True

    assert run(main())


def test_syncer_offer_reject_format_and_sender():
    """OFFER_SNAPSHOT_REJECT_FORMAT skips every snapshot of that format;
    REJECT_SENDER distrusts the advertising peers (syncer.go:208-212)."""
    from cometbft_tpu.abci import types as abci_t
    from cometbft_tpu.abci.types import Snapshot
    from cometbft_tpu.statesync.syncer import StatesyncError, Syncer

    offers = []

    class SnapConn:
        async def offer_snapshot(self, snapshot, app_hash):
            offers.append((snapshot.height, snapshot.format))
            if snapshot.format == 9:
                return abci_t.OFFER_SNAPSHOT_REJECT_FORMAT
            return abci_t.OFFER_SNAPSHOT_REJECT_SENDER

    class Provider:
        async def app_hash(self, h):
            return b"\x01" * 32

    async def main():
        class Conns:
            pass

        conns = Conns()
        conns.snapshot = SnapConn()
        syncer = Syncer(conns, Provider())

        async def advertise():
            # sync() clears the pool at round start; deliver the offers
            # during the discovery window like the reactor would
            await asyncio.sleep(0.05)
            for h in (10, 20):
                syncer.add_snapshot("pA", Snapshot(height=h, format=9,
                                                   chunks=1, hash=b"\x09",
                                                   metadata=b""))
                syncer.add_snapshot("pB", Snapshot(height=h, format=1,
                                                   chunks=1, hash=b"\x01",
                                                   metadata=b""))

        adv = asyncio.get_event_loop().create_task(advertise())
        with pytest.raises(StatesyncError):
            await syncer.sync(discovery_time=0.2, rounds=1)
        await adv

        # format 9 was offered exactly once (highest height), then the
        # whole format was skipped; format-1 offers hit REJECT_SENDER so
        # both peers end up distrusted
        f9 = [o for o in offers if o[1] == 9]
        assert f9 == [(20, 9)], offers
        assert any(o[1] == 1 for o in offers)
        assert "pB" in syncer._banned
        return True

    assert run(main())


def test_concurrent_chunk_fetch_scales_with_peers():
    """VERDICT r3 item 6: per-peer in-flight caps make restore bandwidth
    scale with the number of serving peers — doubling peers roughly
    halves wall-clock — while no peer ever holds more than
    MAX_INFLIGHT_PER_PEER outstanding requests."""
    import time

    from cometbft_tpu.abci import types as abci_t
    from cometbft_tpu.abci.types import InfoResponse, Snapshot
    from cometbft_tpu.statesync.syncer import (MAX_INFLIGHT_PER_PEER,
                                               Syncer)

    N_CHUNKS = 16
    SERVE_DELAY = 0.02          # per-chunk service time per peer

    class SnapConn:
        async def offer_snapshot(self, snapshot, app_hash):
            return abci_t.OFFER_SNAPSHOT_ACCEPT

        async def apply_snapshot_chunk(self, index, chunk, sender):
            return abci_t.APPLY_CHUNK_ACCEPT

    class QueryConn:
        async def info(self):
            return InfoResponse(last_block_height=7,
                                last_block_app_hash=b"\xab" * 32)

    class Provider:
        async def app_hash(self, h):
            return b"\xab" * 32

        async def state(self, h):
            return "S"

        async def commit(self, h):
            return "C"

    class SerialPeerReactor:
        """Each peer is a serial worker: one chunk every SERVE_DELAY —
        models per-peer bandwidth, so aggregate throughput is
        proportional to peer count only if requests spread out."""

        def __init__(self, syncer_ref):
            self.syncer_ref = syncer_ref
            self.queues: dict[str, asyncio.Queue] = {}
            self.max_inflight: dict[str, int] = {}
            self.inflight: dict[str, int] = {}
            self.workers = []

        def request_chunk(self, peer, height, format_, index, h):
            self.inflight[peer] = self.inflight.get(peer, 0) + 1
            self.max_inflight[peer] = max(self.max_inflight.get(peer, 0),
                                          self.inflight[peer])
            if peer not in self.queues:
                self.queues[peer] = asyncio.Queue()
                self.workers.append(asyncio.get_event_loop().create_task(
                    self._serve(peer)))
            self.queues[peer].put_nowait((height, format_, index, h))

        async def _serve(self, peer):
            while True:
                height, format_, index, h = await self.queues[peer].get()
                await asyncio.sleep(SERVE_DELAY)
                self.inflight[peer] -= 1
                self.syncer_ref[0].add_chunk(peer, height, format_, index,
                                             b"DATA-%d" % index, h)

    async def restore_with(n_peers: int) -> tuple[float, dict]:
        class Conns:
            pass

        conns = Conns()
        conns.snapshot = SnapConn()
        conns.query = QueryConn()
        ref = [None]
        reactor = SerialPeerReactor(ref)
        syncer = Syncer(conns, Provider(), reactor=reactor)
        ref[0] = syncer
        snapshot = Snapshot(height=7, format=1, chunks=N_CHUNKS,
                            hash=b"\xcd" * 32, metadata=b"")
        for k in range(n_peers):
            syncer.add_snapshot(f"peer{k}", snapshot)
        t0 = time.perf_counter()
        await syncer._restore(syncer._snapshots[(7, 1, b"\xcd" * 32)])
        dt = time.perf_counter() - t0
        for w in reactor.workers:
            w.cancel()
        return dt, reactor.max_inflight

    t1, m1 = run(restore_with(1))
    t2, m2 = run(restore_with(2))
    t4, m4 = run(restore_with(4))
    for m in (m1, m2, m4):
        assert all(v <= MAX_INFLIGHT_PER_PEER for v in m.values()), m
    # 2 peers ~halve, 4 peers ~quarter (generous slack for event-loop
    # jitter; the unscaled ratio would be ~1.0)
    assert t2 < t1 * 0.7, (t1, t2)
    assert t4 < t1 * 0.45, (t1, t4)


def test_chunk_store_spools_to_disk(tmp_path, monkeypatch):
    """Chunks live on disk while awaiting the sequential applier
    (reference chunks.go), are freed as they apply, and the spool dir is
    removed after a successful restore."""
    import os
    import tempfile

    from cometbft_tpu.statesync.syncer import _ChunkStore

    # pytest owns cleanup even if an assertion below fails mid-test
    monkeypatch.setattr(tempfile, "tempdir", str(tmp_path))
    store = _ChunkStore()
    assert store._dir is None                 # lazy: no dir until a write
    store[2] = (b"C2" * 100, "p1")
    store[0] = (b"C0" * 100, "p2")
    d = store._dir
    assert d and len(os.listdir(d)) == 2      # bytes live on disk...
    assert 0 in store and 1 not in store
    assert store[2] == (b"C2" * 100, "p1")
    assert store.indices_from("p2") == [0]
    store.pop(0)
    assert len(os.listdir(d)) == 1            # ...freed on apply
    store.close()
    assert not os.path.exists(d)


def test_add_chunk_rejects_malicious_indices():
    """A chunk index off the wire becomes a spool FILENAME: non-int,
    negative, out-of-range, and bool indices must all be dropped (path
    traversal / orphan-file defense)."""
    from cometbft_tpu.abci.types import Snapshot
    from cometbft_tpu.statesync.syncer import Syncer, _PendingSnapshot

    async def main():
        sy = Syncer(app_conns=None, state_provider=None)
        snap = Snapshot(height=7, format=1, chunks=4, hash=b"\xcd" * 32,
                        metadata=b"")
        sy._current = _PendingSnapshot(snap)
        for bad in ("../../etc/x", -1, 4, 10**9, True, None, 2.0):
            sy.add_chunk("p", 7, 1, bad, b"data", b"\xcd" * 32)
        await asyncio.sleep(0.05)      # let any (wrong) spool task land
        assert sy._chunks._senders == {}
        assert sy._chunks._dir is None, "a bad index touched the disk"
        # a GOOD index still spools
        sy.add_chunk("p", 7, 1, 2, b"data", b"\xcd" * 32)
        await asyncio.sleep(0.05)
        assert 2 in sy._chunks
        sy._chunks.close()
        return True

    assert run(main())
