"""Statesync: a fresh node restores an application snapshot from peers —
verified against the light client — instead of replaying the chain, then
follows via blocksync + consensus (reference: ``statesync/syncer_test.go``
and the node-startup handoff)."""

import asyncio

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.config import test_consensus_config as _tcc
from cometbft_tpu.light import Client, LocalNodeProvider, TrustOptions
from cometbft_tpu.node import Node
from cometbft_tpu.p2p import NodeKey
from cometbft_tpu.statesync import StateProvider
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import MockPV

pytestmark = pytest.mark.timeout(150)

PERIOD = 3600 * 1_000_000_000


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _config() -> Config:
    cfg = Config(consensus=_tcc())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    return cfg


def test_statesync_bootstraps_fresh_node():
    async def main():
        pvs = [MockPV.from_secret(b"ssnode%d" % i) for i in range(3)]
        doc = GenesisDoc(chain_id="ss-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            n = await Node.create(
                doc, KVStoreApplication(), priv_validator=pv,
                config=_config(),
                node_key=NodeKey.from_secret(b"ssk%d" % i), name=f"ss{i}")
            nodes.append(n)
            await n.start()
        for i, a in enumerate(nodes):
            for b in nodes[i + 1:]:
                await a.dial_peer(b.listen_addr, persistent=True)

        async def reach(h, who):
            while not all(n.height() >= h for n in who):
                await asyncio.sleep(0.02)

        try:
            # build history with some app state
            for i in range(4):
                await nodes[0].mempool.check_tx(b"sk%d=sv%d" % (i, i))
            await asyncio.wait_for(reach(8, nodes), 60)

            # the joining node trusts a recent header out of band
            trust_h = 2
            trust_hash = nodes[0].block_store.load_block(trust_h).hash()
            light = Client(
                "ss-net", TrustOptions(PERIOD, trust_h, trust_hash),
                LocalNodeProvider(nodes[0].block_store,
                                  nodes[0].state_store),
                backend="cpu")
            provider = StateProvider(light, doc)

            fresh = await Node.create(
                doc, KVStoreApplication(), config=_config(),
                node_key=NodeKey.from_secret(b"ssk9"),
                state_sync_provider=provider, name="ssfresh")
            nodes.append(fresh)
            await fresh.start()
            for a in nodes[:3]:
                await fresh.dial_peer(a.listen_addr, persistent=True)

            # must state-sync (no history below the snapshot), then follow
            target = max(n.height() for n in nodes[:3]) + 3
            await asyncio.wait_for(reach(target, [fresh]), 90)
            assert fresh.block_store.base() > 1, \
                "node replayed from genesis instead of state syncing"
            # restored app state contains pre-snapshot keys
            q = await fresh.app_conns.query.query("/key", b"sk0", 0, False)
            assert q.value == b"sv0"
            # chain agreement at the target height
            hashes = {n.block_store.load_block(target).hash()
                      for n in nodes if n.block_store.load_block(target)}
            assert len(hashes) == 1
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())
