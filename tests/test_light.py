"""Light client: adjacent/non-adjacent verification, batched sequential
sync (BASELINE configs[3]: 1000 headers), bisection, detector
(reference: ``light/verifier_test.go``, ``light/client_test.go``,
``light/detector_test.go``)."""

import asyncio

import pytest

from cometbft_tpu.light import (Client, DivergenceError,
                                ErrInvalidHeader, ErrNewValSetCantBeTrusted,
                                LightBlock, LightClientError, Provider,
                                SEQUENTIAL, TrustOptions, TrustedStore,
                                verify_adjacent, verify_non_adjacent,
                                verify_sequential_batched)
from cometbft_tpu.light.provider import ErrLightBlockNotFound
from cometbft_tpu.testing import make_light_chain
from cometbft_tpu.types.validation import ErrBatchItemInvalid

pytestmark = pytest.mark.timeout(120)

CHAIN = "light-chain"
PERIOD = 3600 * 1_000_000_000       # 1 h trusting period


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _now(chain):
    return chain[-1].header.time_ns + 60 * 1_000_000_000


class ChainProvider(Provider):
    """Serves a pre-generated chain; counts fetches, records reported
    evidence (the detector's two-sided dispatch)."""

    def __init__(self, chain, name="prov"):
        self.by_height = {lb.height: lb for lb in chain}
        self.tip = max(self.by_height)
        self.name = name
        self.fetches = 0
        self.reported = []

    def id(self):
        return self.name

    async def light_block(self, height):
        self.fetches += 1
        if height == 0:
            height = self.tip
        lb = self.by_height.get(height)
        if lb is None:
            raise ErrLightBlockNotFound(f"{self.name}: {height}")
        return lb

    async def report_evidence(self, evidence):
        self.reported.append(evidence)


def test_verify_adjacent_ok_and_bad_linkage():
    chain = make_light_chain(3)
    verify_adjacent(CHAIN, chain[0], chain[1], PERIOD, _now(chain),
                    backend="cpu")
    # non-consecutive heights refuse the adjacent path
    with pytest.raises(ErrInvalidHeader):
        verify_adjacent(CHAIN, chain[0], chain[2], PERIOD, _now(chain),
                        backend="cpu")


def test_verify_adjacent_rejects_forged_valset():
    chain = make_light_chain(3)
    forged = make_light_chain(3, seed=b"other")
    # same heights, different keys: next_validators_hash cannot match
    with pytest.raises(ErrInvalidHeader):
        verify_adjacent(CHAIN, chain[0], forged[1], PERIOD, _now(chain),
                        backend="cpu")


def test_verify_non_adjacent_skip_ok():
    chain = make_light_chain(50)
    verify_non_adjacent(CHAIN, chain[0], chain[49], PERIOD, _now(chain),
                        backend="cpu")


def test_verify_non_adjacent_rotated_set_cant_be_trusted():
    # one validator of 4 swapped every block: after 3 rotations the
    # original set retains < 1/3 overlap power... rotate 3 of 4 by height 4
    chain = make_light_chain(20, n_vals=4, rotate_every=1)
    with pytest.raises(ErrNewValSetCantBeTrusted):
        verify_non_adjacent(CHAIN, chain[0], chain[15], PERIOD,
                            _now(chain), backend="cpu")


def test_expired_trusting_period_rejected():
    chain = make_light_chain(5)
    late = chain[0].header.time_ns + PERIOD + 1
    with pytest.raises(LightClientError):
        verify_adjacent(CHAIN, chain[0], chain[1], PERIOD, late,
                        backend="cpu")


def test_sequential_batched_1000_headers():
    """BASELINE configs[3]: 1000-header sequential sync on the batched
    path — correctness here, device timing in the bench."""
    chain = make_light_chain(1000, n_vals=8)
    verify_sequential_batched(CHAIN, chain[0], chain[1:], PERIOD,
                              _now(chain), backend="cpu")


def test_sequential_batched_flags_corrupt_header():
    chain = make_light_chain(40, n_vals=4)
    bad = chain[25]
    sig = bytearray(bad.commit.signatures[0].signature)
    sig[10] ^= 1
    bad.commit.signatures[0].signature = bytes(sig)
    with pytest.raises(ErrBatchItemInvalid) as exc:
        verify_sequential_batched(CHAIN, chain[0], chain[1:], PERIOD,
                                  _now(chain), backend="cpu")
    assert exc.value.height == bad.height


def test_client_skipping_sync_is_sublinear():
    chain = make_light_chain(200, n_vals=4, rotate_every=10)
    primary = ChainProvider(chain)
    client = Client(CHAIN, TrustOptions(PERIOD, 1, chain[0].header.hash()),
                    primary, backend="cpu",
                    now_ns=lambda: _now(chain))

    async def main():
        lb = await client.verify_light_block_at_height(200)
        assert lb.header.hash() == chain[199].header.hash()
        return True

    assert run(main())
    # bisection fetches far fewer than one header per height
    assert primary.fetches < 60, primary.fetches


def test_client_sequential_mode():
    chain = make_light_chain(60, n_vals=4)
    primary = ChainProvider(chain)
    client = Client(CHAIN, TrustOptions(PERIOD, 1, chain[0].header.hash()),
                    primary, mode=SEQUENTIAL, backend="cpu",
                    now_ns=lambda: _now(chain))

    async def main():
        lb = await client.verify_light_block_at_height(60)
        assert lb.header.hash() == chain[59].header.hash()
        # every intermediate header is now trusted
        assert client.store.get(30) is not None
        return True

    assert run(main())


def test_client_detects_forked_witness():
    chain = make_light_chain(30, n_vals=4)
    fork = make_light_chain(30, n_vals=4, seed=b"fork")
    primary = ChainProvider(chain, "primary")
    witness = ChainProvider(chain[:20] + fork[20:], "witness")
    client = Client(CHAIN, TrustOptions(PERIOD, 1, chain[0].header.hash()),
                    primary, witnesses=[witness], backend="cpu",
                    now_ns=lambda: _now(chain))

    async def main():
        with pytest.raises(DivergenceError) as exc:
            await client.verify_light_block_at_height(25)
        assert exc.value.witness_id == "witness"
        assert exc.value.evidence is not None
        return True

    assert run(main())


def test_detector_trace_walk_two_sided_evidence():
    """VERDICT r4 next 5: a fork at height H with divergence point H-k
    must yield evidence whose common_height is the TRUE fork height
    (trace examination, detector.go:285), two-sided evidence, and
    delivery to both honest parties — the witness receives the case
    against the primary, the primary the case against the witness."""
    H, F = 30, 22                       # tip and fork heights
    chain = make_light_chain(H, n_vals=4)
    forked = make_light_chain(H, n_vals=4, fork_at=F, fork_skew_ns=777)
    # sanity: shared validly-signed prefix, divergent suffix
    assert chain[F - 1].header.hash() == forked[F - 1].header.hash()
    assert chain[F].header.hash() != forked[F].header.hash()

    primary = ChainProvider(chain, "primary")
    witness = ChainProvider(forked, "witness")
    client = Client(CHAIN, TrustOptions(PERIOD, 1, chain[0].header.hash()),
                    primary, witnesses=[witness], mode=SEQUENTIAL,
                    backend="cpu", now_ns=lambda: _now(chain))

    async def main():
        with pytest.raises(DivergenceError) as exc:
            await client.verify_light_block_at_height(H)
        e = exc.value
        assert e.common_height == F
        # primary's side of the fork at the first divergent height
        assert e.evidence_against_primary.common_height == F
        assert e.evidence_against_primary.conflicting_height == F + 1
        assert e.evidence_against_primary.conflicting_header_hash == \
            chain[F].header.hash()
        # witness's side
        assert e.evidence_against_witness.common_height == F
        assert e.evidence_against_witness.conflicting_height == F + 1
        assert e.evidence_against_witness.conflicting_header_hash == \
            forked[F].header.hash()
        # each honest party received the case against the other side
        assert [ev.conflicting_header_hash for ev in witness.reported] == \
            [chain[F].header.hash()]
        assert [ev.conflicting_header_hash for ev in primary.reported] == \
            [forked[F].header.hash()]
        # nothing divergent was persisted as trusted
        assert client.store.get(H) is None
        return True

    assert run(main())


def test_detector_drops_persistently_lagging_witness():
    """VERDICT r4 weak 7: a witness that can never serve the height is
    struck out after MAX_WITNESS_LAG_STRIKES consecutive misses instead
    of being retried forever; an agreeing witness survives."""
    from cometbft_tpu.light.detector import (MAX_WITNESS_LAG_STRIKES,
                                             detect_divergence)

    chain = make_light_chain(10, n_vals=4)
    primary = ChainProvider(chain, "primary")
    laggard = ChainProvider(chain[:2], "laggard")     # tip stuck at 2
    healthy = ChainProvider(chain, "healthy")
    client = Client(CHAIN, TrustOptions(PERIOD, 1, chain[0].header.hash()),
                    primary, witnesses=[laggard, healthy], backend="cpu",
                    now_ns=lambda: _now(chain))

    async def main():
        client.store.save(chain[0])
        for i in range(MAX_WITNESS_LAG_STRIKES):
            assert laggard in client.witnesses, f"dropped too early ({i})"
            await detect_divergence(client, chain[7], _now(chain))
        assert laggard not in client.witnesses
        assert healthy in client.witnesses
        return True

    assert run(main())


def test_client_backwards_verification():
    chain = make_light_chain(40, n_vals=4)
    primary = ChainProvider(chain)
    client = Client(CHAIN, TrustOptions(PERIOD, 30,
                                        chain[29].header.hash()),
                    primary, backend="cpu", now_ns=lambda: _now(chain))

    async def main():
        await client.initialize()
        lb = await client.verify_light_block_at_height(10)
        assert lb.header.hash() == chain[9].header.hash()
        return True

    assert run(main())


def test_client_prunes_store_to_pruning_size():
    """light/client.go:26 defaultPruningSize: the trusted store keeps a
    bounded number of light blocks as sync advances."""
    chain = make_light_chain(20)
    primary = ChainProvider(chain, "primary")

    async def main():
        client = Client(CHAIN,
                        TrustOptions(PERIOD, 1, chain[0].header.hash()),
                        primary, mode=SEQUENTIAL, backend="cpu",
                        pruning_size=5, now_ns=lambda: _now(chain))
        await client.initialize()
        await client.verify_light_block_at_height(20)
        stored = [h for h in range(1, 21)
                  if client.store.get(h) is not None]
        assert len(stored) <= 5, stored
        assert client.latest_trusted().height == 20
        return True

    assert run(main())
