"""secp256k1 keys + mixed-key commit verification through the batch seam
(reference: ``crypto/secp256k1/secp256k1_test.go``; mixed routing is the
BASELINE configs[5] shape — where the reference REFUSES to batch mixed key
types, the TpuBatchVerifier routes ed25519 lanes to the device and
secp256k1 lanes to CPU)."""

import pytest

from cometbft_tpu.crypto.batch import create_batch_verifier
from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.crypto.secp256k1 import (Secp256k1PrivKey, Secp256k1PubKey,
                                           _HALF_N, _N)
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.validation import VerifyCommit
from cometbft_tpu.types.validator_set import Validator, ValidatorSet

from test_types import CHAIN_ID, make_commit

# ~90 s of pure-Python EC arithmetic on this image (no `cryptography`
# backend) — tier-2; tier-1 keeps secp coverage via the mixed-key
# routing tests in test_batch_verifier.
pytestmark = [pytest.mark.timeout(120), pytest.mark.slow]


def test_sign_verify_roundtrip():
    sk = Secp256k1PrivKey.generate()
    pk = sk.pub_key()
    sig = sk.sign(b"a message")
    assert len(sig) == 64
    assert pk.verify_signature(b"a message", sig)
    assert not pk.verify_signature(b"another message", sig)
    # r3 flake root cause: the old tamper `sig[:-1] + b"\x00"` was an
    # IDENTITY transform whenever sig[-1] was already 0x00 (p = 1/256
    # per run with random nonces) — the "tampered" sig verified because
    # it was the untampered sig.  XOR guarantees a real change.
    assert not pk.verify_signature(b"a message",
                                   sig[:-1] + bytes([sig[-1] ^ 1]))


def test_sign_is_rfc6979_deterministic():
    """Reference parity: dcrec's SignCompact derives k per RFC 6979
    (secp256k1.go:121-125), so signatures are a pure function of
    (key, msg) — and every test failure is replayable.  Vectors are the
    widely-published community RFC6979/secp256k1/SHA-256 set."""
    sk = Secp256k1PrivKey((1).to_bytes(32, "big"))
    sig = sk.sign(b"Satoshi Nakamoto")
    assert sig == sk.sign(b"Satoshi Nakamoto")
    assert sig.hex() == (
        "934b1ea10a4b3c1757e2b0c017d0b6143ce3c9a7e6a4a49860d7a6ab210ee3d8"
        "2442ce9d2b916064108014783e923ec36b49743e2ffa1c4496f01a512aafd9e5")
    sig2 = sk.sign(b"All those moments will be lost in time, like tears "
                   b"in rain. Time to die...")
    assert sig2.hex() == (
        "8600dbd41e348fe5c9465ab92d23e3db8b98b873beecd930736488696438cb6b"
        "547fe64427496db33bf66019dacbf0039c04199abb0122918601db38a72cfc21")
    # n-1 secret exercises the high end of the key range
    sk2 = Secp256k1PrivKey((_N - 1).to_bytes(32, "big"))
    sig3 = sk2.sign(b"Satoshi Nakamoto")
    assert sig3.hex() == (
        "fd567d121db66e382991534ada77a6bd3106f0a1098c231e47993447cd6af2d0"
        "6b39cd0eb1bc8603e159ef5c20a5c8ad685a45b06ce9bebed3f153d10d93bed5")
    for s_, m in ((sk, b"Satoshi Nakamoto"), (sk2, b"Satoshi Nakamoto")):
        assert s_.pub_key().verify_signature(m, s_.sign(m))


def test_low_s_enforced_and_malleable_rejected():
    sk = Secp256k1PrivKey.from_secret(b"malleable")
    sig = sk.sign(b"msg")
    s = int.from_bytes(sig[32:], "big")
    assert s <= _HALF_N
    # the complementary (high-S) signature verifies under plain ECDSA but
    # must be REJECTED here
    high = sig[:32] + (_N - s).to_bytes(32, "big")
    assert not sk.pub_key().verify_signature(b"msg", high)


def test_address_is_ripemd160_sha256():
    import hashlib

    pk = Secp256k1PrivKey.from_secret(b"addr").pub_key()
    want = hashlib.new("ripemd160",
                       hashlib.sha256(pk.bytes()).digest()).digest()
    assert pk.address() == want
    assert len(pk.address()) == 20


def test_from_secret_deterministic():
    a = Secp256k1PrivKey.from_secret(b"same")
    b = Secp256k1PrivKey.from_secret(b"same")
    assert a.bytes() == b.bytes()
    assert a.pub_key().bytes() == b.pub_key().bytes()


def test_pubkey_roundtrip_compressed():
    pk = Secp256k1PrivKey.generate().pub_key()
    again = Secp256k1PubKey(pk.bytes())
    assert again.bytes() == pk.bytes()
    assert pk.bytes()[0] in (2, 3) and len(pk.bytes()) == 33


def _mixed_vals(n_ed, n_secp):
    privs = [Ed25519PrivKey.from_secret(b"med%d" % i) for i in range(n_ed)]
    privs += [Secp256k1PrivKey.from_secret(b"msec%d" % i)
              for i in range(n_secp)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    return vals, by_addr


def test_mixed_key_batch_verifier_routes_both():
    vals, by_addr = _mixed_vals(6, 3)
    bv = create_batch_verifier("jax")       # device-style verifier on CPU
    import os

    msgs = []
    for i, v in enumerate(vals.validators):
        msg = b"lane %d" % i
        bv.add(v.pub_key, msg, by_addr[v.address].sign(msg))
        msgs.append(msg)
    ok, oks = bv.verify()
    assert ok and all(oks) and len(oks) == 9


def test_mixed_key_commit_verifies():
    """A commit signed by both key families passes VerifyCommit through the
    TPU-style verifier (the reference's shouldBatchVerify would bail to
    one-by-one; here it is one call)."""
    vals, by_addr = _mixed_vals(5, 3)
    commit = make_commit(vals, by_addr, height=10, round_=0)
    VerifyCommit(CHAIN_ID, vals, commit.block_id, 10, commit, backend="jax")
    # and a corrupted secp lane is caught
    secp_idx = next(i for i, v in enumerate(vals.validators)
                    if v.pub_key.type() == "secp256k1")
    commit2 = make_commit(vals, by_addr, height=10, round_=0,
                          bad_at={secp_idx})
    from cometbft_tpu.types.validation import ErrInvalidSignature

    with pytest.raises(ErrInvalidSignature):
        VerifyCommit(CHAIN_ID, vals, commit2.block_id, 10, commit2,
                     backend="jax")


# ---------------------------------------------------------------- bls12381

def test_bls12381_stub_surface():
    """Default builds mirror the reference's !bls12381 stub
    (crypto/bls12381/key.go): key type registered, sizes fixed,
    operations raise ErrDisabled unless a host backend exists."""
    import pytest as _pytest

    from cometbft_tpu.crypto import bls12381 as bls
    from cometbft_tpu.crypto.keys import (BLS12381_KEY_TYPE,
                                          pub_key_from_type_bytes)

    pub = pub_key_from_type_bytes(BLS12381_KEY_TYPE, b"\x01" * 48)
    assert pub.type() == BLS12381_KEY_TYPE
    assert len(pub.address()) == 20
    with _pytest.raises(ValueError):
        pub_key_from_type_bytes(BLS12381_KEY_TYPE, b"\x01" * 32)

    if not bls.ENABLED:
        with _pytest.raises(bls.ErrDisabled):
            pub.verify_signature(b"msg", b"\x00" * 96)
        with _pytest.raises(bls.ErrDisabled):
            bls.Bls12381PrivKey(b"\x02" * 32).sign(b"msg")
        with _pytest.raises(bls.ErrDisabled):
            bls.Bls12381PrivKey.generate()
    else:  # a host backend is present: sign/verify round-trips
        sk = bls.Bls12381PrivKey.generate()
        sig = sk.sign(b"msg")
        assert len(sig) == 96
        assert sk.pub_key().verify_signature(b"msg", sig)


def test_bls_validator_backend_guard(monkeypatch):
    """Consensus-split guard: a genesis with bls12_381 validator keys is
    refused when the node's backend speaks the non-standard bundled
    ciphersuite, unless the closed-network opt-in env is set (a hazard
    the reference sidesteps by having exactly one blst backend)."""
    import pytest as _pytest

    from cometbft_tpu.crypto import bls12381 as bls
    from cometbft_tpu.types.genesis import (GenesisDoc, GenesisError,
                                            GenesisValidator)

    if not bls.ENABLED:
        _pytest.skip("no BLS backend in this build")
    sk = bls.Bls12381PrivKey.from_secret(b"backend-guard")
    doc = GenesisDoc(chain_id="bls-chain",
                     validators=[GenesisValidator(
                         pub_key=sk.pub_key(), power=10,
                         pop=bls.pop_prove(sk.bytes()))])

    monkeypatch.delenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", raising=False)
    if bls.is_standard_backend():
        doc.validate_and_complete()          # standard suite: always fine
        return
    with _pytest.raises(GenesisError, match="ciphersuite|suite|backend"):
        doc.validate_and_complete()
    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    doc.validate_and_complete()              # explicit opt-in unblocks


def test_differential_fuzz_smoke():
    """In-process slice of the differential fuzzer (same process as the
    full suite → exercises the cross-library state the r3 flake was
    suspected of; the standalone harness runs millions of triples)."""
    from fuzz_secp256k1 import fuzz

    assert fuzz(n_triples=60, seed=7) >= 60 * 6


def test_native_secp256k1_matches_openssl_oracle():
    """native/secp256k1.cpp differential: valid, tampered, malleable
    (high-s), boundary r/s, and malformed-pubkey cases must all agree
    with the OpenSSL-backed path."""
    import random
    import secrets

    from cometbft_tpu.crypto import secp256k1 as s

    lib = s._native_lib()
    assert lib is not None, "native secp256k1 must build on this image"

    from fuzz_secp256k1 import _oracle as oracle

    random.seed(5)
    for i in range(25):
        sk = s.Secp256k1PrivKey.from_secret(b"n%d" % i)
        pub = sk.pub_key()
        m = secrets.token_bytes(random.randrange(0, 150))
        sig = sk.sign(m)
        assert s._native_verify(pub.bytes(), m, sig) is True
        bad = bytearray(sig)
        bad[random.randrange(64)] ^= 1
        assert s._native_verify(pub.bytes(), m, bytes(bad)) == \
            oracle(pub, m, bytes(bad))

    # regression: this key's sqrt-candidate negation underflowed the old
    # 2p subtraction bias, making native reject a VALID signature (a
    # consensus divergence between native and fallback nodes)
    sk = s.Secp256k1PrivKey.from_secret(b"probe204524")
    m = b"underflow-probe"
    sig = sk.sign(m)
    assert oracle(sk.pub_key(), m, sig) is True
    assert s._native_verify(sk.pub_key().bytes(), m, sig) is True

    sk = s.Secp256k1PrivKey.from_secret(b"edge")
    pub, m = sk.pub_key().bytes(), b"edge-msg"
    sig = sk.sign(m)
    r = int.from_bytes(sig[:32], "big")
    sval = int.from_bytes(sig[32:], "big")
    # high-s (malleable) flip must be rejected
    flipped = sig[:32] + (s._N - sval).to_bytes(32, "big")
    assert s._native_verify(pub, m, flipped) is False
    # r/s out of range
    assert s._native_verify(pub, m, b"\x00" * 32 + sig[32:]) is False
    assert s._native_verify(
        pub, m, s._N.to_bytes(32, "big") + sig[32:]) is False
    # x coordinate >= p in the pubkey encoding
    P = 2**256 - 2**32 - 977
    assert s._native_verify(
        b"\x02" + P.to_bytes(32, "big"), m, sig) is False
