"""secp256k1 keys + mixed-key commit verification through the batch seam
(reference: ``crypto/secp256k1/secp256k1_test.go``; mixed routing is the
BASELINE configs[5] shape — where the reference REFUSES to batch mixed key
types, the TpuBatchVerifier routes ed25519 lanes to the device and
secp256k1 lanes to CPU)."""

import pytest

from cometbft_tpu.crypto.batch import create_batch_verifier
from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.crypto.secp256k1 import (Secp256k1PrivKey, Secp256k1PubKey,
                                           _HALF_N, _N)
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.validation import VerifyCommit
from cometbft_tpu.types.validator_set import Validator, ValidatorSet

from test_types import CHAIN_ID, make_commit

pytestmark = pytest.mark.timeout(120)


def test_sign_verify_roundtrip():
    sk = Secp256k1PrivKey.generate()
    pk = sk.pub_key()
    sig = sk.sign(b"a message")
    assert len(sig) == 64
    assert pk.verify_signature(b"a message", sig)
    assert not pk.verify_signature(b"another message", sig)
    assert not pk.verify_signature(b"a message", sig[:-1] + b"\x00")


def test_low_s_enforced_and_malleable_rejected():
    sk = Secp256k1PrivKey.from_secret(b"malleable")
    sig = sk.sign(b"msg")
    s = int.from_bytes(sig[32:], "big")
    assert s <= _HALF_N
    # the complementary (high-S) signature verifies under plain ECDSA but
    # must be REJECTED here
    high = sig[:32] + (_N - s).to_bytes(32, "big")
    assert not sk.pub_key().verify_signature(b"msg", high)


def test_address_is_ripemd160_sha256():
    import hashlib

    pk = Secp256k1PrivKey.from_secret(b"addr").pub_key()
    want = hashlib.new("ripemd160",
                       hashlib.sha256(pk.bytes()).digest()).digest()
    assert pk.address() == want
    assert len(pk.address()) == 20


def test_from_secret_deterministic():
    a = Secp256k1PrivKey.from_secret(b"same")
    b = Secp256k1PrivKey.from_secret(b"same")
    assert a.bytes() == b.bytes()
    assert a.pub_key().bytes() == b.pub_key().bytes()


def test_pubkey_roundtrip_compressed():
    pk = Secp256k1PrivKey.generate().pub_key()
    again = Secp256k1PubKey(pk.bytes())
    assert again.bytes() == pk.bytes()
    assert pk.bytes()[0] in (2, 3) and len(pk.bytes()) == 33


def _mixed_vals(n_ed, n_secp):
    privs = [Ed25519PrivKey.from_secret(b"med%d" % i) for i in range(n_ed)]
    privs += [Secp256k1PrivKey.from_secret(b"msec%d" % i)
              for i in range(n_secp)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    return vals, by_addr


def test_mixed_key_batch_verifier_routes_both():
    vals, by_addr = _mixed_vals(6, 3)
    bv = create_batch_verifier("jax")       # device-style verifier on CPU
    import os

    msgs = []
    for i, v in enumerate(vals.validators):
        msg = b"lane %d" % i
        bv.add(v.pub_key, msg, by_addr[v.address].sign(msg))
        msgs.append(msg)
    ok, oks = bv.verify()
    assert ok and all(oks) and len(oks) == 9


def test_mixed_key_commit_verifies():
    """A commit signed by both key families passes VerifyCommit through the
    TPU-style verifier (the reference's shouldBatchVerify would bail to
    one-by-one; here it is one call)."""
    vals, by_addr = _mixed_vals(5, 3)
    commit = make_commit(vals, by_addr, height=10, round_=0)
    VerifyCommit(CHAIN_ID, vals, commit.block_id, 10, commit, backend="jax")
    # and a corrupted secp lane is caught
    secp_idx = next(i for i, v in enumerate(vals.validators)
                    if v.pub_key.type() == "secp256k1")
    commit2 = make_commit(vals, by_addr, height=10, round_=0,
                          bad_at={secp_idx})
    from cometbft_tpu.types.validation import ErrInvalidSignature

    with pytest.raises(ErrInvalidSignature):
        VerifyCommit(CHAIN_ID, vals, commit2.block_id, 10, commit2,
                     backend="jax")


# ---------------------------------------------------------------- bls12381

def test_bls12381_stub_surface():
    """Default builds mirror the reference's !bls12381 stub
    (crypto/bls12381/key.go): key type registered, sizes fixed,
    operations raise ErrDisabled unless a host backend exists."""
    import pytest as _pytest

    from cometbft_tpu.crypto import bls12381 as bls
    from cometbft_tpu.crypto.keys import (BLS12381_KEY_TYPE,
                                          pub_key_from_type_bytes)

    pub = pub_key_from_type_bytes(BLS12381_KEY_TYPE, b"\x01" * 48)
    assert pub.type() == BLS12381_KEY_TYPE
    assert len(pub.address()) == 20
    with _pytest.raises(ValueError):
        pub_key_from_type_bytes(BLS12381_KEY_TYPE, b"\x01" * 32)

    if not bls.ENABLED:
        with _pytest.raises(bls.ErrDisabled):
            pub.verify_signature(b"msg", b"\x00" * 96)
        with _pytest.raises(bls.ErrDisabled):
            bls.Bls12381PrivKey(b"\x02" * 32).sign(b"msg")
        with _pytest.raises(bls.ErrDisabled):
            bls.Bls12381PrivKey.generate()
    else:  # a host backend is present: sign/verify round-trips
        sk = bls.Bls12381PrivKey.generate()
        sig = sk.sign(b"msg")
        assert len(sig) == 96
        assert sk.pub_key().verify_signature(b"msg", sig)


def test_bls_validator_backend_guard(monkeypatch):
    """Consensus-split guard: a genesis with bls12_381 validator keys is
    refused when the node's backend speaks the non-standard bundled
    ciphersuite, unless the closed-network opt-in env is set (a hazard
    the reference sidesteps by having exactly one blst backend)."""
    import pytest as _pytest

    from cometbft_tpu.crypto import bls12381 as bls
    from cometbft_tpu.types.genesis import (GenesisDoc, GenesisError,
                                            GenesisValidator)

    pub = bls.Bls12381PubKey(b"\x01" * 48)
    doc = GenesisDoc(chain_id="bls-chain",
                     validators=[GenesisValidator(pub_key=pub, power=10)])

    monkeypatch.delenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", raising=False)
    if bls.is_standard_backend():
        doc.validate_and_complete()          # standard suite: always fine
        return
    with _pytest.raises(GenesisError, match="ciphersuite|suite|backend"):
        doc.validate_and_complete()
    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    doc.validate_and_complete()              # explicit opt-in unblocks
