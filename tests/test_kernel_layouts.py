"""Layout-promotion tests: the production kernel is limb-major (20,B)
internally (ops/fe_lm.py via ops/group.py); the batch-major
instantiation (ops/edwards.py over ops/fe.py) remains the test surface.
These tests pin (a) the two group instantiations against each other on
the point-op level and (b) the production kernel's verdicts on the
ZIP-215 edge corpus against the pure-Python oracle lane by lane —
covering what the deleted limb-major/batch-major twin comparison used
to, but with the oracle as the single source of truth."""

import numpy as np
import jax
import pytest

pytestmark = [pytest.mark.timeout(900), pytest.mark.slow]

from cometbft_tpu.crypto import _ed25519_py as ref
from cometbft_tpu.ops import ed25519, fe, fe_lm
from cometbft_tpu.ops.group import make_group
from cometbft_tpu.testing import dense_signature_batch

_gbm = make_group(fe)
_glm = make_group(fe_lm)


def test_group_instantiations_agree_on_point_ops():
    """dbl/add/decompress agree between the batch-major and limb-major
    field layouts on random curve points (transposition at the edges)."""
    rng = np.random.default_rng(5)
    encs = []
    while len(encs) < 16:
        cand = rng.bytes(32)
        if ref.pt_decompress_zip215(cand) is not None:
            encs.append(cand)
    arr = np.stack([np.frombuffer(e, np.uint8) for e in encs]).astype(np.int32)

    def bm(enc):
        p, ok = _gbm.decompress_zip215(enc)
        d = _gbm.dbl(p)
        s = _gbm.add_cached(d, _gbm.cache(p))      # 3P
        return fe.freeze(fe.mul(s.x, fe.invert(s.z))), ok

    def lm(enc_T):
        p, ok = _glm.decompress_zip215(enc_T)
        d = _glm.dbl(p)
        s = _glm.add_cached(d, _glm.cache(p))
        return fe_lm.freeze(fe_lm.mul(s.x, fe_lm.invert(s.z))), ok

    x_bm, ok_bm = jax.jit(bm)(arr)
    x_lm, ok_lm = jax.jit(lm)(arr.T)
    assert np.asarray(ok_bm).all() and np.asarray(ok_lm).all()
    assert (np.asarray(x_bm) == np.asarray(x_lm).T).all()


def test_production_kernel_zip215_edge_corpus_vs_oracle():
    """Edge encodings (sign-bit families, non-canonical y, S >= L) get
    the oracle's verdict from the production (limb-major) kernel."""
    args, items = dense_signature_batch(24, msg_len=80, seed=31)
    pub, rb, sb, blocks, active = [np.asarray(a).copy() for a in args]
    pub[0, 31] |= 0x80      # sign-bit x=0 family
    rb[1, 31] |= 0x80
    pub[2] = 0; pub[2, 0] = 1                      # y = 0 + sign 0
    rb[3] = 255                                    # non-canonical y >= p
    sb[4] = 255                                    # S >= L (must reject)
    got = np.asarray(jax.jit(ed25519.verify_padded)(
        pub, rb, sb, blocks, active))
    assert not got[4]                              # sanity: S>=L rejected
    for i, (pk, msg, sig) in enumerate(items):
        pk2 = bytes(pub[i].astype(np.uint8))
        sig2 = bytes(rb[i].astype(np.uint8)) + bytes(sb[i].astype(np.uint8))
        want = ref.verify_zip215(pk2, msg, sig2)
        assert bool(got[i]) == want, i


def test_production_kernel_tampered_lanes_vs_oracle():
    args, items = dense_signature_batch(24, msg_len=80, seed=7)
    pub, rb, sb, blocks, active = [np.asarray(a).copy() for a in args]
    sb[3, 0] ^= 1          # bad S
    rb[7, 31] ^= 0x40      # bad R encoding
    pub[11, 5] ^= 2        # bad A
    got = np.asarray(jax.jit(ed25519.verify_padded)(
        pub, rb, sb, blocks, active))
    assert not got[3] and not got[7] and not got[11]
    for i, (pk, msg, sig) in enumerate(items):
        pk2 = bytes(pub[i].astype(np.uint8))
        sig2 = bytes(rb[i].astype(np.uint8)) + bytes(sb[i].astype(np.uint8))
        assert bool(got[i]) == ref.verify_zip215(pk2, msg, sig2), i
