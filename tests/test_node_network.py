"""Tier-2 tests: full Node assemblies talking over REAL localhost TCP —
the reference's e2e tier shrunk to one machine (``test/e2e/README.md``,
SURVEY §4 "three tiers").  Exercises the whole stack: transport secret
connections, MConnection channels, consensus + mempool reactors, gossip,
WAL, handshake."""

import asyncio

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.config import test_consensus_config as make_test_consensus_config
from cometbft_tpu.node import Node
from cometbft_tpu.p2p import NodeKey
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import MockPV

# live multi-node TCP nets — tier-2 with the other net suites.
pytestmark = [pytest.mark.timeout(150), pytest.mark.slow]


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _genesis(n: int, chain_id="tcp-net"):
    pvs = [MockPV.from_secret(b"tcpnode%d" % i) for i in range(n)]
    doc = GenesisDoc(chain_id=chain_id,
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])
    return doc, pvs


def _config() -> Config:
    cfg = Config(consensus=make_test_consensus_config())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    return cfg


async def _make_net(n: int, homes=None):
    doc, pvs = _genesis(n)
    nodes = []
    for i in range(n):
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pvs[i],
            config=_config(), node_key=NodeKey.from_secret(b"nk%d" % i),
            home=(homes[i] if homes else None), name=f"tnode{i}")
        nodes.append(node)
    for node in nodes:
        await node.start()
    # full mesh: i dials j for i < j
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            await a.dial_peer(b.listen_addr, persistent=True)
    return nodes


async def _wait_height(nodes, h, timeout=90.0):
    async def all_reached():
        while not all(n.height() >= h for n in nodes):
            await asyncio.sleep(0.02)

    await asyncio.wait_for(all_reached(), timeout)


async def _stop_all(nodes):
    for n in nodes:
        try:
            await n.stop()
        except Exception:
            pass


def test_four_nodes_commit_over_tcp():
    """4 single-process nodes on localhost TCP commit 10+ blocks with txs
    gossiped via the mempool channel (VERDICT round-1 item 3's bar)."""

    async def main():
        nodes = await _make_net(4)
        try:
            # txs injected on ONE node must reach proposers via gossip
            for i in range(4):
                await nodes[0].mempool.check_tx(b"gk%d=gv%d" % (i, i))
            await _wait_height(nodes, 10)
            for h in range(1, 11):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"fork at height {h}"
            committed = set()
            for h in range(1, nodes[1].height() + 1):
                for tx in nodes[1].block_store.load_block(h).data.txs:
                    committed.add(bytes(tx))
            want = {b"gk%d=gv%d" % (i, i) for i in range(4)}
            assert want <= committed, f"missing gossiped txs: {want - committed}"
            # the app state converged everywhere
            for n in nodes:
                assert n.app_conns is not None
        finally:
            await _stop_all(nodes)
        return True

    assert run(main())


def test_vote_extensions_over_tcp():
    """Tier-2 version of the in-proc extensions test: 4 real nodes over
    TCP with vote_extensions_enable_height=1 store extended commits whose
    extensions the kvstore app produced and verified across the wire."""

    async def main():
        doc, pvs = _genesis(4, chain_id="ext-net")
        doc.consensus_params.feature.vote_extensions_enable_height = 1
        nodes = []
        for i in range(4):
            node = await Node.create(
                doc, KVStoreApplication(), priv_validator=pvs[i],
                config=_config(), node_key=NodeKey.from_secret(b"ek%d" % i),
                name=f"ext{i}")
            nodes.append(node)
        try:
            for node in nodes:
                await node.start()
            for i, a in enumerate(nodes):
                for b in nodes[i + 1:]:
                    await a.dial_peer(b.listen_addr, persistent=True)
            await _wait_height(nodes, 4)
            for n in nodes:
                ext = n.block_store.load_block_extended_commit(3)
                if ext is None:
                    continue        # only the proposer path must store it
                assert ext.ensure_extensions(True)
                n_with_ext = sum(1 for e in ext.extended_signatures
                                 if e.commit_sig.is_commit()
                                 and e.extension_signature)
                assert n_with_ext >= 3, "extensions missing over TCP"
                break
            else:
                raise AssertionError("no node stored an extended commit")
        finally:
            await _stop_all(nodes)
        return True

    assert run(main())


def test_node_joins_late_and_catches_up_votes():
    """A 4th validator connecting after the others started still joins
    consensus (vote catch-up via gossip; no blocksync needed when it
    connects within the first height)."""

    async def main():
        doc, pvs = _genesis(4)
        nodes = []
        for i in range(4):
            node = await Node.create(
                doc, KVStoreApplication(), priv_validator=pvs[i],
                config=_config(), node_key=NodeKey.from_secret(b"lk%d" % i),
                name=f"late{i}")
            nodes.append(node)
        try:
            for node in nodes[:3]:
                await node.start()
            for i, a in enumerate(nodes[:3]):
                for b in nodes[i + 1:3]:
                    await a.dial_peer(b.listen_addr, persistent=True)
            await _wait_height(nodes[:3], 1)
            # now bring up the 4th and connect it
            await nodes[3].start()
            for a in nodes[:3]:
                await nodes[3].dial_peer(a.listen_addr, persistent=True)
            target = max(n.height() for n in nodes[:3]) + 3
            await _wait_height(nodes, target)
            hashes = {n.block_store.load_block(target).hash()
                      for n in nodes}
            assert len(hashes) == 1
        finally:
            await _stop_all(nodes)
        return True

    assert run(main())
