"""Storage integrity doctor (node/doctor.py) + LogDB mid-log salvage
(storage/db.py).

Fast tier only: salvage/quarantine/dirty-marker semantics, the
``db.replay.corrupt`` / ``db.compact.eio`` chaos sites, the boot
cross-store consistency matrix (ahead blockstore, ahead statestore, WAL
lineage, privval-ahead refusal), the deep hash-chain scan with
truncate-to-verified repair, the pruned-base / statesync-anchor edge
cases, serving gated on a dirty store, and the doctor CLI.  The live
corrupt-restart-blocksync acceptance run lives in test_chaos.py.
"""

import asyncio
import errno
import json
import os
import shutil
import time

import pytest

from cometbft_tpu.libs import failures as F
from cometbft_tpu.node.doctor import DoctorError, StorageDoctor
from cometbft_tpu.storage import BlockStore, StateStore, open_db
from cometbft_tpu.storage.blockstore import K_BLOCK
from cometbft_tpu.storage.db import LogDB, height_key

pytestmark = pytest.mark.timeout(120)


@pytest.fixture(autouse=True)
def _clean_plane():
    F.reset()
    yield
    F.reset()


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# --------------------------------------------------------- LogDB salvage


def _corrupt_at(path: str, marker: bytes, delta: int = 10) -> None:
    raw = bytearray(open(path, "rb").read())
    off = raw.find(marker)
    assert off >= 0
    raw[off + delta] ^= 0x40
    open(path, "wb").write(bytes(raw))


def test_logdb_mid_log_salvage_quarantines_and_flags_dirty(tmp_path):
    p = str(tmp_path / "kv.db")
    db = LogDB(p)
    for i in range(10):
        db.set(b"k%d" % i, b"v" * 50 + b"%d" % i)
    db.close()
    _corrupt_at(p, b"k5")
    db2 = LogDB(p)
    # the corrupt record is skipped; everything after it survives
    assert db2.salvaged and len(db2.salvage_spans) == 1
    assert db2.get(b"k5") is None
    assert db2.get(b"k4") is not None and db2.get(b"k6") is not None
    assert db2.is_dirty()
    assert os.path.exists(p + ".quarantine")
    db2.close()
    # the log was rewritten clean: reopening does NOT re-salvage, but the
    # dirty marker persists until deep verification clears it
    db3 = LogDB(p)
    assert not db3.salvaged and db3.is_dirty()
    info = db3.dirty_info()
    assert info and info.get("spans")
    db3.clear_dirty()
    assert not db3.is_dirty()
    db3.close()


def test_logdb_salvage_can_resurrect_stale_value_hence_dirty(tmp_path):
    """The reason salvage alone is untrustworthy: losing the LATEST
    record for a key silently resurrects the previous value (and losing
    a tombstone resurrects a deleted key).  The dirty marker is what
    forces the doctor's deep verification before anything is served."""
    p = str(tmp_path / "kv.db")
    db = LogDB(p)
    db.set(b"key", b"OLDVALUE")
    db.set(b"pad", b"p" * 40)
    db.set(b"key", b"NEWVALUE")
    db.set(b"gone", b"g" * 40)
    db.delete(b"gone")
    db.close()
    _corrupt_at(p, b"NEWVALUE", delta=0)
    db2 = LogDB(p)
    assert db2.salvaged
    assert db2.get(b"key") == b"OLDVALUE"      # stale resurrection!
    assert db2.is_dirty()
    db2.close()


def test_logdb_torn_tail_still_truncates_without_dirty(tmp_path):
    p = str(tmp_path / "kv.db")
    db = LogDB(p)
    db.set(b"a", b"1")
    db.close()
    with open(p, "ab") as f:
        f.write(b"\xff" * 37)          # no valid record can follow
    db2 = LogDB(p)
    assert not db2.salvaged and not db2.is_dirty()
    assert db2.get(b"a") == b"1"
    db2.set(b"b", b"2")                # fresh handle writes fine
    db2.close()


def test_db_replay_corrupt_site_is_seeded_and_file_selected(tmp_path):
    """The ``db.replay.corrupt`` chaos site: seeded bit-flip on open,
    scoped to one file via the ``file=`` selector; same seed -> the
    identical salvage span."""
    def build(name):
        p = str(tmp_path / name)
        db = LogDB(p)
        for i in range(20):
            db.set(b"k%02d" % i, b"v" * 64)
        db.close()
        return p

    p1, p2 = build("blockstore.db"), build("state.db")
    spans = []
    for _ in range(2):
        shutil.copy(p1, p1 + ".bak")
        F.configure(enabled=True, seed=99, faults=[
            "db.replay.corrupt:file=blockstore.db:at=1:frac=0.5"])
        db = LogDB(p1)
        assert db.salvaged, "seeded flip must corrupt a record"
        spans.append(tuple(db.salvage_spans))
        db.close()
        other = LogDB(p2)          # file selector: state.db untouched
        assert not other.salvaged
        other.close()
        assert F.signature() == [("db.replay.corrupt", 1, 1)]
        F.reset()
        os.replace(p1 + ".bak", p1)
        os.unlink(p1 + ".dirty")
    assert spans[0] == spans[1]


def test_logdb_compact_failure_goes_dead_not_valueerror(tmp_path):
    """The compact fsyncgate satellite: an IO failure between the close
    and the reopen must leave a DEAD handle (OSError on every later
    write), not a closed-file ValueError; restart recovers."""
    p = str(tmp_path / "kv.db")
    db = LogDB(p)
    db.set(b"a", b"1")
    F.configure(enabled=True, seed=1, faults=["db.compact.eio:at=1"])
    with pytest.raises(OSError) as ei:
        db._compact()
    assert ei.value.errno == errno.EIO
    # dead handle: the OSError discipline, never ValueError
    with pytest.raises(OSError) as ei2:
        db.set(b"b", b"2")
    assert ei2.value.errno == errno.EIO
    db.close()
    F.reset()
    db2 = LogDB(p)
    assert db2.get(b"a") == b"1"
    db2.set(b"c", b"3")
    db2.close()


# ------------------------------------------------- solo home scaffolding


HOME_SECRET = b"doctor-home-pv"


def _doc_pv():
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    pv = MockPV.from_secret(HOME_SECRET)
    doc = GenesisDoc(chain_id="doctor-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])
    return doc, pv


async def _run_node(home, doc, pv, *, min_height=0, extra_heights=0,
                    fast_sync=False):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey

    cfg = Config(consensus=test_consensus_config())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = ""
    cfg.base.signature_backend = "cpu"
    cfg.instrumentation.watchdog_stall_threshold_s = 0.0
    node = await Node.create(doc, KVStoreApplication(), priv_validator=pv,
                             config=cfg,
                             node_key=NodeKey.from_secret(b"doctor-nk"),
                             home=home, name="drhome",
                             fast_sync=fast_sync)
    await node.start()
    target = max(min_height, node.height() + extra_heights)
    deadline = time.monotonic() + 60
    while node.height() < target:
        assert time.monotonic() < deadline, \
            f"stuck at {node.height()} < {target}"
        await asyncio.sleep(0.02)
    h = node.height()
    report = node.doctor_report
    await node.stop()
    return h, report


@pytest.fixture(scope="module")
def solo_home(tmp_path_factory):
    """One committed solo-validator home (height >= 6), copied per
    test."""
    home = str(tmp_path_factory.mktemp("doctor") / "home")
    doc, pv = _doc_pv()
    h, _ = run(_run_node(home, doc, pv, min_height=6))
    return home, h


@pytest.fixture
def home_copy(solo_home, tmp_path):
    src, h = solo_home
    dst = str(tmp_path / "home")
    shutil.copytree(src, dst)
    return dst, h


def _stores(home):
    bs = BlockStore(open_db("logdb",
                            os.path.join(home, "data", "blockstore.db")))
    ss = StateStore(open_db("logdb",
                            os.path.join(home, "data", "state.db")))
    return bs, ss


def _close(bs, ss):
    bs.db.close()
    ss.db.close()


def _wal_path(home):
    return os.path.join(home, "data", "cs.wal")


# ----------------------------------------------------------- boot check


def test_doctor_consistent_home_is_a_noop(home_copy):
    home, h = home_copy
    bs, ss = _stores(home)
    rep = StorageDoctor(bs, ss, wal_path=_wal_path(home)).boot_check(
        repair=True)
    assert rep.ok and not rep.actions and not rep.findings
    assert rep.heights["blockstore"] == h >= 6
    scan = StorageDoctor(bs, ss).deep_scan(window=0)
    assert scan["ok"] and not scan["bad"] and scan["verified_to"] == 1
    json.dumps(rep.to_dict())          # report is JSON-serializable
    _close(bs, ss)


def test_doctor_blockstore_ahead_truncates_to_state_plus_one(home_copy):
    home, h = home_copy
    bs, ss = _stores(home)
    # rebuild the state snapshot two heights back without touching the
    # blockstore: the blockstore is now "ahead" beyond the one-block
    # crash window the Handshaker covers
    doctor = StorageDoctor(bs, ss)
    from cometbft_tpu.node.doctor import DoctorReport

    doctor._rebuild_state_at(DoctorReport(), ss.load(), h - 2, False)
    assert ss.load().last_block_height == h - 2
    rep = StorageDoctor(bs, ss, wal_path=_wal_path(home)).boot_check(
        repair=True)
    assert bs.height() == h - 1          # truncated to state + 1
    assert any("ahead of state" in a for a in rep.actions)
    _close(bs, ss)


def test_doctor_state_ahead_rewinds_and_quarantines_wal(home_copy):
    home, h = home_copy
    bs, ss = _stores(home)
    bs.remove_tip()
    bs.remove_tip()                      # blockstore lost its tip
    rep = StorageDoctor(bs, ss, wal_path=_wal_path(home)).boot_check(
        repair=True)
    assert rep.ok
    assert ss.load().last_block_height == bs.height() == h - 2
    assert any("state ahead" in a for a in rep.actions)
    # the WAL's EndHeight lineage ran past the rolled-back stores
    assert any("quarantined" in a for a in rep.actions)
    from cometbft_tpu.consensus.wal import wal_segments

    assert wal_segments(_wal_path(home)) == []
    assert any(n.endswith(".quarantine")
               for n in os.listdir(os.path.dirname(_wal_path(home)))
               if n.startswith("cs.wal"))
    _close(bs, ss)
    # the repaired home boots and keeps committing
    doc, pv = _doc_pv()
    h2, rep2 = run(_run_node(home, doc, pv, extra_heights=2))
    assert h2 >= h - 2 + 2 and rep2 is not None and rep2.ok


def test_doctor_privval_ahead_refuses_with_double_sign_warning(home_copy):
    home, h = home_copy
    pv_state = os.path.join(home, "data", "pv_state.json")
    with open(pv_state, "w") as f:
        json.dump({"height": h + 50, "round": 0, "step": 3}, f)
    bs, ss = _stores(home)
    with pytest.raises(DoctorError) as ei:
        StorageDoctor(bs, ss, privval_state_path=pv_state).boot_check(
            repair=True)
    assert "double-sign" in str(ei.value)
    assert ei.value.report is not None and ei.value.report.refused
    # report-only mode surfaces the refusal without raising
    rep = StorageDoctor(bs, ss, privval_state_path=pv_state).boot_check(
        repair=False, raise_on_refusal=False)
    assert not rep.ok and "double-sign" in rep.refused
    # ... but a salvaged (dirty) store EXPLAINS the gap: the repair +
    # deep scan own the recovery, so the node may start and re-fetch
    bs.db.mark_dirty()
    rep2 = StorageDoctor(bs, ss, privval_state_path=pv_state).boot_check(
        repair=True)
    assert rep2.ok and rep2.refused is None
    assert not bs.is_dirty()             # clean scan cleared the marker
    _close(bs, ss)


def test_doctor_privval_plus_one_is_normal(home_copy):
    """The signer votes for height h+1 while the stores hold h — the
    everyday crash window must NOT trip the double-sign refusal."""
    home, h = home_copy
    pv_state = os.path.join(home, "data", "pv_state.json")
    with open(pv_state, "w") as f:
        json.dump({"height": h + 1, "round": 0, "step": 3}, f)
    bs, ss = _stores(home)
    rep = StorageDoctor(bs, ss, privval_state_path=pv_state).boot_check(
        repair=True)
    assert rep.ok and rep.refused is None
    _close(bs, ss)


# ------------------------------------------------------------ deep scan


def test_deep_scan_detects_mid_chain_corruption_and_truncates(home_copy):
    home, h = home_copy
    bad_h = h - 3
    bs, ss = _stores(home)
    bs.db.set(height_key(K_BLOCK, bad_h), b"garbage-not-a-block")
    doctor = StorageDoctor(bs, ss, wal_path=_wal_path(home))
    rep = doctor.boot_check(repair=True, force_deep=True)
    scan = rep.deep_scan
    assert scan["bad"] == [bad_h]
    assert scan["truncated_to"] == bad_h - 1 and scan["ok"]
    assert bs.height() == bad_h - 1
    assert ss.load().last_block_height == bad_h - 1
    # WAL ran past the truncation -> quarantined in the same pass
    assert any("quarantined" in a for a in rep.actions)
    _close(bs, ss)
    # the repaired solo home re-proposes past its old tip
    doc, pv = _doc_pv()
    h2, _ = run(_run_node(home, doc, pv, min_height=bad_h + 1))
    assert h2 >= bad_h + 1


def test_deep_scan_report_only_leaves_store_untouched(home_copy):
    home, h = home_copy
    bs, ss = _stores(home)
    bs.db.set(height_key(K_BLOCK, h - 1), b"junk")
    scan = StorageDoctor(bs, ss).deep_scan(window=0, repair=False)
    assert scan["bad"] == [h - 1] and not scan["ok"]
    assert scan["truncated_to"] is None
    assert bs.height() == h              # nothing was modified
    _close(bs, ss)


def test_deep_scan_window_clamps_at_pruned_base(home_copy):
    """Satellite: prune_blocks + doctor interplay — the scan window
    clamps to the pruned base, and a truncating repair above a base > 1
    keeps the base."""
    home, h = home_copy
    bs, ss = _stores(home)
    assert bs.prune_blocks(3) == 2       # base 1 -> 3
    doctor = StorageDoctor(bs, ss)
    scan = doctor.deep_scan(window=100)
    assert scan["window"] == [3, h] and scan["ok"]
    # corruption above the pruned base: normal truncate, base kept
    bad_h = h - 1
    bs.db.set(height_key(K_BLOCK, bad_h), b"junk")
    scan2 = doctor.deep_scan(window=100, repair=True)
    assert scan2["truncated_to"] == bad_h - 1
    assert bs.base() == 3 and bs.height() == bad_h - 1
    _close(bs, ss)


def test_deep_scan_corruption_at_pruned_base_refuses(home_copy):
    home, h = home_copy
    bs, ss = _stores(home)
    bs.prune_blocks(4)
    bs.db.set(height_key(K_BLOCK, 4), b"junk")     # the base itself
    from cometbft_tpu.node.doctor import DoctorReport

    rep = DoctorReport()
    scan = StorageDoctor(bs, ss).deep_scan(window=0, repair=True,
                                           report=rep)
    assert not scan["ok"]
    assert rep.refused and "resync" in rep.refused
    _close(bs, ss)


def test_doctor_statesync_anchor_store_is_healthy(home_copy):
    """Satellite: a statesync'd store (base == height > 1, no blocks,
    just the trusted seen-commit + bookkeeping) passes both the boot
    check and the deep scan."""
    home, h = home_copy
    bs, ss = _stores(home)
    state = ss.load()
    commit = bs.load_block_commit(h - 1) or bs.load_seen_commit()
    import tempfile

    d = tempfile.mkdtemp()
    bs2 = BlockStore(open_db("logdb", os.path.join(d, "blockstore.db")))
    ss2 = StateStore(open_db("logdb", os.path.join(d, "state.db")))
    from dataclasses import replace as dc_replace

    anchor_state = dc_replace(state, last_block_height=commit.height)
    ss2.bootstrap(anchor_state)
    bs2.bootstrap_statesync(commit.height, commit)
    rep = StorageDoctor(bs2, ss2).boot_check(repair=True, force_deep=True)
    assert rep.ok and rep.deep_scan.get("anchor_only")
    _close(bs, ss)
    _close(bs2, ss2)


# ---------------------------------------------- serving gate + surfaces


def test_blocksync_serving_gated_on_dirty_store(tmp_path):
    from cometbft_tpu.blocksync.reactor import BlocksyncReactor

    import msgpack

    bs = BlockStore(open_db("logdb", str(tmp_path / "blockstore.db")))
    reactor = BlocksyncReactor(None, bs, None)

    sent = []

    class _Peer:
        id = "p1"

        def send(self, ch, msg):
            sent.append(msgpack.unpackb(msg, raw=False))

    bs.db.mark_dirty()
    reactor._serve_block(_Peer(), 3)
    assert sent and sent[0]["@"] == "nores"
    bs.db.close()


def test_inspect_mode_carries_doctor_report(home_copy):
    from cometbft_tpu.config import Config
    from cometbft_tpu.rpc.inspect import InspectNode

    home, h = home_copy
    doc, _ = _doc_pv()
    cfg = Config()
    node = InspectNode(home, cfg, doc)
    rep = node.doctor_report
    assert rep is not None and rep.ok
    assert rep.heights["blockstore"] == h
    # inspect NEVER repairs: corrupt a record, re-open, report-only
    node.block_store.db.set(height_key(K_BLOCK, h - 1), b"junk")
    node.block_store.db.close()
    node.state_store.db.close()
    node2 = InspectNode(home, cfg, doc)
    assert node2.doctor_report is not None
    assert node2.block_store.height() == h     # untouched
    node2.block_store.db.close()
    node2.state_store.db.close()


def test_status_route_surfaces_doctor_report(home_copy):
    from cometbft_tpu.rpc.core import Environment, status

    home, h = home_copy
    doc, pv = _doc_pv()

    async def main():
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.config import Config, test_consensus_config
        from cometbft_tpu.node import Node
        from cometbft_tpu.p2p import NodeKey

        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""
        cfg.base.signature_backend = "cpu"
        cfg.instrumentation.watchdog_stall_threshold_s = 0.0
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pv, config=cfg,
            node_key=NodeKey.from_secret(b"doctor-nk"), home=home,
            name="drhome")
        await node.start()
        try:
            st = await status(Environment(node))
            assert st["doctor"] is not None and st["doctor"]["ok"]
            json.dumps(st["doctor"])
        finally:
            await node.stop()
        return True

    assert run(main())


# ------------------------------------------------------------------ CLI


def _write_config(home):
    from cometbft_tpu.config import Config

    Config().save(os.path.join(home, "config", "config.toml"))


def test_doctor_cli_report_and_repair(home_copy, capsys):
    from cometbft_tpu.cmd import main as cmd_main

    home, h = home_copy
    _write_config(home)
    assert cmd_main(["--home", home, "doctor"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] and rep["deep_scan"]["ok"]

    # corrupt a mid-chain block: report-only exits 1 and changes nothing
    bs, ss = _stores(home)
    bs.db.set(height_key(K_BLOCK, h - 2), b"junk")
    _close(bs, ss)
    assert cmd_main(["--home", home, "doctor"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["deep_scan"]["bad"] == [h - 2]

    # --repair truncates to the last verified height and exits 0
    assert cmd_main(["--home", home, "doctor", "--repair"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"] and rep["deep_scan"]["truncated_to"] == h - 3
    bs, ss = _stores(home)
    assert bs.height() == h - 3 == ss.load().last_block_height
    _close(bs, ss)


def test_deep_scan_catches_stale_statestore_records(home_copy):
    """The headers commit to the per-height statestore records
    (validators_hash / consensus_hash): a salvaged statestore whose
    record at some height was stale-resurrected must keep its dirty
    marker and refuse repair (the content behind the hash is gone)."""
    from cometbft_tpu.storage.statestore import K_VALS
    from cometbft_tpu.types import codec

    home, h = home_copy
    bs, ss = _stores(home)
    # simulate a stale resurrection: overwrite the valset record at h-2
    # with a DIFFERENT (still decodable) validator set
    from cometbft_tpu.types.priv_validator import MockPV
    from cometbft_tpu.types.validator_set import Validator, ValidatorSet

    wrong = ValidatorSet([Validator(
        MockPV.from_secret(b"not-the-real-one").get_pub_key(), 10)])
    ss.db.set(height_key(K_VALS, h - 2), codec.pack(wrong))
    ss.db.mark_dirty()
    doctor = StorageDoctor(bs, ss, wal_path=_wal_path(home))
    rep = doctor.boot_check(repair=True, raise_on_refusal=False)
    assert rep.refused and "resync" in rep.refused
    assert rep.deep_scan["state_records_ok"] is False
    assert ss.db.is_dirty()              # marker NOT cleared
    assert any("validators_hash" in f for f in rep.findings)
    _close(bs, ss)


def test_deep_scan_clears_dirty_statestore_when_records_verify(home_copy):
    home, h = home_copy
    bs, ss = _stores(home)
    ss.db.mark_dirty()
    rep = StorageDoctor(bs, ss, wal_path=_wal_path(home)).boot_check(
        repair=True)
    assert rep.ok and rep.deep_scan["state_records_ok"] is True
    assert not ss.db.is_dirty()
    _close(bs, ss)
