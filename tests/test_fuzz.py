"""Randomized robustness tests (reference: ``test/fuzz/`` — mempool
CheckTx, SecretConnection read/write, JSON-RPC server).

Go's fuzzer explores inputs coverage-guided; here a seeded PRNG drives a
few thousand adversarial inputs per surface with the same bar: the
component must never crash the process, hang, or corrupt state — malformed
input produces an error (or a closed connection), nothing else.
"""

import asyncio
import os
import random
import struct

import pytest

SEED = int(os.environ.get("FUZZ_SEED", "20260730"))
N = int(os.environ.get("FUZZ_ITERS", "300"))


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _rand_bytes(rng: random.Random, max_len: int = 512) -> bytes:
    return rng.randbytes(rng.randint(0, max_len))


# ------------------------------------------------------------- mempool

def test_fuzz_mempool_checktx():
    """Arbitrary tx bytes through CheckTx never crash the mempool; state
    stays consistent (size == committed set of valid txs)."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.mempool.clist_mempool import CListMempool
    from cometbft_tpu.proxy import AppConns, local_client_creator

    async def main():
        rng = random.Random(SEED)
        conns = AppConns(local_client_creator(KVStoreApplication()))
        await conns.start()
        mp = CListMempool(conns.mempool, max_txs=1000)
        for _ in range(N):
            tx = _rand_bytes(rng, 64)
            try:
                await mp.check_tx(tx)
            except Exception as e:
                # only the mempool-domain rejection is acceptable
                assert type(e).__name__ == "TxRejectedError", e
        assert mp.size() <= 1000
        reaped = mp.reap_max_bytes_max_gas(10 << 20, -1)
        assert len(reaped) == mp.size()
        await conns.stop()
        return True

    assert run(main())


# ----------------------------------------------------- secret connection

def test_fuzz_secret_connection_frames():
    """Garbage and bit-flipped ciphertext on an established
    SecretConnection must raise/close, never hang or decrypt."""
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.p2p.secret_connection import (SecretConnectionError,
                                                    handshake)

    async def main():
        rng = random.Random(SEED + 1)
        server_done = asyncio.Event()
        results = {}
        received = []

        async def server(reader, writer):
            try:
                sc = await handshake(reader, writer,
                                     NodeKey.from_secret(b"srv").priv_key)
                while True:
                    received.append(await sc.read_msg())
            except Exception as e:
                results["server"] = e
            finally:
                server_done.set()
                writer.close()

        srv = await asyncio.start_server(server, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        sc = await handshake(reader, writer, NodeKey.from_secret(b"cli").priv_key)
        # a valid message flows
        await sc.write_msg(b"hello")
        # now inject garbage straight into the TCP stream (bypassing the
        # encryption layer) — frames that cannot authenticate
        for _ in range(64):
            writer.write(_rand_bytes(rng, 128))
        try:
            await writer.drain()
        except ConnectionError:
            pass
        await asyncio.wait_for(server_done.wait(), 10)
        # the legitimate message was the ONLY thing delivered: none of
        # the unauthenticated garbage decrypted into a message, and the
        # stream died with an AEAD/framing error, not EOF-acceptance
        assert received == [b"hello"], received
        assert isinstance(results["server"],
                          (SecretConnectionError, ConnectionError,
                           asyncio.IncompleteReadError)), results["server"]
        writer.close()
        srv.close()
        return True

    assert run(main())


def test_fuzz_secret_connection_handshake_garbage():
    """Random bytes instead of a handshake must error out promptly."""
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.p2p.secret_connection import handshake

    async def main():
        rng = random.Random(SEED + 2)

        async def server(reader, writer):
            try:
                await asyncio.wait_for(
                    handshake(reader, writer, NodeKey.from_secret(b"s").priv_key), 5)
            except Exception:
                pass
            finally:
                writer.close()

        srv = await asyncio.start_server(server, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        for _ in range(16):
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(_rand_bytes(rng, 256))
                await w.drain()
                w.close()
            except ConnectionError:
                pass
        srv.close()
        await srv.wait_closed()
        return True

    assert run(main())


# ------------------------------------------------------------ JSON-RPC

def test_fuzz_jsonrpc_server():
    """Malformed HTTP/JSON-RPC requests (bad JSON, huge ids, wrong types,
    random bytes) get error responses or closed connections — the server
    survives and still answers a well-formed request afterwards."""
    from cometbft_tpu.rpc.server import RPCServer

    class _FakeNode:
        event_bus = None

    async def main():
        rng = random.Random(SEED + 3)
        server = RPCServer(_FakeNode())
        host, port = await server.listen("127.0.0.1", 0)

        async def send_raw(payload: bytes) -> None:
            try:
                r, w = await asyncio.open_connection(host, port)
                w.write(payload)
                await w.drain()
                try:
                    await asyncio.wait_for(r.read(4096), 2)
                except TimeoutError:
                    pass
                w.close()
            except ConnectionError:
                pass

        cases = []
        for _ in range(N // 4):
            cases.append(_rand_bytes(rng, 200))                 # raw noise
        for body in (b"{", b"[]", b'{"jsonrpc":"2.0"}',
                     b'{"method":123}', b'{"id":{}, "method":"status"}',
                     b'{"jsonrpc":"2.0","id":1,"method":"nope"}',
                     b'{"jsonrpc":"2.0","id":1,"method":"tx_search",'
                     b'"params":{"query":"junk ("}}'):
            cases.append(b"POST / HTTP/1.1\r\nHost: x\r\nContent-Length: "
                         + str(len(body)).encode() + b"\r\n\r\n" + body)
        cases.append(b"GET /%ff%fe HTTP/1.1\r\n\r\n")
        cases.append(b"POST / HTTP/1.1\r\nContent-Length: 99999999\r\n\r\n")
        for c in cases:
            await send_raw(c)

        # the server is still healthy: a valid request round-trips
        r, w = await asyncio.open_connection(host, port)
        body = b'{"jsonrpc":"2.0","id":1,"method":"health","params":{}}'
        w.write(b"POST / HTTP/1.1\r\nHost: x\r\nContent-Length: "
                + str(len(body)).encode() + b"\r\n\r\n" + body)
        await w.drain()
        resp = await asyncio.wait_for(r.read(4096), 5)
        assert b"200" in resp.split(b"\r\n")[0] or b'"error"' in resp
        w.close()
        await server.close()
        return True

    assert run(main())
