"""Randomized robustness tests (reference: ``test/fuzz/`` — mempool
CheckTx, SecretConnection read/write, JSON-RPC server).

Go's fuzzer explores inputs coverage-guided; here a seeded PRNG drives a
few thousand adversarial inputs per surface with the same bar: the
component must never crash the process, hang, or corrupt state — malformed
input produces an error (or a closed connection), nothing else.
"""

import asyncio
import os
import random

import pytest

SEED = int(os.environ.get("FUZZ_SEED", "20260730"))
N = int(os.environ.get("FUZZ_ITERS", "300"))


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _rand_bytes(rng: random.Random, max_len: int = 512) -> bytes:
    return rng.randbytes(rng.randint(0, max_len))


# ------------------------------------------------------------- mempool

def test_fuzz_mempool_checktx():
    """Arbitrary tx bytes through CheckTx never crash the mempool; state
    stays consistent (size == committed set of valid txs)."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.mempool.clist_mempool import CListMempool
    from cometbft_tpu.proxy import AppConns, local_client_creator

    async def main():
        rng = random.Random(SEED)
        conns = AppConns(local_client_creator(KVStoreApplication()))
        await conns.start()
        mp = CListMempool(conns.mempool, max_txs=1000)
        for _ in range(N):
            tx = _rand_bytes(rng, 64)
            try:
                await mp.check_tx(tx)
            except Exception as e:
                # only the mempool-domain rejection is acceptable
                assert type(e).__name__ == "TxRejectedError", e
        assert mp.size() <= 1000
        reaped = mp.reap_max_bytes_max_gas(10 << 20, -1)
        assert len(reaped) == mp.size()
        await conns.stop()
        return True

    assert run(main())


# ----------------------------------------------------- secret connection

def test_fuzz_secret_connection_frames():
    """Garbage and bit-flipped ciphertext on an established
    SecretConnection must raise/close, never hang or decrypt."""
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.p2p.secret_connection import (SecretConnectionError,
                                                    handshake)

    async def main():
        rng = random.Random(SEED + 1)
        server_done = asyncio.Event()
        results = {}
        received = []

        async def server(reader, writer):
            try:
                sc = await handshake(reader, writer,
                                     NodeKey.from_secret(b"srv").priv_key)
                while True:
                    received.append(await sc.read_msg())
            except Exception as e:
                results["server"] = e
            finally:
                server_done.set()
                writer.close()

        srv = await asyncio.start_server(server, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        sc = await handshake(reader, writer, NodeKey.from_secret(b"cli").priv_key)
        # a valid message flows
        await sc.write_msg(b"hello")
        # now inject garbage straight into the TCP stream (bypassing the
        # encryption layer) — frames that cannot authenticate
        for _ in range(64):
            writer.write(_rand_bytes(rng, 128))
        try:
            await writer.drain()
        except ConnectionError:
            pass
        await asyncio.wait_for(server_done.wait(), 10)
        # the legitimate message was the ONLY thing delivered: none of
        # the unauthenticated garbage decrypted into a message, and the
        # stream died with an AEAD/framing error, not EOF-acceptance
        assert received == [b"hello"], received
        assert isinstance(results["server"],
                          (SecretConnectionError, ConnectionError,
                           asyncio.IncompleteReadError)), results["server"]
        writer.close()
        srv.close()
        return True

    assert run(main())


def test_fuzz_secret_connection_handshake_garbage():
    """Random bytes instead of a handshake must error out promptly."""
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.p2p.secret_connection import handshake

    async def main():
        rng = random.Random(SEED + 2)

        async def server(reader, writer):
            try:
                await asyncio.wait_for(
                    handshake(reader, writer, NodeKey.from_secret(b"s").priv_key), 5)
            except Exception:
                pass
            finally:
                writer.close()

        srv = await asyncio.start_server(server, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        for _ in range(16):
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(_rand_bytes(rng, 256))
                await w.drain()
                w.close()
            except ConnectionError:
                pass
        srv.close()
        await srv.wait_closed()
        return True

    assert run(main())


# ------------------------------------------------------------ JSON-RPC

def test_fuzz_jsonrpc_server():
    """Malformed HTTP/JSON-RPC requests (bad JSON, huge ids, wrong types,
    random bytes) get error responses or closed connections — the server
    survives and still answers a well-formed request afterwards."""
    from cometbft_tpu.rpc.server import RPCServer

    class _FakeNode:
        event_bus = None

    async def main():
        rng = random.Random(SEED + 3)
        server = RPCServer(_FakeNode())
        host, port = await server.listen("127.0.0.1", 0)

        async def send_raw(payload: bytes) -> None:
            try:
                r, w = await asyncio.open_connection(host, port)
                w.write(payload)
                await w.drain()
                try:
                    await asyncio.wait_for(r.read(4096), 2)
                except asyncio.TimeoutError:   # != builtin TimeoutError
                    pass                       # until Python 3.11
                w.close()
            except ConnectionError:
                pass

        cases = []
        for _ in range(N // 4):
            cases.append(_rand_bytes(rng, 200))                 # raw noise
        for body in (b"{", b"[]", b'{"jsonrpc":"2.0"}',
                     b'{"method":123}', b'{"id":{}, "method":"status"}',
                     b'{"jsonrpc":"2.0","id":1,"method":"nope"}',
                     b'{"jsonrpc":"2.0","id":1,"method":"tx_search",'
                     b'"params":{"query":"junk ("}}'):
            cases.append(b"POST / HTTP/1.1\r\nHost: x\r\nContent-Length: "
                         + str(len(body)).encode() + b"\r\n\r\n" + body)
        cases.append(b"GET /%ff%fe HTTP/1.1\r\n\r\n")
        cases.append(b"POST / HTTP/1.1\r\nContent-Length: 99999999\r\n\r\n")
        for c in cases:
            await send_raw(c)

        # the server is still healthy: a valid request round-trips
        r, w = await asyncio.open_connection(host, port)
        body = b'{"jsonrpc":"2.0","id":1,"method":"health","params":{}}'
        w.write(b"POST / HTTP/1.1\r\nHost: x\r\nContent-Length: "
                + str(len(body)).encode() + b"\r\n\r\n" + body)
        await w.drain()
        resp = await asyncio.wait_for(r.read(4096), 5)
        assert b"200" in resp.split(b"\r\n")[0] or b'"error"' in resp
        w.close()
        await server.close()
        return True

    assert run(main())


# ----------------------------------------------- fuzzed peer connection

def test_fuzzed_connection_drop_and_kill():
    """p2p/fuzz.go FuzzedConnection semantics: dropped writes are
    swallowed whole, prob_drop_conn kills the stream, delay mode only
    slows IO down."""
    from cometbft_tpu.p2p.fuzz import (FuzzConnConfig, MODE_DELAY,
                                       fuzz_streams)

    async def main():
        async def pair():
            q = asyncio.Queue()

            async def on_conn(r, w):
                await q.put((r, w))

            server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
            host, port = server.sockets[0].getsockname()
            cr, cw = await asyncio.open_connection(host, port)
            sr, sw = await q.get()
            return server, (cr, cw), (sr, sw)

        # 1) drop everything: the peer never sees the write
        server, (cr, cw), (sr, sw) = await pair()
        fr, fw = fuzz_streams(cr, cw, FuzzConnConfig(
            prob_drop_rw=1.0, start_after_s=0.0, seed=1))
        fw.write(b"swallowed")
        await fw.drain()
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(sr.readexactly(9), 0.5)
        server.close()

        # 2) kill the connection
        server, (cr, cw), (sr, sw) = await pair()
        fr, fw = fuzz_streams(cr, cw, FuzzConnConfig(
            prob_drop_rw=0.0, prob_drop_conn=1.0, start_after_s=0.0,
            seed=2))
        fw.write(b"x")
        await fw.drain()
        assert await sr.read(16) == b""      # EOF: conn was closed
        server.close()

        # 3) delay mode delivers everything, just late
        server, (cr, cw), (sr, sw) = await pair()
        fr, fw = fuzz_streams(cr, cw, FuzzConnConfig(
            mode=MODE_DELAY, max_delay_s=0.05, start_after_s=0.0, seed=3))
        for _ in range(5):
            fw.write(b"abc")
            await fw.drain()
        assert await sr.readexactly(15) == b"abc" * 5
        sw.close(); cw.close(); server.close()
        return True

    assert run(main())


def test_network_commits_under_connection_fuzzing():
    """4 sim nodes with the chaos plane dropping ~3% of wire packets
    (message reassembly corruption -> real teardown + reconnect path)
    still commit blocks — on the VIRTUAL clock.

    History: the real-TCP ancestor of this test raced wall-clock
    reconnect backoff against a 90 s deadline; PR 12 had to widen it to
    150 s because clean recoveries measured 77-90 s on a loaded CI box.
    On virtual time the same 150 s liveness deadline is exact and free:
    backoff sleeps cost nothing real, and a wedge still fails the
    assertion — the flake class is gone, not padded."""
    from cometbft_tpu.libs import clock, failures
    from cometbft_tpu.sim import Scenario, run_scenario

    scn = Scenario(
        name="fuzz-drop-net", seed=20260730, n_nodes=4, out_links=2,
        target_height=4, max_virtual_s=150.0,
        faults=["p2p.send.drop:prob=0.03"])
    v = run_scenario(scn)
    assert v["reached_target"], \
        f"stuck at height {v['common_height']} under 3% packet drop"
    assert v["fork_free"]
    # the drop schedule really ran (prob= site, seeded)
    assert v["chaos"]["sites"].get("p2p.send.drop", 0) > 0
    # seam hygiene: the virtual clock was uninstalled on exit
    assert clock.installed() is None
    assert failures.stats() == {"enabled": False}


def test_node_test_fuzz_wiring_real_net():
    """``cfg.p2p.test_fuzz`` must reach the Transport as a
    ``FuzzConnConfig`` and the fuzzed streams must thread through
    SecretConnection on a REAL 2-node TCP net — the Node-wiring coverage
    the old 4-node liveness test provided implicitly (its
    liveness-under-drops axis now lives in the virtual-clock test
    above).  Delay mode exercises the FuzzedReader/Writer path on every
    frame without fuzz-killing handshakes, so the net commits in
    seconds instead of racing reconnect backoff."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config
    from cometbft_tpu.config import test_consensus_config as _tcc
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.p2p.fuzz import MODE_DELAY, FuzzConnConfig
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    async def main():
        pvs = [MockPV.from_secret(b"fzw%d" % i) for i in range(2)]
        doc = GenesisDoc(chain_id="fuzz-wire",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = Config(consensus=_tcc())
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.rpc.laddr = "tcp://127.0.0.1:0"
            cfg.p2p.test_fuzz = True
            cfg.p2p.fuzz_mode = MODE_DELAY
            cfg.p2p.fuzz_max_delay_s = 0.02
            cfg.p2p.fuzz_start_after_s = 0.0
            node = await Node.create(
                doc, KVStoreApplication(), priv_validator=pv, config=cfg,
                node_key=NodeKey.from_secret(b"fzwk%d" % i), name=f"fzw{i}")
            nodes.append(node)
        # the wiring, asserted directly: the config reached the transport
        fc = nodes[0].transport.fuzz_config
        assert isinstance(fc, FuzzConnConfig) and fc.mode == MODE_DELAY
        for n in nodes:
            await n.start()
        try:
            await nodes[0].dial_peer(nodes[1].listen_addr, persistent=True)
            deadline = asyncio.get_event_loop().time() + 60
            while max(n.consensus.rs.height for n in nodes
                      if n.consensus is not None) < 3:
                assert asyncio.get_event_loop().time() < deadline, \
                    "stuck under delay fuzzing"
                await asyncio.sleep(0.2)
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())


# ------------------------------------------------------------ WAL decode

def test_fuzz_wal_corruption_never_crashes():
    """Random byte corruption anywhere in a WAL must never crash decode:
    iter_records yields an intact prefix and stops; the read-only tool
    path surfaces WALError; a reopened WAL truncates the torn tail and
    keeps appending (crash-safety contract of consensus/wal.py)."""
    import tempfile

    from cometbft_tpu.consensus.wal import (WAL, WALError,
                                            iter_wal_records_readonly)

    rng = random.Random(SEED)
    for trial in range(25):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "cs.wal")
            wal = WAL(path)
            records = [{"#": "vote", "n": i, "b": rng.randbytes(20)}
                       for i in range(30)]
            for rec in records:
                wal.write(rec)
            wal.write_end_height(1)
            wal.close()

            size = os.path.getsize(path)
            blob = bytearray(open(path, "rb").read())
            pos = rng.randrange(size)
            blob[pos] ^= 1 << rng.randrange(8)
            with open(path, "wb") as f:
                f.write(blob)

            # read-only iteration: intact prefix, then clean stop/error
            got = []
            try:
                for rec in iter_wal_records_readonly(path):
                    got.append(rec)
            except WALError:
                pass
            for a, b in zip(got, records):
                if a.get("#") == "endheight":
                    break
                assert a == b, f"trial {trial}: corrupted record yielded"

            # reopen-for-append truncates the tail and stays writable
            wal2 = WAL(path)
            wal2.write_sync({"#": "vote", "n": 999, "b": b"after"})
            tail = list(wal2.iter_records())
            assert tail[-1]["n"] == 999
            wal2.close()
