"""PEX + address book: peers discovered transitively without direct dials
(reference: ``p2p/pex/pex_reactor_test.go``, ``addrbook_test.go``)."""

import asyncio

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.config import test_consensus_config as _tcc
from cometbft_tpu.node import Node
from cometbft_tpu.p2p import AddrBook, NodeKey
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import MockPV

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_addr_book_roundtrip(tmp_path):
    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path)
    assert book.add("aa" * 20, "127.0.0.1:1001")
    assert book.add("bb" * 20, "127.0.0.1:1002")
    assert not book.add("aa" * 20, "127.0.0.1:1001")     # unchanged
    book.mark_bad("bb" * 20)
    assert not book.add("bb" * 20, "127.0.0.1:1002")     # banned stays out
    book2 = AddrBook(path)
    assert book2.size() == 1
    assert book2.pick(set())[0][0] == "aa" * 20
    assert book2.pick({"aa" * 20}) == []


def test_pex_discovers_transitive_peer():
    """A-B and B-C are dialed; PEX must connect A-C without a dial from
    the test."""

    def cfg():
        c = Config(consensus=_tcc())
        c.p2p.laddr = "tcp://127.0.0.1:0"
        c.rpc.laddr = "tcp://127.0.0.1:0"
        c.p2p.pex = True
        c.p2p.pex_interval_seconds = 0.5       # fast discovery in tests
        return c

    async def main():
        pvs = [MockPV.from_secret(b"pex%d" % i) for i in range(3)]
        doc = GenesisDoc(chain_id="pex-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            n = await Node.create(doc, KVStoreApplication(),
                                  priv_validator=pv, config=cfg(),
                                  node_key=NodeKey.from_secret(b"pk%d" % i),
                                  name=f"pex{i}")
            nodes.append(n)
            await n.start()
        a, b, c = nodes
        try:
            await a.dial_peer(b.listen_addr, persistent=True)
            await b.dial_peer(c.listen_addr, persistent=True)
            assert c.node_key.id not in a.switch.peers

            async def connected():
                while c.node_key.id not in a.switch.peers:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(connected(), 60)   # loaded-box margin
            # and the address book learned it
            assert any(nid == c.node_key.id
                       for nid, _ in a.addr_book.sample(100))
        finally:
            for n in nodes:
                try:
                    await asyncio.wait_for(n.stop(), 15)
                except Exception:
                    pass
        return True

    assert run(main())
