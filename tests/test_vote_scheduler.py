"""Coalescing vote-verification scheduler (crypto/scheduler.py): flush
ordering, per-item demux, cache safety, dedup, lifecycle, and the
VoteSet/VerifyCommit cache integrations.  All tier-1-fast, CPU backend.
"""

import asyncio
import random

import pytest

from cometbft_tpu.crypto import scheduler as vsched
from cometbft_tpu.crypto.keys import gen_priv_key
from cometbft_tpu.crypto.scheduler import (VerificationScheduler,
                                           VerifiedSigCache, cache_key,
                                           snap_lane_cap)
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.validator_set import Validator, ValidatorSet
from cometbft_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote
from cometbft_tpu.types.vote_set import (ConflictingVoteError, VoteSet,
                                         VoteSetError)

CHAIN = "sched-test"


@pytest.fixture(autouse=True)
def _no_global_scheduler():
    """Tests manage the process-global scheduler explicitly; never leak
    one into (or out of) a test."""
    vsched.set_scheduler(None)
    yield
    vsched.set_scheduler(None)


def _signed(n=4, msg_len=64, seed=1):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        priv = gen_priv_key()
        msg = bytes(rng.randrange(256) for _ in range(msg_len))
        out.append((priv.pub_key(), msg, priv.sign(msg)))
    return out


def _run(coro):
    return asyncio.run(coro)


def _flushes(sched, reason):
    return sched._m[6].value(reason=reason)


# ------------------------------------------------------------- unit: cache

def test_cache_lru_bound_and_positive_only():
    c = VerifiedSigCache(max_size=3)
    keys = [cache_key(bytes([i]) * 32, b"m%d" % i, b"s" * 64)
            for i in range(5)]
    for k in keys:
        c.seed(k)
    assert len(c) == 3
    assert not c.hit(keys[0]) and not c.hit(keys[1])   # evicted, oldest
    assert c.hit(keys[2]) and c.hit(keys[3]) and c.hit(keys[4])
    # hit refreshes recency: 2 is now newest, seeding 2 more evicts 3
    c.hit(keys[2])
    c.seed(keys[0])
    c.seed(keys[1])
    assert c.hit(keys[2])
    assert not c.hit(keys[3])


def test_cache_size_zero_disables():
    c = VerifiedSigCache(max_size=0)
    k = cache_key(b"p" * 32, b"m", b"s" * 64)
    c.seed(k)
    assert not c.hit(k)


def test_snap_lane_cap_buckets():
    assert snap_lane_cap(256) == 256
    assert snap_lane_cap(300) == 256          # down, never up
    assert snap_lane_cap(4) == 4              # below 16: honored exactly
    assert snap_lane_cap(17) == 16            # between buckets: down
    assert snap_lane_cap(100000) == 4096      # lane cap


# --------------------------------------------------------- flush ordering

def test_window_flush_fires_without_filling_lanes():
    async def main():
        s = VerificationScheduler(backend="cpu", max_wait_ms=20,
                                  max_lanes=256)
        await s.start()
        try:
            items = _signed(3)
            t0 = asyncio.get_event_loop().time()
            oks = await asyncio.gather(
                *(s.verify(p, m, sig) for p, m, sig in items))
            dt = asyncio.get_event_loop().time() - t0
            assert oks == [True, True, True]
            # resolved by the WINDOW (3 lanes never reach the 256 cap),
            # after >= the window bound but well under a second
            assert _flushes(s, "window") >= 1
            assert _flushes(s, "size") == 0
            assert 0.015 <= dt < 2.0
        finally:
            await s.stop()
    _run(main())


def test_size_flush_preempts_window():
    async def main():
        # max_wait absurdly long: only the size trigger can resolve the
        # batch quickly — proves cap-filling flushes immediately
        s = VerificationScheduler(backend="cpu", max_wait_ms=30_000,
                                  max_lanes=16)
        assert s.max_lanes == 16
        await s.start()
        try:
            items = _signed(16)
            occ = s._m[0]                      # process-global histogram:
            c0, sum0 = occ.count(), occ.sum()  # assert on the DELTA
            t0 = asyncio.get_event_loop().time()
            oks = await asyncio.wait_for(asyncio.gather(
                *(s.verify(p, m, sig) for p, m, sig in items)), timeout=10)
            dt = asyncio.get_event_loop().time() - t0
            assert all(oks)
            assert _flushes(s, "size") >= 1
            assert dt < 5.0                    # nowhere near 30 s
            # occupancy histogram saw exactly one full 16-lane bucket
            assert occ.count() - c0 == 1
            assert occ.sum() - sum0 == 16
        finally:
            await s.stop()
    _run(main())


# ------------------------------------------------- demux + cache safety

def test_mixed_batch_matches_per_item_verdicts():
    """Property test: a mixed good/bad batch demuxes per-item verdicts
    identical to per-item verification — one bad signature never poisons
    or rejects its batchmates."""
    rng = random.Random(42)
    items = _signed(24, seed=7)
    corrupted = set(rng.sample(range(24), 6))
    batch = []
    for i, (pub, msg, sig) in enumerate(items):
        if i in corrupted:
            sig = bytes([sig[0] ^ 0x5A]) + sig[1:]
        batch.append((pub, msg, sig))
    expect = [pub.verify_signature(m, s) for pub, m, s in batch]
    assert [i for i, ok in enumerate(expect) if not ok] == sorted(corrupted)

    async def main():
        s = VerificationScheduler(backend="cpu", max_wait_ms=5,
                                  max_lanes=256)
        await s.start()
        try:
            got = await asyncio.gather(
                *(s.verify(p, m, sig) for p, m, sig in batch))
            assert got == expect
            # NEGATIVE verdicts were not cached: resubmitting a bad sig
            # re-verifies and re-fails (cache holds only the good lanes)
            assert len(s.cache) == 24 - len(corrupted)
            for i in corrupted:
                pub, msg, sig = batch[i]
                assert not s.cache.hit(cache_key(pub.bytes(), msg, sig))
                assert not await s.verify(pub, msg, sig)
        finally:
            await s.stop()
    _run(main())


def test_duplicate_suppression_counts():
    """k concurrent requests for one signature verify once: one lane,
    k-1 in-flight dedup hits; later repeats are cache hits."""
    async def main():
        s = VerificationScheduler(backend="cpu", max_wait_ms=5,
                                  max_lanes=256)
        await s.start()
        try:
            (pub, msg, sig), = _signed(1)
            dedup0 = s.stats()["dedup_inflight"]
            lanes0 = s.stats()["lanes_ok"]
            oks = await asyncio.gather(
                *(s.verify(pub, msg, sig) for _ in range(9)))
            assert oks == [True] * 9
            st = s.stats()
            assert st["dedup_inflight"] - dedup0 == 8
            assert st["lanes_ok"] - lanes0 == 1            # ONE scalar mul
            hits0 = st["cache_hits"]
            assert await s.verify(pub, msg, sig)           # now cached
            assert s.stats()["cache_hits"] - hits0 == 1
        finally:
            await s.stop()
    _run(main())


def test_submit_nowait_callbacks_and_cache_hit_sync():
    async def main():
        s = VerificationScheduler(backend="cpu", max_wait_ms=5,
                                  max_lanes=256)
        await s.start()
        try:
            (pub, msg, sig), = _signed(1)
            got: list[bool] = []
            fut = asyncio.get_running_loop().create_future()
            s.submit_nowait(pub, msg, sig,
                            on_done=lambda ok: (got.append(ok),
                                                fut.set_result(None)))
            await asyncio.wait_for(fut, 5)
            assert got == [True]
            # cache hit path invokes the callback synchronously
            s.submit_nowait(pub, msg, sig, on_done=got.append)
            assert got == [True, True]
        finally:
            await s.stop()
    _run(main())


def test_clean_stop_resolves_inflight_requests():
    """stop() with requests parked in an unexpired window: every caller
    gets a real verdict, nothing hangs, nothing leaks."""
    async def main():
        s = VerificationScheduler(backend="cpu", max_wait_ms=60_000,
                                  max_lanes=256)
        await s.start()
        items = _signed(5)
        tasks = [asyncio.create_task(s.verify(p, m, sig))
                 for p, m, sig in items]
        await asyncio.sleep(0.05)          # parked: window is a minute out
        assert not any(t.done() for t in tasks)
        await asyncio.wait_for(s.stop(), timeout=10)
        oks = await asyncio.wait_for(asyncio.gather(*tasks), timeout=5)
        assert oks == [True] * 5
        assert _flushes(s, "stop") >= 1
        assert not s._pending and not s._inflight
        # post-stop verification degrades to the direct path
        pub, msg, sig = items[0]
        assert await s.verify(pub, msg, sig)
    _run(main())


def test_dispatch_failure_resolves_every_batchmate_with_real_verdicts():
    """Regression via the ``sched.dispatch.raise`` chaos site: an
    exception in the dispatch body must not hang or fail-closed the
    batch — every future AND callback gets the per-item verdict from
    the direct recovery pass."""
    from cometbft_tpu.libs import failures as F

    async def main():
        F.configure(enabled=True, seed=1,
                    faults=["sched.dispatch.raise:at=1"])
        try:
            s = VerificationScheduler(backend="cpu", max_wait_ms=1.0)
            await s.start()
            items = _signed(4)
            bad = (items[2][0], items[2][1], b"\x00" * 64)
            cb_verdicts = {}
            s.submit_nowait(*bad, on_done=lambda ok: cb_verdicts
                            .setdefault("bad", ok))
            oks = await asyncio.wait_for(asyncio.gather(
                *[s.verify(p, m, sig) for p, m, sig in items]), timeout=10)
            assert oks == [True] * 4       # real verdicts, not fail-closed
            assert cb_verdicts == {"bad": False}
            # the injected failure is on record, and the NEXT batch rides
            # the normal path again
            assert [e["site"] for e in F.events()] == \
                ["sched.dispatch.raise"]
            assert await s.verify(*_signed(1, seed=9)[0])
            await s.stop()
        finally:
            F.reset()

    _run(main())


def test_verify_deadline_falls_back_to_direct_verification():
    """``verify()`` must never hang on a future nothing will resolve: a
    wedged flush path (here: _flush stubbed out) trips the bounded wait
    and the caller re-verifies directly — correct verdict, bounded
    latency."""
    async def main():
        s = VerificationScheduler(backend="cpu", max_wait_ms=1.0,
                                  verify_timeout_s=0.3)
        assert s.verify_timeout_s == 0.3
        await s.start()
        s._flush = lambda reason: None       # nothing ever dispatches
        pub, msg, sig = _signed(1)[0]
        t0 = asyncio.get_event_loop().time()
        ok = await asyncio.wait_for(s.verify(pub, msg, sig), timeout=5)
        dt = asyncio.get_event_loop().time() - t0
        assert ok and 0.25 <= dt < 2.0
        # the direct fallback seeded the cache: the retry is a hit
        assert s.cache.hit(cache_key(pub.bytes(), msg, sig))
        del s._flush                          # let stop() flush cleanly
        await s.stop()

    _run(main())


def test_verify_timeout_default_floors_at_one_second():
    s = VerificationScheduler(backend="cpu", max_wait_ms=2.0)
    assert s.verify_timeout_s == 1.0         # 5x window, floored
    s2 = VerificationScheduler(backend="cpu", max_wait_ms=500.0)
    assert s2.verify_timeout_s == 2.5        # 5x window above the floor


# ------------------------------------------------------ VoteSet integration

def _valset(n):
    privs = [gen_priv_key() for _ in range(n)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    return vals, by_addr


def _vote(vals, by_addr, i, bid, typ=PREVOTE_TYPE, height=3):
    v = vals.get_by_index(i)
    vote = Vote(type=typ, height=height, round=0, block_id=bid,
                timestamp_ns=5_000 + i, validator_address=v.address,
                validator_index=i)
    vote.signature = by_addr[v.address].sign(vote.sign_bytes(CHAIN))
    return vote


def test_vote_set_rides_scheduler_cache():
    """Votes pre-verified through the scheduler hit the cache inside
    VoteSet._verify — zero direct verifications on the add_vote path."""
    async def main():
        s = await vsched.acquire_scheduler(backend="cpu", max_wait_ms=2,
                                           max_lanes=64)
        try:
            vals, by_addr = _valset(4)
            bid = BlockID(b"\x07" * 32, PartSetHeader(1, b"\x08" * 32))
            votes = [_vote(vals, by_addr, i, bid) for i in range(4)]
            await asyncio.gather(*(
                s.verify(vals.get_by_index(v.validator_index).pub_key,
                         v.sign_bytes(CHAIN), v.signature) for v in votes))
            hits0 = s._m[3].value(source="votes")
            vs = VoteSet(CHAIN, 3, 0, PREVOTE_TYPE, vals)
            for v in votes:
                assert vs.add_vote(v)
            assert s._m[3].value(source="votes") - hits0 == 4
            assert vs.has_two_thirds_majority()
        finally:
            await vsched.release_scheduler()
    _run(main())


def test_conflicting_vote_never_trusts_cache():
    """Equivocation path: a conflicting vote with an INVALID signature
    must be rejected even when a (hypothetically poisoned) cache entry
    claims it valid — the evidence path bypasses the cache."""
    async def main():
        s = await vsched.acquire_scheduler(backend="cpu", max_wait_ms=2,
                                           max_lanes=64)
        try:
            vals, by_addr = _valset(4)
            bid_a = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
            bid_b = BlockID(b"\x03" * 32, PartSetHeader(1, b"\x04" * 32))
            vs = VoteSet(CHAIN, 3, 0, PREVOTE_TYPE, vals)
            assert vs.add_vote(_vote(vals, by_addr, 0, bid_a))
            # conflicting vote for a different block, signature INVALID
            bad = _vote(vals, by_addr, 0, bid_b)
            bad.signature = bytes([bad.signature[0] ^ 0xFF]) \
                + bad.signature[1:]
            pub = vals.get_by_index(0).pub_key
            s.cache.seed(cache_key(pub.bytes(), bad.sign_bytes(CHAIN),
                                   bad.signature))       # poison attempt
            with pytest.raises(VoteSetError):
                vs.add_vote(bad)
            # the SAME conflicting vote validly signed still raises
            # ConflictingVoteError (the evidence hook), proving only the
            # cache-trusting shortcut was bypassed, not the logic
            good = _vote(vals, by_addr, 0, bid_b)
            with pytest.raises(ConflictingVoteError):
                vs.add_vote(good)
        finally:
            await vsched.release_scheduler()
    _run(main())


# -------------------------------------------------- VerifyCommit integration

def test_verify_commit_consults_and_seeds_cache():
    from cometbft_tpu.types.validation import VerifyCommit

    async def main():
        s = await vsched.acquire_scheduler(backend="cpu", max_wait_ms=2,
                                           max_lanes=64)
        try:
            vals, by_addr = _valset(4)
            bid = BlockID(b"\x05" * 32, PartSetHeader(1, b"\x06" * 32))
            vs = VoteSet(CHAIN, 7, 0, PRECOMMIT_TYPE, vals)
            votes = [_vote(vals, by_addr, i, bid, typ=PRECOMMIT_TYPE,
                           height=7) for i in range(4)]
            # gossip first: precommits verify through the scheduler
            await asyncio.gather(*(
                s.verify(vals.get_by_index(v.validator_index).pub_key,
                         v.sign_bytes(CHAIN), v.signature) for v in votes))
            for v in votes:
                vs.add_vote(v)
            commit = vs.make_commit()
            hits0 = s._m[3].value(source="commit")
            miss0 = s._m[4].value(source="commit")
            VerifyCommit(CHAIN, vals, bid, 7, commit, backend="cpu")
            hits = s._m[3].value(source="commit") - hits0
            miss = s._m[4].value(source="commit") - miss0
            # every commit signature was already verified as a gossiped
            # vote: all cache hits, zero new scalar multiplications
            assert hits == 4 and miss == 0
        finally:
            await vsched.release_scheduler()
    _run(main())


def test_verify_commit_seeds_then_second_pass_free():
    from cometbft_tpu.types.validation import VerifyCommit

    async def main():
        # fixtures built with NO scheduler registered: nothing seeds the
        # cache, modeling a commit whose signatures this node never saw
        # as gossip (cold start / catch-up)
        vals, by_addr = _valset(4)
        bid = BlockID(b"\x09" * 32, PartSetHeader(1, b"\x0a" * 32))
        vs = VoteSet(CHAIN, 9, 0, PRECOMMIT_TYPE, vals)
        votes = [_vote(vals, by_addr, i, bid, typ=PRECOMMIT_TYPE,
                       height=9) for i in range(4)]
        for v in votes:
            vs.add_vote(v)
        commit = vs.make_commit()
        s = await vsched.acquire_scheduler(backend="cpu", max_wait_ms=2,
                                           max_lanes=64)
        try:
            # an EMPTY cache is skipped by the dense paths entirely (a
            # cold-start node must not pay per-lane key building for
            # guaranteed misses): no cache traffic, no seeding
            miss0 = s._m[4].value(source="commit")
            VerifyCommit(CHAIN, vals, bid, 9, commit, backend="cpu")
            assert s._m[4].value(source="commit") - miss0 == 0
            assert len(s.cache) == 0
            # one gossiped vote warms the cache; the next VerifyCommit
            # consults, hits that lane, verifies + SEEDS the other three
            v0 = votes[0]
            assert await s.verify(
                vals.get_by_index(0).pub_key, v0.sign_bytes(CHAIN),
                v0.signature)
            VerifyCommit(CHAIN, vals, bid, 9, commit, backend="cpu")
            hits0 = s._m[3].value(source="commit")
            VerifyCommit(CHAIN, vals, bid, 9, commit, backend="cpu")
            assert s._m[3].value(source="commit") - hits0 == 4
        finally:
            await vsched.release_scheduler()
    _run(main())


def test_evidence_variant_bypasses_poisoned_cache():
    """VerifyCommitLightAllSignatures (evidence path) must re-verify and
    reject a corrupted signature even when the cache claims it valid."""
    from cometbft_tpu.types.validation import (ErrInvalidSignature,
                                               VerifyCommitLightAllSignatures)

    async def main():
        s = await vsched.acquire_scheduler(backend="cpu", max_wait_ms=2,
                                           max_lanes=64)
        try:
            vals, by_addr = _valset(4)
            bid = BlockID(b"\x0b" * 32, PartSetHeader(1, b"\x0c" * 32))
            vs = VoteSet(CHAIN, 11, 0, PRECOMMIT_TYPE, vals)
            for i in range(4):
                vs.add_vote(_vote(vals, by_addr, i, bid,
                                  typ=PRECOMMIT_TYPE, height=11))
            commit = vs.make_commit()
            # corrupt one signature post-commit, then poison the cache
            # with the corrupted triple
            cs0 = commit.signatures[0]
            cs0.signature = bytes([cs0.signature[0] ^ 0x80]) \
                + cs0.signature[1:]
            s.cache.seed(cache_key(
                vals.get_by_index(0).pub_key.bytes(),
                commit.vote_sign_bytes(CHAIN, 0), cs0.signature))
            with pytest.raises(ErrInvalidSignature):
                VerifyCommitLightAllSignatures(CHAIN, vals, bid, 11,
                                               commit, backend="cpu")
        finally:
            await vsched.release_scheduler()
    _run(main())


# ----------------------------------------------------------- feed_vote path

def test_feed_vote_prefetch_enqueues_after_verdict():
    """ConsensusState.feed_vote with a running scheduler: the vote lands
    in the state queue exactly once, post-verification, and the cache is
    warm for add_vote."""
    from cometbft_tpu.consensus.state import ConsensusState

    async def main():
        s = await vsched.acquire_scheduler(backend="cpu", max_wait_ms=2,
                                           max_lanes=64)
        try:
            vals, by_addr = _valset(4)
            bid = BlockID(b"\x0d" * 32, PartSetHeader(1, b"\x0e" * 32))
            vote = _vote(vals, by_addr, 1, bid, height=1)

            # minimal stand-in: only the attributes feed_vote touches
            cs = ConsensusState.__new__(ConsensusState)
            cs.queue = asyncio.Queue()
            cs.rs = type("RS", (), {})()
            cs.rs.height = 1
            cs.rs.validators = vals
            cs.rs.last_validators = None
            cs.state = type("S", (), {"chain_id": CHAIN})()

            cs.feed_vote(vote, "peer1")
            kind, payload, peer = await asyncio.wait_for(cs.queue.get(), 5)
            assert (kind, peer) == ("vote", "peer1") and payload is vote
            assert cs.queue.empty()
            pub = vals.get_by_index(1).pub_key
            assert s.cache.hit(cache_key(pub.bytes(),
                                         vote.sign_bytes(CHAIN),
                                         vote.signature))
            # own votes (peer "") skip the scheduler: enqueued directly
            own = _vote(vals, by_addr, 2, bid, height=1)
            cs.feed_vote(own, "")
            kind2, payload2, peer2 = cs.queue.get_nowait()
            assert payload2 is own and peer2 == ""
        finally:
            await vsched.release_scheduler()
    _run(main())


def test_acquire_release_refcount():
    async def main():
        s1 = await vsched.acquire_scheduler(backend="cpu")
        s2 = await vsched.acquire_scheduler(backend="cpu")
        assert s1 is s2 and vsched.get_scheduler() is s1
        await vsched.release_scheduler()
        assert vsched.get_scheduler() is s1 and s1.is_running
        await vsched.release_scheduler()
        assert vsched.get_scheduler() is None and not s1.is_running
    _run(main())
