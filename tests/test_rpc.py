"""RPC surface: JSON-RPC over HTTP, URI-style GET, WebSocket
subscriptions, driven against a live 4-node TCP testnet (reference:
``rpc/jsonrpc/jsonrpc_test.go``, ``rpc/core``)."""

import asyncio
import json

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.config import test_consensus_config as _tcc
from cometbft_tpu.node import Node
from cometbft_tpu.p2p import NodeKey
from cometbft_tpu.rpc import HTTPClient, RPCError, WSClient, parse_query
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import MockPV

pytestmark = pytest.mark.timeout(150)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _config() -> Config:
    cfg = Config(consensus=_tcc())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    return cfg


async def _net(n=4):
    pvs = [MockPV.from_secret(b"rpcnode%d" % i) for i in range(n)]
    doc = GenesisDoc(chain_id="rpc-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])
    nodes = []
    for i, pv in enumerate(pvs):
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pv, config=_config(),
            node_key=NodeKey.from_secret(b"rk%d" % i), name=f"rpc{i}")
        nodes.append(node)
        await node.start()
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            await a.dial_peer(b.listen_addr, persistent=True)
    return nodes


async def _stop(nodes):
    for n in nodes:
        try:
            await n.stop()
        except Exception:
            pass


def test_query_language_subset():
    q = parse_query("tm.event='NewBlock' AND tx.hash='AB12'")
    assert q == {"tm.event": "NewBlock", "tx.hash": "AB12"}
    with pytest.raises(RPCError):
        parse_query("junk clause")


def test_rpc_full_surface_over_http():
    async def main():
        nodes = await _net(4)
        try:
            cli = HTTPClient(*nodes[0].rpc_addr)

            # submit a tx and wait until it is committed
            res = await cli.call("broadcast_tx_commit", tx=b"rk=rv".hex())
            assert res["tx_result"]["code"] == 0
            committed_h = res["height"]

            st = await cli.call("status")
            assert st["sync_info"]["latest_block_height"] >= committed_h
            assert st["node_info"]["network"] == "rpc-net"

            # health / net_info
            assert await cli.call("health") == {}
            ni = await cli.call("net_info")
            assert ni["n_peers"] == 3

            blk = await cli.call("block", height=committed_h)
            assert blk["block"]["hdr"]["h"] == committed_h
            txs = blk["block"]["data"]["txs"]
            assert {"~b": b"rk=rv".hex()} in txs

            # block_by_hash / header_by_hash round-trip
            bh = blk["block_id"]["hash"]["~b"]
            blk2 = await cli.call("block_by_hash", hash=bh)
            assert blk2["block"]["hdr"]["h"] == committed_h
            hd = await cli.call("header_by_hash", hash=bh)
            assert hd["header"]["h"] == committed_h

            cm = await cli.call("commit", height=committed_h)
            assert cm["commit"]["h"] == committed_h

            bi = await cli.call("blockchain")
            assert bi["last_height"] >= committed_h
            assert len(bi["block_metas"]) >= 1

            br = await cli.call("block_results", height=committed_h)
            assert any(r["code"] == 0 for r in br["tx_results"])
            # full ResultBlockResults shape (responses.go:54)
            assert "finalize_block_events" in br
            assert "consensus_param_updates" in br
            assert all("events" in r for r in br["tx_results"])

            vals = await cli.call("validators")
            assert vals["total"] == 4 and len(vals["validators"]) == 4

            cp = await cli.call("consensus_params")
            assert cp["consensus_params"]["validator"]["pub_key_types"]

            cs = await cli.call("consensus_state")
            assert cs["round_state"]["height"] >= committed_h

            dcs = await cli.call("dump_consensus_state")
            assert len(dcs["peers"]) == 3

            ab = await cli.call("abci_info")
            assert ab["response"]["last_block_height"] >= 1

            # kvstore app query for the committed key
            q = await cli.call("abci_query", path="/key",
                               data=b"rk".hex())
            assert bytes.fromhex(q["response"]["value"]) == b"rv"

            gen = await cli.call("genesis")
            assert gen["genesis"]["chain_id"] == "rpc-net"

            # chunked genesis reassembles to the same doc
            import base64
            gc = await cli.call("genesis_chunked", chunk=0)
            raw = b""
            for i in range(gc["total"]):
                part = await cli.call("genesis_chunked", chunk=i)
                raw += base64.b64decode(part["data"])
            assert json.loads(raw)["chain_id"] == "rpc-net"
            with pytest.raises(RPCError):
                await cli.call("genesis_chunked", chunk=gc["total"])

            # check_tx runs CheckTx without mempool insertion
            ct = await cli.call("check_tx", tx=b"ck=cv".hex())
            assert ct["code"] == 0
            ct_bad = await cli.call("check_tx", tx=b"notakv".hex())
            assert ct_bad["code"] != 0

            # unsafe routes are not registered without rpc.unsafe
            with pytest.raises(RPCError):
                await cli.call("unsafe_flush_mempool")

            nut = await cli.call("num_unconfirmed_txs")
            assert nut["n_txs"] >= 0

            # sync-path broadcast
            r2 = await cli.call("broadcast_tx_sync", tx=b"k2=v2".hex())
            assert r2["code"] == 0

            # indexing is not enabled on this node: explicit error
            with pytest.raises(RPCError):
                await cli.call("tx", hash="00" * 32)
            with pytest.raises(RPCError):
                await cli.call("nonexistent_method")
        finally:
            await _stop(nodes)
        return True

    assert run(main())


def test_rpc_batch_requests():
    """JSON-RPC batch over one HTTP round-trip
    (rpc/jsonrpc/server/http_json_handler.go:46): ordered results,
    per-call errors, notifications skipped."""
    async def main():
        nodes = await _net(2)
        try:
            cli = HTTPClient(*nodes[0].rpc_addr)
            deadline = asyncio.get_event_loop().time() + 60
            while True:
                st = await cli.call("status")
                if st["sync_info"]["latest_block_height"] >= 2:
                    break
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.2)

            res = await cli.call_batch([
                ("status", {}),
                ("block", {"height": 1}),
                ("bogus_method", {}),
                ("health", {}),
            ])
            assert res[0]["node_info"]["network"] == "rpc-net"
            assert res[1]["block"]["hdr"]["h"] == 1
            assert isinstance(res[2], RPCError) and res[2].code == -32601
            assert res[3] == {}

            # raw batch with a notification (no id): no response entry
            import urllib.request
            raw = json.dumps([
                {"jsonrpc": "2.0", "method": "health"},          # notif
                {"jsonrpc": "2.0", "id": 7, "method": "health"},
            ]).encode()
            host, port = nodes[0].rpc_addr
            loop = asyncio.get_event_loop()
            body = await loop.run_in_executor(
                None, lambda: urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://{host}:{port}/", data=raw,
                        headers={"Content-Type": "application/json"}),
                    timeout=10).read())
            out = json.loads(body)
            assert out == [{"jsonrpc": "2.0", "id": 7, "result": {}}]
        finally:
            await _stop(nodes)
        return True

    assert run(main())


def test_rpc_unsafe_routes():
    """rpc/core/{net,dev}.go unsafe routes, gated by rpc.unsafe: wire two
    isolated validators together via dial_peers, then flush the mempool."""
    async def main():
        pvs = [MockPV.from_secret(b"unsafe%d" % i) for i in range(2)]
        doc = GenesisDoc(chain_id="unsafe-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = _config()
            cfg.rpc.unsafe = True
            node = await Node.create(
                doc, KVStoreApplication(), priv_validator=pv, config=cfg,
                node_key=NodeKey.from_secret(b"uk%d" % i), name=f"un{i}")
            nodes.append(node)
            await node.start()
        try:
            cli = HTTPClient(*nodes[0].rpc_addr)
            ni = await cli.call("net_info")
            assert ni["n_peers"] == 0

            await cli.call("dial_peers", peers=[nodes[1].listen_addr],
                           persistent=True)
            deadline = asyncio.get_event_loop().time() + 30
            while (await cli.call("net_info"))["n_peers"] < 1:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.2)

            # with both validators wired, blocks start committing
            while True:
                st = await cli.call("status")
                if st["sync_info"]["latest_block_height"] >= 1:
                    break
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.2)

            assert await cli.call("unsafe_flush_mempool") == {}
            nut = await cli.call("num_unconfirmed_txs")
            assert nut["n_txs"] == 0
        finally:
            await _stop(nodes)
        return True

    assert run(main())


def test_rpc_uri_style_get():
    async def main():
        nodes = await _net(4)
        try:
            host, port = nodes[0].rpc_addr
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            status_line = await reader.readline()
            assert b"200" in status_line
            headers = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = await reader.readexactly(int(headers["content-length"]))
            writer.close()
            resp = json.loads(body)
            assert resp["result"]["node_info"]["network"] == "rpc-net"
        finally:
            await _stop(nodes)
        return True

    assert run(main())


def test_websocket_subscription_streams_blocks():
    async def main():
        nodes = await _net(4)
        try:
            ws = await WSClient.connect(*nodes[0].rpc_addr)
            await ws.subscribe("tm.event='NewBlock'")
            ev1 = await ws.next_event(timeout=30)
            ev2 = await ws.next_event(timeout=30)
            h1 = ev1["data"]["value"]["block"]["hdr"]["h"]
            h2 = ev2["data"]["value"]["block"]["hdr"]["h"]
            assert h2 == h1 + 1
            await ws.close()
        finally:
            await _stop(nodes)
        return True

    assert run(main())


def test_websocket_tx_subscription():
    async def main():
        nodes = await _net(4)
        try:
            from cometbft_tpu.mempool.mempool import TxKey

            tx = b"wsk=wsv"
            key = TxKey(tx).hex()
            ws = await WSClient.connect(*nodes[1].rpc_addr)
            await ws.subscribe(f"tm.event='Tx' AND tx.hash='{key}'")
            cli = HTTPClient(*nodes[0].rpc_addr)
            await cli.call("broadcast_tx_sync", tx=tx.hex())
            evt = await ws.next_event(timeout=30)
            assert evt["events"]["tx.hash"] == key
            await ws.close()
        finally:
            await _stop(nodes)
        return True

    assert run(main())
