"""RPC TLS, CORS, and the generated OpenAPI document (reference:
``config/config.go:353-364,428-442`` wiring in ``rpc/jsonrpc/server``;
``rpc/openapi/openapi.yaml``)."""

import asyncio
import datetime
import json
import ssl

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.config import test_consensus_config as _tcc
from cometbft_tpu.node import Node
from cometbft_tpu.p2p import NodeKey
from cometbft_tpu.types.priv_validator import MockPV
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _self_signed(tmp_path):
    """Self-signed localhost cert via the cryptography package, falling
    back to the openssl CLI on images without it (the TLS round-trip
    only needs a cert the client can pin, not any particular issuer)."""
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
    except ImportError:
        import shutil
        import subprocess

        if shutil.which("openssl") is None:
            pytest.skip("needs the cryptography package or openssl CLI")
        cert_path = tmp_path / "rpc.crt"
        key_path = tmp_path / "rpc.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
             "ec_paramgen_curve:prime256v1", "-keyout", str(key_path),
             "-out", str(cert_path), "-days", "1", "-nodes",
             "-subj", "/CN=localhost",
             "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1"],
            check=True, capture_output=True)
        return str(cert_path), str(key_path)

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost"),
                 x509.IPAddress(__import__("ipaddress")
                                .ip_address("127.0.0.1"))]), critical=False)
            .sign(key, hashes.SHA256()))
    cert_path = tmp_path / "rpc.crt"
    key_path = tmp_path / "rpc.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    return str(cert_path), str(key_path)


async def _node(cfg: Config) -> Node:
    pv = MockPV.from_secret(b"tlsnode")
    doc = GenesisDoc(chain_id="tls-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])
    node = await Node.create(doc, KVStoreApplication(), priv_validator=pv,
                             config=cfg,
                             node_key=NodeKey.from_secret(b"tlsk"),
                             name="tls0")
    await node.start()
    return node


def _cfg() -> Config:
    cfg = Config(consensus=_tcc())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    return cfg


async def _raw_http(host, port, req: bytes, ssl_ctx=None) -> bytes:
    reader, writer = await asyncio.open_connection(host, port, ssl=ssl_ctx)
    writer.write(req)
    await writer.drain()
    data = await asyncio.wait_for(reader.read(-1), 10)  # to EOF
    writer.close()
    return data


def test_tls_round_trip(tmp_path):
    """Both tls files configured -> the RPC listener speaks HTTPS; a
    TLS client round-trips a status call, a plaintext client fails."""
    cert, key = _self_signed(tmp_path)

    async def main():
        cfg = _cfg()
        cfg.rpc.tls_cert_file = cert      # absolute paths
        cfg.rpc.tls_key_file = key
        node = await _node(cfg)
        try:
            host, port = node.rpc_addr
            cli = ssl.create_default_context()
            cli.check_hostname = False
            cli.verify_mode = ssl.CERT_NONE
            raw = await _raw_http(
                host, port,
                b"GET /status HTTP/1.1\r\nHost: x\r\n"
                b"Connection: close\r\n\r\n", ssl_ctx=cli)
            body = raw.split(b"\r\n\r\n", 1)[1]
            assert json.loads(body)["result"]["node_info"][
                "network"] == "tls-net"
            # plaintext against the TLS port must NOT yield an HTTP reply
            try:
                raw2 = await _raw_http(
                    host, port,
                    b"GET /status HTTP/1.1\r\nHost: x\r\n"
                    b"Connection: close\r\n\r\n")
                assert not raw2.startswith(b"HTTP/1.1 200")
            except (ConnectionError, asyncio.TimeoutError, OSError):
                pass
        finally:
            await node.stop()

    run(main())


def test_cors_preflight_and_simple_request():
    async def main():
        cfg = _cfg()
        cfg.rpc.cors_allowed_origins = ["https://app.example.com",
                                        "https://*.trusted.dev"]
        node = await _node(cfg)
        try:
            host, port = node.rpc_addr
            # preflight from an allowed origin
            raw = await _raw_http(
                host, port,
                b"OPTIONS /status HTTP/1.1\r\nHost: x\r\n"
                b"Origin: https://app.example.com\r\n"
                b"Access-Control-Request-Method: POST\r\n"
                b"Connection: close\r\n\r\n")
            head = raw.split(b"\r\n\r\n", 1)[0].decode()
            assert "204" in head.splitlines()[0]
            assert "Access-Control-Allow-Origin: https://app.example.com" \
                in head
            assert "Access-Control-Allow-Methods:" in head
            # wildcard origin matches one subdomain level (rs/cors rule:
            # one * per origin)
            raw = await _raw_http(
                host, port,
                b"GET /status HTTP/1.1\r\nHost: x\r\n"
                b"Origin: https://ci.trusted.dev\r\n"
                b"Connection: close\r\n\r\n")
            head = raw.split(b"\r\n\r\n", 1)[0].decode()
            assert "Access-Control-Allow-Origin: https://ci.trusted.dev" \
                in head
            # a disallowed origin gets NO CORS headers (browser blocks)
            raw = await _raw_http(
                host, port,
                b"GET /status HTTP/1.1\r\nHost: x\r\n"
                b"Origin: https://evil.example.net\r\n"
                b"Connection: close\r\n\r\n")
            head = raw.split(b"\r\n\r\n", 1)[0].decode()
            assert "Access-Control-Allow-Origin" not in head
        finally:
            await node.stop()

    run(main())


def test_cors_off_by_default():
    async def main():
        node = await _node(_cfg())
        try:
            host, port = node.rpc_addr
            raw = await _raw_http(
                host, port,
                b"GET /status HTTP/1.1\r\nHost: x\r\n"
                b"Origin: https://anything.example\r\n"
                b"Connection: close\r\n\r\n")
            assert b"Access-Control-Allow-Origin" not in raw
        finally:
            await node.stop()

    run(main())


def test_openapi_spec_served_and_complete():
    async def main():
        node = await _node(_cfg())
        try:
            host, port = node.rpc_addr
            raw = await _raw_http(
                host, port,
                b"GET /openapi HTTP/1.1\r\nHost: x\r\n"
                b"Connection: close\r\n\r\n")
            spec = json.loads(raw.split(b"\r\n\r\n", 1)[1])
            assert spec["openapi"].startswith("3.")
            # every live route is documented; spot-check parameters
            for route in ("status", "block", "tx", "validators",
                          "broadcast_tx_commit", "abci_query"):
                assert f"/{route}" in spec["paths"], route
            names = [p["name"] for p in
                     spec["paths"]["/block"]["get"]["parameters"]]
            assert "height" in names
        finally:
            await node.stop()

    run(main())


def test_https_client_round_trip(tmp_path):
    """The package's own HTTPClient speaks TLS (the reference's rpc
    client accepts https:// addresses): status + broadcast round-trip
    against a TLS-configured node."""
    cert, key = _self_signed(tmp_path)

    async def main():
        cfg = _cfg()
        cfg.rpc.tls_cert_file = cert
        cfg.rpc.tls_key_file = key
        node = await _node(cfg)
        try:
            from cometbft_tpu.rpc.client import HTTPClient

            host, port = node.rpc_addr
            cli = HTTPClient(host, port, tls=True, tls_verify=False)
            st = await cli.call("status")
            assert st["node_info"]["network"] == "tls-net"
            res = await cli.call("broadcast_tx_sync", tx=b"k=v".hex())
            assert res["code"] == 0
            await cli.close()
        finally:
            await node.stop()

    run(main())
