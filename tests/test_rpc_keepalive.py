"""Keep-alive HTTPClient: connection reuse, idempotency-gated retry,
and cancellation safety (a timed-out call must never desync the stream
so that a stale response answers the next request)."""

import asyncio
import json
import re

import pytest

from cometbft_tpu.rpc.client import HTTPClient

pytestmark = pytest.mark.timeout(60)


class EchoServer:
    """Minimal keep-alive JSON-RPC echo server with per-method hooks."""

    def __init__(self):
        self.connections = 0
        self.requests = 0
        self.server = None
        self._tasks: set = set()

    async def start(self):
        self.server = await asyncio.start_server(self._handle,
                                                 "127.0.0.1", 0)
        return self.server.sockets[0].getsockname()[1]

    async def _handle(self, reader, writer):
        self.connections += 1
        self._tasks.add(asyncio.current_task())
        try:
            while True:
                headers = b""
                while True:
                    line = await reader.readline()
                    if not line:
                        return
                    headers += line
                    if line in (b"\r\n", b"\n"):
                        break
                n = int(re.search(rb"Content-Length: (\d+)",
                                  headers).group(1))
                req = json.loads(await reader.readexactly(n))
                self.requests += 1
                if req["method"] == "slow":
                    await asyncio.sleep(1.0)
                if req["method"] == "hangup":
                    writer.close()
                    return
                body = json.dumps({
                    "jsonrpc": "2.0", "id": req["id"],
                    "result": {"method": req["method"],
                               "req_no": self.requests}}).encode()
                writer.write(
                    b"HTTP/1.1 200 OK\r\nContent-Length: "
                    + str(len(body)).encode()
                    + b"\r\nConnection: keep-alive\r\n\r\n" + body)
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError,
                asyncio.CancelledError):
            pass
        finally:
            self._tasks.discard(asyncio.current_task())
            writer.close()

    async def stop(self):
        if self.server is not None:
            self.server.close()
        for t in list(self._tasks):
            t.cancel()
        await asyncio.sleep(0)         # let cancellations unwind before
        #   the loop closes (no 'Event loop is closed' unraisables)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_connection_reuse_and_stale_retry():
    async def main():
        srv = EchoServer()
        port = await srv.start()
        cli = HTTPClient("127.0.0.1", port)
        for i in range(5):
            r = await cli.call("ping")
            assert r["method"] == "ping"
        assert srv.connections == 1, "keep-alive did not reuse"

        # server hangs up; the next IDEMPOTENT call silently reconnects
        with pytest.raises(Exception):
            await cli.call("hangup")
        r = await cli.call("ping")
        assert r["method"] == "ping"
        assert srv.connections >= 2
        await cli.close()
        await srv.stop()
        return True

    assert run(main())


def test_broadcast_never_retries():
    """The retry decision is idempotency-gated: broadcast_* requests set
    retry_ok=False (a stale-connection resend could double-send a tx the
    server already accepted); read-only methods allow the retry."""

    async def main():
        cli = HTTPClient("127.0.0.1", 1)
        seen = []

        async def fake_post(body, retry_ok=True):
            seen.append(retry_ok)
            req = json.loads(body)
            if isinstance(req, list):
                return [{"jsonrpc": "2.0", "id": r["id"], "result": {}}
                        for r in req]
            return {"jsonrpc": "2.0", "id": req["id"], "result": {}}

        cli._post = fake_post
        await cli.call("status")
        await cli.call("broadcast_tx_async", tx="00")
        await cli.call("broadcast_tx_commit", tx="00")
        await cli.call_batch([("status", {}), ("block", {"height": 1})])
        await cli.call_batch([("status", {}),
                              ("broadcast_tx_sync", {"tx": "00"})])
        assert seen == [True, False, False, True, False]
        return True

    assert run(main())


def test_cancellation_does_not_desync():
    """wait_for cancelling a call mid-response must drop the connection;
    the next call gets ITS OWN response, never the stale one."""

    async def main():
        srv = EchoServer()
        port = await srv.start()
        cli = HTTPClient("127.0.0.1", port)
        r = await cli.call("warm")
        assert r["method"] == "warm"
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(cli.call("slow"), 0.2)
        r = await cli.call("fast")
        assert r["method"] == "fast"
        await cli.close()
        await srv.stop()
        return True

    assert run(main())
