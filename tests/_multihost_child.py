"""Child process for test_multihost.py: one host of a 2-process
jax.distributed CPU cluster.  Each host contributes 2 virtual devices;
the global mesh spans 4.  Runs one lane-sharded verification step
through the production ``parallel/mesh.py`` path and prints MULTIHOST_OK
on success."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

port, proc_id = sys.argv[1], int(sys.argv[2])

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

from cometbft_tpu.jaxenv import enable_compile_cache, harden_cpu_pinned_env

harden_cpu_pinned_env()
enable_compile_cache()

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from cometbft_tpu.parallel.mesh import init_multihost, sharded_verify_fn
from cometbft_tpu.testing import dense_signature_batch

mesh = init_multihost(coordinator=f"127.0.0.1:{port}",
                      num_processes=2, process_id=proc_id)
n_global = mesh.devices.size
assert n_global == 4, f"expected 4 global devices, got {n_global}"
assert jax.process_count() == 2

# identical batch on both hosts; each host materializes only its
# addressable shards of the global arrays
args, _ = dense_signature_batch(8, msg_len=80, seed=5)


def to_global(a):
    a = np.asarray(a)
    spec = P(*(("batch",) + (None,) * (a.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(a.shape, sharding,
                                        lambda idx: a[idx])


out = sharded_verify_fn(mesh)(*[to_global(a) for a in args])
local = np.concatenate(
    [np.asarray(s.data).ravel() for s in out.addressable_shards])
assert local.all(), "sharded verify rejected valid signatures"
print(f"MULTIHOST_OK {proc_id}", flush=True)
