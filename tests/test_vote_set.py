"""VoteSet, PartSet, BitArray, evidence, genesis tests."""

import pytest

from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.types import (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT,
                                BlockID, PartSetHeader, Validator,
                                ValidatorSet, Vote, PRECOMMIT_TYPE,
                                PREVOTE_TYPE)
from cometbft_tpu.types.evidence import DuplicateVoteEvidence
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.part_set import PartSet, PartSetError
from cometbft_tpu.types.priv_validator import MockPV
from cometbft_tpu.types.vote_set import (ConflictingVoteError, VoteSet,
                                         VoteSetError)

CHAIN_ID = "vs-chain"
BID = BlockID(b"\x0a" * 32, PartSetHeader(1, b"\x0b" * 32))
BID2 = BlockID(b"\x0c" * 32, PartSetHeader(1, b"\x0d" * 32))


def setup_vals(n, power=10):
    pvs = [MockPV.from_secret(b"pv%d" % i) for i in range(n)]
    vals = ValidatorSet([Validator(pv.get_pub_key(), power) for pv in pvs])
    ordered = []
    for v in vals.validators:
        ordered.append(next(p for p in pvs
                            if p.get_pub_key().address() == v.address))
    return vals, ordered


def make_vote(pv, vals, idx, bid, typ=PRECOMMIT_TYPE, height=3, round_=0,
              ts=1_700_000_000_000_000_000):
    v = Vote(type=typ, height=height, round=round_, block_id=bid,
             timestamp_ns=ts, validator_address=pv.get_pub_key().address(),
             validator_index=idx)
    import asyncio

    asyncio.run(pv.sign_vote(CHAIN_ID, v, sign_extension=False))
    return v


def test_vote_set_majority_and_commit():
    vals, pvs = setup_vals(4)
    vs = VoteSet(CHAIN_ID, 3, 0, PRECOMMIT_TYPE, vals)
    assert not vs.has_two_thirds_any()
    for i in range(3):
        assert vs.add_vote(make_vote(pvs[i], vals, i, BID))
        if i < 2:
            assert not vs.has_two_thirds_majority()
    assert vs.has_two_thirds_majority()
    maj, ok = vs.two_thirds_majority()
    assert ok and maj == BID

    commit = vs.make_commit()
    assert commit.height == 3 and commit.block_id == BID
    assert commit.size() == 4
    assert commit.signatures[3].block_id_flag == BLOCK_ID_FLAG_ABSENT
    flags = [cs.block_id_flag for cs in commit.signatures[:3]]
    assert flags == [BLOCK_ID_FLAG_COMMIT] * 3
    # commit verifies against the validator set
    from cometbft_tpu.types import VerifyCommit
    VerifyCommit(CHAIN_ID, vals, BID, 3, commit, backend="cpu")


def test_vote_set_rejects():
    vals, pvs = setup_vals(4)
    vs = VoteSet(CHAIN_ID, 3, 0, PRECOMMIT_TYPE, vals)
    good = make_vote(pvs[0], vals, 0, BID)
    assert vs.add_vote(good)
    assert not vs.add_vote(good)          # duplicate -> False, no error
    with pytest.raises(VoteSetError):      # wrong height
        vs.add_vote(make_vote(pvs[1], vals, 1, BID, height=4))
    with pytest.raises(VoteSetError):      # index/address mismatch
        bad = make_vote(pvs[1], vals, 2, BID)
        vs.add_vote(bad)
    with pytest.raises(VoteSetError):      # bad signature
        v = make_vote(pvs[1], vals, 1, BID)
        v.signature = v.signature[:-1] + bytes([v.signature[-1] ^ 1])
        vs.add_vote(v)


def test_vote_set_conflicting_votes_surface_for_evidence():
    vals, pvs = setup_vals(4)
    vs = VoteSet(CHAIN_ID, 3, 0, PREVOTE_TYPE, vals)
    v1 = make_vote(pvs[0], vals, 0, BID, typ=PREVOTE_TYPE)
    v2 = make_vote(pvs[0], vals, 0, BID2, typ=PREVOTE_TYPE)
    assert vs.add_vote(v1)
    with pytest.raises(ConflictingVoteError) as ce:
        vs.add_vote(v2)
    ev = DuplicateVoteEvidence.from_votes(ce.value.existing, ce.value.new,
                                          1234, vals)
    assert ev.validate_basic() is None
    assert ev.validator_power == 10 and ev.total_voting_power == 40


def test_vote_set_peer_maj23_tracks_conflicts():
    vals, pvs = setup_vals(4)
    vs = VoteSet(CHAIN_ID, 3, 0, PREVOTE_TYPE, vals)
    assert vs.add_vote(make_vote(pvs[0], vals, 0, BID, typ=PREVOTE_TYPE))
    vs.set_peer_maj23("peer1", BID2)
    with pytest.raises(ConflictingVoteError):
        vs.add_vote(make_vote(pvs[0], vals, 0, BID2, typ=PREVOTE_TYPE))
    ba = vs.bit_array_by_block_id(BID2)
    assert ba is not None and ba.get_index(0)   # tracked under declared maj23
    with pytest.raises(VoteSetError):
        vs.set_peer_maj23("peer1", BID)         # changed claim


def test_part_set_roundtrip_and_proofs():
    data = bytes(range(256)) * 1024           # 256 KB -> 4 parts
    ps = PartSet.from_data(data)
    assert ps.total == 4 and ps.is_complete()
    header = ps.header()

    rx = PartSet(header)
    assert not rx.is_complete()
    for i in (2, 0, 3, 1):
        assert rx.add_part(ps.get_part(i))
    assert rx.is_complete() and rx.get_data() == data

    rx2 = PartSet(header)
    bad = ps.get_part(1)
    tampered = type(bad)(1, bad.bytes_[:-1] + b"\x00", bad.proof)
    with pytest.raises(PartSetError):
        rx2.add_part(tampered)

    tiny = PartSet.from_data(b"x")
    assert tiny.total == 1
    rt = PartSet(tiny.header())
    assert rt.add_part(tiny.get_part(0)) and rt.get_data() == b"x"


def test_bit_array():
    b = BitArray(10)
    assert b.is_empty() and not b.is_full()
    b.set_index(3, True)
    b.set_index(9, True)
    assert b.get_true_indices() == [3, 9]
    c = b.copy()
    c.set_index(3, False)
    assert b.get_index(3) and not c.get_index(3)
    assert b.sub(c).get_true_indices() == [3]
    assert b.or_(c).get_true_indices() == [3, 9]
    idx, ok = b.pick_random()
    assert ok and idx in (3, 9)
    full = BitArray.from_indices(3, [0, 1, 2])
    assert full.is_full()


def test_genesis_roundtrip(tmp_path):
    pvs = [MockPV.from_secret(b"g%d" % i) for i in range(3)]
    doc = GenesisDoc(chain_id="genesis-chain",
                     validators=[GenesisValidator(p.get_pub_key(), 5)
                                 for p in pvs])
    doc.consensus_params.feature.vote_extensions_enable_height = 100
    path = str(tmp_path / "genesis.json")
    doc.save(path)
    doc2 = GenesisDoc.load(path)
    assert doc2.chain_id == "genesis-chain"
    assert doc2.validator_set().hash() == doc.validator_set().hash()
    assert doc2.consensus_params.feature.vote_extensions_enable_height == 100


def test_genesis_roundtrip_all_params(tmp_path):
    doc = GenesisDoc(chain_id="p-chain")
    doc.consensus_params.evidence.max_age_num_blocks = 50_000
    doc.consensus_params.synchrony.precision_ns = 123
    doc.consensus_params.block.max_gas = 777
    path = str(tmp_path / "g.json")
    doc.save(path)
    doc2 = GenesisDoc.load(path)
    assert doc2.consensus_params.evidence.max_age_num_blocks == 50_000
    assert doc2.consensus_params.synchrony.precision_ns == 123
    assert doc2.consensus_params.block.max_gas == 777
    assert doc2.consensus_params.hash() == doc.consensus_params.hash()


def test_peer_maj23_conflicts_can_promote():
    vals, pvs = setup_vals(4)
    vs = VoteSet(CHAIN_ID, 3, 0, PREVOTE_TYPE, vals)
    # all four first vote for BID... but peers claim BID2 has maj23
    vs.set_peer_maj23("p", BID2)
    for i in range(4):
        assert vs.add_vote(make_vote(pvs[i], vals, i, BID, typ=PREVOTE_TYPE))
    # oops: BID already promoted (4/4). build a fresh set where only 1 votes BID
    vals2, pvs2 = setup_vals(4, power=10)
    vs2 = VoteSet(CHAIN_ID, 3, 0, PREVOTE_TYPE, vals2)
    vs2.set_peer_maj23("p", BID2)
    for i in range(3):
        assert vs2.add_vote(make_vote(pvs2[i], vals2, i, BID,
                                      typ=PREVOTE_TYPE))
    # equivocators now vote BID2; conflicts tracked AND promote BID2? they
    # can't outnumber BID... use a set where BID never got 2/3:
    vals3, pvs3 = setup_vals(4, power=10)
    vs3 = VoteSet(CHAIN_ID, 3, 0, PREVOTE_TYPE, vals3)
    vs3.set_peer_maj23("p", BID2)
    assert vs3.add_vote(make_vote(pvs3[0], vals3, 0, BID, typ=PREVOTE_TYPE))
    assert vs3.add_vote(make_vote(pvs3[1], vals3, 1, BID2, typ=PREVOTE_TYPE))
    assert vs3.add_vote(make_vote(pvs3[2], vals3, 2, BID2, typ=PREVOTE_TYPE))
    # validator 0 equivocates to BID2 -> conflict, but tracked: 3 x 10 = 30 > 2/3*40
    with pytest.raises(ConflictingVoteError):
        vs3.add_vote(make_vote(pvs3[0], vals3, 0, BID2, typ=PREVOTE_TYPE))
    maj, ok = vs3.two_thirds_majority()
    assert ok and maj == BID2
