"""Tier-2 byzantine test over real TCP: a double-signed precommit rides
the live vote gossip, the conflict becomes DuplicateVoteEvidence, the
evidence channel gossips it between pools, a proposal carries it, and
every replica's app sees the ABCI misbehavior (reference:
``internal/consensus/byzantine_test.go`` + ``internal/evidence/reactor.go``
as one scenario)."""

import asyncio

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.config import test_consensus_config as _tcc
from cometbft_tpu.node import Node
from cometbft_tpu.p2p import NodeKey
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.priv_validator import MockPV
from cometbft_tpu.types.vote import PRECOMMIT_TYPE, Vote

# 4-validator TCP net per test: minutes of wall clock on a small CPU box
# and timing-sensitive under load — tier-2 alongside the e2e suites (the
# in-proc evidence-pool logic stays tier-1 in test_evidence.py).
pytestmark = [pytest.mark.timeout(120), pytest.mark.slow]


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_double_sign_detected_and_gossiped_over_tcp():
    async def main():
        pvs = [MockPV.from_secret(b"evnet%d" % i) for i in range(4)]
        doc = GenesisDoc(chain_id="ev-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes, apps = [], []
        for i, pv in enumerate(pvs):
            cfg = Config(consensus=_tcc())
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.rpc.laddr = "tcp://127.0.0.1:0"
            app = KVStoreApplication()
            node = await Node.create(
                doc, app, priv_validator=pv, config=cfg,
                node_key=NodeKey.from_secret(b"evk%d" % i), name=f"ev{i}")
            nodes.append(node)
            apps.append(app)
            await node.start()
        try:
            for i, a in enumerate(nodes):
                for b in nodes[i + 1:]:
                    await a.dial_peer(b.listen_addr, persistent=True)

            # let the chain roll
            while min(n.height() for n in nodes) < 2:
                await asyncio.sleep(0.05)

            byz = nodes[3]
            byz_addr = pvs[3].get_pub_key().address()
            byz_idx, _ = byz.consensus.state.validators.get_by_address(
                byz_addr)

            for _ in range(20):
                h = byz.consensus.rs.height
                fake = Vote(
                    type=PRECOMMIT_TYPE, height=h, round=0,
                    block_id=BlockID(b"\x55" * 32,
                                     PartSetHeader(1, b"\x44" * 32)),
                    timestamp_ns=424242,
                    validator_address=byz_addr, validator_index=byz_idx)
                await pvs[3].sign_vote("ev-net", fake,
                                       sign_extension=False)
                # the byzantine replica broadcasts its equivocation over
                # the REAL consensus vote channel
                byz.consensus_reactor._broadcast_vote(fake)
                try:
                    await asyncio.wait_for(
                        _all_apps_saw_misbehavior(apps, byz_addr), 5)
                    break
                except asyncio.TimeoutError:
                    continue
            else:
                raise AssertionError("misbehavior never reached the apps")

            # evidence-channel gossip, isolated from the vote channel:
            # hand-craft fresh DuplicateVoteEvidence for a NEW height,
            # add it only to node0's pool, and require the evidence
            # reactor to deliver it into node1's pool directly
            from cometbft_tpu.types.evidence import DuplicateVoteEvidence

            h2 = byz.consensus.rs.height - 1   # committed height
            vals = nodes[0].consensus.state.validators
            va = Vote(type=PRECOMMIT_TYPE, height=h2, round=0,
                      block_id=BlockID(b"\x11" * 32,
                                       PartSetHeader(1, b"\x22" * 32)),
                      timestamp_ns=7, validator_address=byz_addr,
                      validator_index=byz_idx)
            vb = Vote(type=PRECOMMIT_TYPE, height=h2, round=0,
                      block_id=BlockID(b"\x33" * 32,
                                       PartSetHeader(1, b"\x99" * 32)),
                      timestamp_ns=7, validator_address=byz_addr,
                      validator_index=byz_idx)
            await pvs[3].sign_vote("ev-net", va, sign_extension=False)
            await pvs[3].sign_vote("ev-net", vb, sign_extension=False)
            ev2 = DuplicateVoteEvidence.from_votes(
                va, vb, nodes[0].consensus.state.last_block_time_ns
                if hasattr(nodes[0].consensus.state, "last_block_time_ns")
                else 0, vals)
            assert nodes[0].evidence_pool.add_evidence(ev2)
            deadline = asyncio.get_event_loop().time() + 20
            while not nodes[1].evidence_pool.is_pending(ev2) and \
                    not nodes[1].evidence_pool.is_committed(ev2):
                assert asyncio.get_event_loop().time() < deadline, \
                    "evidence never gossiped pool-to-pool"
                await asyncio.sleep(0.05)
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    async def _all_apps_saw_misbehavior(apps, byz_addr):
        while True:
            hits = 0
            for app in apps:
                for mb in app.misbehavior_seen:
                    if mb.validator_address == byz_addr and \
                            mb.type == "DUPLICATE_VOTE":
                        hits += 1
                        break
            if hits == len(apps):
                return None
            await asyncio.sleep(0.05)

    assert run(main())


def test_broadcast_evidence_rpc():
    """rpc broadcast_evidence: externally submitted DuplicateVoteEvidence
    enters the pool after verification; invalid evidence is rejected with
    an RPC error (rpc/core/evidence.go)."""
    from cometbft_tpu.rpc import HTTPClient, RPCError
    from cometbft_tpu.rpc.json import jsonable
    from cometbft_tpu.types.evidence import DuplicateVoteEvidence

    async def main():
        pvs = [MockPV.from_secret(b"bevn%d" % i) for i in range(4)]
        doc = GenesisDoc(chain_id="bev-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = Config(consensus=_tcc())
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.rpc.laddr = "tcp://127.0.0.1:0"
            node = await Node.create(
                doc, KVStoreApplication(), priv_validator=pv, config=cfg,
                node_key=NodeKey.from_secret(b"bek%d" % i), name=f"bev{i}")
            nodes.append(node)
            await node.start()
        try:
            for i, a in enumerate(nodes):
                for b in nodes[i + 1:]:
                    await a.dial_peer(b.listen_addr, persistent=True)
            while min(n.height() for n in nodes) < 3:
                await asyncio.sleep(0.05)

            cli = HTTPClient(*nodes[0].rpc_addr)
            byz_addr = pvs[3].get_pub_key().address()
            byz_idx, _ = nodes[0].consensus.state.validators \
                .get_by_address(byz_addr)
            h = nodes[0].height() - 1
            votes = []
            for tag in (b"\x10", b"\x20"):
                v = Vote(type=PRECOMMIT_TYPE, height=h, round=0,
                         block_id=BlockID(tag * 32,
                                          PartSetHeader(1, tag * 32)),
                         timestamp_ns=9, validator_address=byz_addr,
                         validator_index=byz_idx)
                await pvs[3].sign_vote("bev-net", v, sign_extension=False)
                votes.append(v)
            blk_time = nodes[0].block_store.load_block(h).header.time_ns
            ev = DuplicateVoteEvidence.from_votes(
                votes[0], votes[1], blk_time,
                nodes[0].consensus.state.validators)

            res = await cli.call("broadcast_evidence",
                                 evidence=jsonable(ev))
            assert res["hash"] == ev.hash().hex()
            assert nodes[0].evidence_pool.is_pending(ev)

            # invalid evidence (unsigned votes) is rejected
            bad = DuplicateVoteEvidence(
                vote_a=Vote(type=PRECOMMIT_TYPE, height=h, round=0,
                            block_id=BlockID(b"\x01" * 32,
                                             PartSetHeader(1, b"\x01" * 32)),
                            timestamp_ns=1, validator_address=byz_addr,
                            validator_index=byz_idx),
                vote_b=Vote(type=PRECOMMIT_TYPE, height=h, round=0,
                            block_id=BlockID(b"\x02" * 32,
                                             PartSetHeader(1, b"\x02" * 32)),
                            timestamp_ns=1, validator_address=byz_addr,
                            validator_index=byz_idx))
            import pytest as _pytest
            with _pytest.raises(RPCError):
                await cli.call("broadcast_evidence",
                               evidence=jsonable(bad))
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())
