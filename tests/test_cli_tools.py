"""CLI tooling commands: reindex-event, compact-db, debug dump
(reference: ``cmd/cometbft/commands/{reindex_event,compact,debug}``)."""

import asyncio
import json
import os
import subprocess
import sys
import tarfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(*args, home):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, *args],
        capture_output=True, text=True, env=env, cwd=REPO)


def _run_node_for(home, seconds, min_height=2):
    """Run a single-validator node on this home until it has committed
    at least ``min_height`` blocks (a fixed sleep flakes under load —
    startup alone can eat several seconds on a busy box)."""
    import json as _json
    import urllib.request

    from cometbft_tpu.config import Config

    cfg = Config.load(f"{home}/config/config.toml")
    port = int(cfg.rpc.laddr.rsplit(":", 1)[1])
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "start"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    deadline = time.monotonic() + max(seconds, 90)
    try:
        while True:
            assert proc.poll() is None, "node died during warm-up"
            try:
                st = _json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=2).read())
                if st["result"]["sync_info"][
                        "latest_block_height"] >= min_height:
                    break
            except Exception:
                pass
            assert time.monotonic() < deadline, "node never reached height"
            time.sleep(0.3)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def _prep_home(tmp_path, port):
    from cometbft_tpu.config import Config

    home = str(tmp_path / "node")
    res = _run_cli("init", "--chain-id", "tools-chain", home=home)
    assert res.returncode == 0, res.stderr
    cfgp = f"{home}/config/config.toml"
    cfg = Config.load(cfgp)
    cfg.consensus.timeout_propose = 300_000_000
    cfg.consensus.timeout_prevote = 150_000_000
    cfg.consensus.timeout_precommit = 150_000_000
    cfg.consensus.timeout_commit = 100_000_000
    cfg.base.signature_backend = "cpu"
    cfg.p2p.laddr = f"tcp://127.0.0.1:{port}"
    cfg.rpc.laddr = f"tcp://127.0.0.1:{port + 1}"
    cfg.save(cfgp)
    return home


def test_reindex_and_compact_and_debug_dump(tmp_path):
    home = _prep_home(tmp_path, 28960)
    _run_node_for(home, 6)

    # -------- reindex-event rebuilds searchable indexes offline
    res = _run_cli("reindex-event", home=home)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "Reindexed" in res.stdout

    from cometbft_tpu.config import Config
    from cometbft_tpu.indexer.block import BlockIndexer
    from cometbft_tpu.storage import open_db

    cfg = Config.load(f"{home}/config/config.toml")
    ix = BlockIndexer(open_db(cfg.storage.db_backend,
                              os.path.join(home, "data", "block_index.db")))
    found = ix.search("block.height >= 1")
    assert found["total_count"] >= 1, found

    # -------- compact-db runs over every store and reports sizes
    res = _run_cli("compact-db", home=home)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "Reclaimed" in res.stdout

    # data survives compaction: stores still open and serve blocks
    from cometbft_tpu.storage import BlockStore

    bs = BlockStore(open_db(cfg.storage.db_backend,
                            os.path.join(home, "data", "blockstore.db")))
    assert bs.height() >= 1
    assert bs.load_block(bs.height()) is not None

    # -------- debug wal dumps JSON-lines records from the consensus WAL
    res = _run_cli("debug", "wal", home=home)
    assert res.returncode == 0, res.stdout + res.stderr
    recs = [json.loads(line) for line in res.stdout.splitlines() if line]
    assert len(recs) >= 1
    kinds = {r.get("#") for r in recs}
    assert "endheight" in kinds, kinds        # height sentinels present

    # -------- debug dump produces a bundle even with the node down
    out_dir = str(tmp_path / "bundle")
    res = _run_cli("debug", "dump", "--rpc", "127.0.0.1:1",  # unreachable
                   "--output-dir", out_dir, home=home)
    assert res.returncode == 0, res.stdout + res.stderr
    assert os.path.exists(out_dir + ".tar.gz")
    with tarfile.open(out_dir + ".tar.gz") as tar:
        names = tar.getnames()
    assert any("config.toml" in n for n in names)
    assert any("data_listing.txt" in n for n in names)
    assert any("status.err" in n for n in names)  # RPC was down


def test_debug_kill_captures_and_terminates(tmp_path):
    """commands/debug/kill.go parity: 'debug kill <pid>' aggregates the
    LIVE node's RPC state + home files + /proc state, triggers its
    SIGUSR1/2 stack dumps, terminates it, and writes one tarball."""
    import urllib.request

    from cometbft_tpu.config import Config

    home = _prep_home(tmp_path, 28970)
    cfg = Config.load(f"{home}/config/config.toml")
    rpc = cfg.rpc.laddr.removeprefix("tcp://")
    port = int(rpc.rsplit(":", 1)[1])
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    log_path = str(tmp_path / "node.log")
    with open(log_path, "wb") as lf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu", "--home", home,
             "start"], stdout=lf, stderr=subprocess.STDOUT, env=env,
            cwd=REPO)
    try:
        deadline = time.monotonic() + 90
        while True:
            assert proc.poll() is None, "node died during warm-up"
            try:
                st = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=2).read())
                if st["result"]["sync_info"]["latest_block_height"] >= 2:
                    break
            except Exception:
                pass
            assert time.monotonic() < deadline, "node never reached height"
            time.sleep(0.3)

        out = str(tmp_path / "kill-bundle.tar.gz")
        res = _run_cli("debug", "kill", str(proc.pid), out,
                       "--rpc", rpc, home=home)
        assert res.returncode == 0, res.stdout + res.stderr
        # the node is gone (TimeoutExpired here = kill failed)
        proc.wait(timeout=15)
        # the bundle carries live RPC state, config, and process state
        with tarfile.open(out) as tar:
            names = tar.getnames()

            def read(suffix):
                name = next(n for n in names if n.endswith(suffix))
                return tar.extractfile(name).read()

            st = json.loads(read("status.json"))
            assert st["node_info"]["network"] == "tools-chain"
            assert json.loads(read("dump_consensus_state.json"))
            assert b"[p2p]" in read("config.toml") or \
                b"laddr" in read("config.toml")
            proc_state = read("proc_state.txt").decode()
            assert "cmdline" in proc_state and "threads:" in proc_state
            assert b"terminated" in read("kill.txt")
        # the SIGUSR1/2 dumps landed in the node's own log
        log = open(log_path, "rb").read().decode(errors="replace")
        assert "asyncio tasks ===" in log       # SIGUSR2 task dump
        assert "Current thread" in log or "Thread 0x" in log  # SIGUSR1
    finally:
        if proc.poll() is None:
            proc.kill()


def test_offline_tooling_refuses_running_node(tmp_path):
    """A live node holds the data-dir flock; compact-db/reindex-event on
    the same home must refuse instead of corrupting the open LogDB."""
    import signal as _signal

    home = _prep_home(tmp_path, 28980)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "start"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    try:
        deadline = time.time() + 30
        lock_path = os.path.join(home, "data", "LOCK")
        while not os.path.exists(lock_path) and time.time() < deadline:
            time.sleep(0.2)
        time.sleep(1.0)          # let the node actually take the flock
        res = _run_cli("compact-db", home=home)
        assert res.returncode == 1, res.stdout
        assert "locked by a running node" in res.stderr
        res = _run_cli("reindex-event", home=home)
        assert res.returncode == 1
    finally:
        proc.send_signal(_signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
    # after the node exits the lock is free again
    res = _run_cli("compact-db", home=home)
    assert res.returncode == 0, res.stdout + res.stderr
