"""Dense VerifyCommit fast path: exact behavioral parity with the
per-lane loop (types/validation._verify), including Light's early exit,
nil/absent handling, and failure localization."""

import copy

import pytest

from cometbft_tpu.testing import make_light_chain
from cometbft_tpu.types import validation as V
from cometbft_tpu.types.commit import (BLOCK_ID_FLAG_ABSENT,
                                       BLOCK_ID_FLAG_COMMIT,
                                       BLOCK_ID_FLAG_NIL)


@pytest.fixture(scope="module")
def chain():
    return make_light_chain(1, n_vals=40)[0]


def outcomes(fn, *args, **kw):
    """(type(exc) | None, exc.idx if any) for comparing the two paths."""
    try:
        fn(*args, **kw)
        return None, None
    except V.CommitVerificationError as e:
        return type(e), getattr(e, "idx", None)


def both_paths(monkeypatch, fn, chain_id, vals, commit, lb):
    fast = outcomes(fn, chain_id, vals, commit.block_id, lb.height, commit,
                    backend="cpu")
    monkeypatch.setattr(V, "_dense_verify", lambda *a, **k: False)
    slow = outcomes(fn, chain_id, vals, commit.block_id, lb.height, commit,
                    backend="cpu")
    monkeypatch.undo()
    return fast, slow


@pytest.mark.parametrize("fn", [V.VerifyCommit, V.VerifyCommitLight,
                                V.VerifyCommitLightAllSignatures])
def test_parity_valid_commit(monkeypatch, chain, fn):
    fast, slow = both_paths(monkeypatch, fn, "light-chain",
                            chain.validators, chain.commit, chain)
    assert fast == slow == (None, None)


@pytest.mark.parametrize("fn", [V.VerifyCommit, V.VerifyCommitLight,
                                V.VerifyCommitLightAllSignatures])
@pytest.mark.parametrize("bad_idx", [0, 17, 39])
def test_parity_bad_signature(monkeypatch, chain, fn, bad_idx):
    c = copy.deepcopy(chain.commit)
    c.signatures[bad_idx].signature = bytes(64)
    fast, slow = both_paths(monkeypatch, fn, "light-chain",
                            chain.validators, c, chain)
    assert fast == slow
    # early-exit variants may or may not reach the lane; when they raise,
    # both must name the same lane
    if fast[0] is not None:
        assert fast[0] is V.ErrInvalidSignature and fast[1] == bad_idx


def test_parity_nil_and_absent_lanes(monkeypatch, chain):
    c = copy.deepcopy(chain.commit)
    # nil-ify some lanes (their sigs no longer match -> VerifyCommit,
    # which checks nil sigs, must fail; Light skips them)
    for i in (3, 5):
        c.signatures[i].block_id_flag = BLOCK_ID_FLAG_NIL
    for i in (7,):
        c.signatures[i].block_id_flag = BLOCK_ID_FLAG_ABSENT
        c.signatures[i].signature = b""
        c.signatures[i].validator_address = b""
    for fn in (V.VerifyCommit, V.VerifyCommitLight,
               V.VerifyCommitLightAllSignatures):
        fast, slow = both_paths(monkeypatch, fn, "light-chain",
                                chain.validators, c, chain)
        assert fast == slow, fn.__name__
    # VerifyCommit must reject (nil lanes signed the commit block id, so
    # their sigs don't verify against the nil-variant sign bytes)
    assert outcomes(V.VerifyCommit, "light-chain", chain.validators,
                    c.block_id, chain.height, c,
                    backend="cpu")[0] is V.ErrInvalidSignature


def test_light_early_exit_skips_trailing_bad_sig(monkeypatch, chain):
    """A bad signature in the last lane is never verified by Light once
    2/3 is already tallied — on BOTH paths."""
    c = copy.deepcopy(chain.commit)
    c.signatures[-1].signature = bytes(64)
    fast, slow = both_paths(monkeypatch, V.VerifyCommitLight, "light-chain",
                            chain.validators, c, chain)
    assert fast == slow == (None, None)
    # the all-signatures variant does verify it
    fast, slow = both_paths(monkeypatch, V.VerifyCommitLightAllSignatures,
                            "light-chain", chain.validators, c, chain)
    assert fast == slow and fast[0] is V.ErrInvalidSignature


def test_not_enough_power_parity(monkeypatch, chain):
    c = copy.deepcopy(chain.commit)
    for cs in c.signatures[: len(c.signatures) * 2 // 3 + 1]:
        cs.block_id_flag = BLOCK_ID_FLAG_ABSENT
        cs.signature = b""
        cs.validator_address = b""
    for fn in (V.VerifyCommit, V.VerifyCommitLight):
        fast, slow = both_paths(monkeypatch, fn, "light-chain",
                                chain.validators, c, chain)
        assert fast == slow and fast[0] is V.ErrNotEnoughVotingPower


def test_dense_cache_invalidation():
    from cometbft_tpu.types.validator_set import Validator

    lb = make_light_chain(1, n_vals=8)[0]
    vals = lb.validators.copy()
    d1 = vals.dense()
    assert d1 is not None and d1[0].shape == (8, 32)
    grown = vals.validators[0].copy()
    grown.voting_power += 5
    vals.update_with_change_set([grown])
    d2 = vals.dense()
    assert d2 is not None
    assert d2[1][[v.address for v in vals.validators].index(
        grown.address)] == grown.voting_power


def test_dense_not_applicable_odd_sig_size(monkeypatch, chain):
    """A 63-byte signature disables the dense path; outcomes still match."""
    c = copy.deepcopy(chain.commit)
    c.signatures[2].signature = c.signatures[2].signature[:63]
    assert c.dense_columns() is None
    fast, slow = both_paths(monkeypatch, V.VerifyCommit, "light-chain",
                            chain.validators, c, chain)
    assert fast == slow and fast[0] is V.ErrInvalidSignature


def test_native_sign_bytes_builder_byte_parity():
    """build_vote_sign_bytes must be byte-exact with CanonicalVoteEncoder
    for BOTH the commit and nil variants across timestamp edge cases
    (zero, sub-second, negative, varint-width boundaries, huge)."""
    import numpy as np

    from cometbft_tpu.crypto import _native_ed25519 as nat
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.canonical import (SIGNED_MSG_TYPE_PRECOMMIT,
                                              CanonicalVoteEncoder)

    assert nat.available()
    bid = BlockID(b"\x11" * 32, PartSetHeader(3, b"\x22" * 32))
    enc_c = CanonicalVoteEncoder("parity-chain", SIGNED_MSG_TYPE_PRECOMMIT,
                                 12345, 2, bid)
    enc_n = CanonicalVoteEncoder("parity-chain", SIGNED_MSG_TYPE_PRECOMMIT,
                                 12345, 2, BlockID())
    tss = [0, 1, 127, 128, 999_999_999, 1_000_000_000,
           1_000_000_001, 2**63 - 1, 1_700_000_000_123_456_789,
           -1, -999_999_999, -1_000_000_001, 2**62]
    flags = [2, 3, 1, 2, 3] * 3
    tss = (tss * 2)[:len(flags)]
    msgs, lens = nat.build_vote_sign_bytes(
        enc_c._prefix, enc_n._prefix, enc_c._suffix,
        np.array(tss, np.int64), np.array(flags, np.uint8))
    for i, (ts, fl) in enumerate(zip(tss, flags)):
        want = (enc_c if fl == 2 else enc_n).sign_bytes(ts)
        assert bytes(msgs[i, :lens[i]]) == want, (ts, fl)


def test_dense_columns_rejects_out_of_range_ints():
    """Peer-supplied out-of-range flags/timestamps must disable the dense
    path (returning None), never crash blocksync with OverflowError."""
    lb = make_light_chain(1, n_vals=4)[0]
    c = copy.deepcopy(lb.commit)
    c.signatures[1].block_id_flag = 300          # > uint8
    assert c.dense_columns() is None
    # the numpy-1.x wrap hazard: 258 would silently become 2 (== COMMIT)
    # under a dtype conversion; the explicit Python bound check must
    # reject it on every numpy major (ADVICE r3)
    c258 = copy.deepcopy(lb.commit)
    c258.signatures[1].block_id_flag = 258
    assert c258.dense_columns() is None
    c2 = copy.deepcopy(lb.commit)
    c2.signatures[2].timestamp_ns = 2**64        # > int64
    assert c2.dense_columns() is None
    # and the full call still completes via the loop path
    outcome = outcomes(V.VerifyCommit, "light-chain", lb.validators,
                       c2.block_id, lb.height, c2, backend="cpu")
    assert outcome[0] is not None  # rejects, but through the loop


def trusting_paths(monkeypatch, vals, commit, **kw):
    def once():
        try:
            V.VerifyCommitLightTrusting("light-chain", vals, commit, **kw)
            return None, None
        except V.CommitVerificationError as e:
            return type(e), getattr(e, "idx", None)

    fast = once()
    monkeypatch.setattr(V, "_dense_verify_trusting", lambda *a, **k: False)
    slow = once()
    monkeypatch.undo()
    return fast, slow


def test_trusting_parity_same_set(monkeypatch, chain):
    fast, slow = trusting_paths(monkeypatch, chain.validators,
                                chain.commit, backend="cpu")
    assert fast == slow == (None, None)


def test_trusting_parity_subset_overlap(monkeypatch, chain):
    """Trusted set is a STRICT SUBSET of the signing set (the skipping-
    verification scenario): only overlapping validators count."""
    from cometbft_tpu.types.validator_set import ValidatorSet

    sub = ValidatorSet([v.copy() for v in chain.validators.validators[:20]])
    fast, slow = trusting_paths(monkeypatch, sub, chain.commit,
                                backend="cpu")
    assert fast == slow
    # 20 of 40 equal-power validators sign; default trust level 1/3 of
    # the SUB-set total is cleared
    assert fast == (None, None)


def test_trusting_parity_duplicate_address(monkeypatch, chain):
    c = copy.deepcopy(chain.commit)
    c.signatures[5].validator_address = c.signatures[4].validator_address
    c.signatures[5].timestamp_ns = c.signatures[4].timestamp_ns
    c.signatures[5].signature = c.signatures[4].signature
    fast, slow = trusting_paths(monkeypatch, chain.validators, c,
                                backend="cpu")
    assert fast == slow and fast[0] is V.ErrInvalidCommit


def test_trusting_nil_then_commit_same_address_accepted(monkeypatch, chain):
    """Reference ordering (validation.go:243-266): ignoreSig skips
    non-commit sigs BEFORE the seen-set/dup bookkeeping, so a NIL sig
    followed by a COMMIT sig from the same address is legal — on both
    the dense and loop trusting paths (ADVICE r3)."""
    from cometbft_tpu.types.commit import BLOCK_ID_FLAG_NIL

    c = copy.deepcopy(chain.commit)
    # lane 4 becomes a NIL vote carrying the same address as lane 5's
    # commit vote; only lane 5 should count, and nothing should raise
    c.signatures[4].validator_address = c.signatures[5].validator_address
    c.signatures[4].block_id_flag = BLOCK_ID_FLAG_NIL
    c.signatures[4].signature = bytes(64)       # NIL sigs aren't verified
    fast, slow = trusting_paths(monkeypatch, chain.validators, c,
                                backend="cpu")
    assert fast == slow == (None, None)


def test_trusting_parity_bad_signature(monkeypatch, chain):
    import fractions

    c = copy.deepcopy(chain.commit)
    c.signatures[3].signature = bytes(64)
    # trust level 1 => every overlapping commit sig must verify
    fast, slow = trusting_paths(monkeypatch, chain.validators, c,
                                backend="cpu",
                                trust_level=fractions.Fraction(1, 1),
                                count_all=True)
    assert fast == slow
    assert fast[0] in (V.ErrInvalidSignature, V.ErrNotEnoughVotingPower)
    if fast[0] is V.ErrInvalidSignature:
        assert fast[1] == 3


def test_trusting_early_exit_skips_trailing_bad_sig(monkeypatch, chain):
    c = copy.deepcopy(chain.commit)
    c.signatures[-1].signature = bytes(64)
    fast, slow = trusting_paths(monkeypatch, chain.validators, c,
                                backend="cpu")
    assert fast == slow == (None, None)   # 1/3 cleared long before
