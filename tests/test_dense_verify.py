"""Dense VerifyCommit fast path: exact behavioral parity with the
per-lane loop (types/validation._verify), including Light's early exit,
nil/absent handling, and failure localization."""

import copy

import pytest

from cometbft_tpu.testing import make_light_chain
from cometbft_tpu.types import validation as V
from cometbft_tpu.types.commit import (BLOCK_ID_FLAG_ABSENT,
                                       BLOCK_ID_FLAG_COMMIT,
                                       BLOCK_ID_FLAG_NIL)


@pytest.fixture(scope="module")
def chain():
    return make_light_chain(1, n_vals=40)[0]


def outcomes(fn, *args, **kw):
    """(type(exc) | None, exc.idx if any) for comparing the two paths."""
    try:
        fn(*args, **kw)
        return None, None
    except V.CommitVerificationError as e:
        return type(e), getattr(e, "idx", None)


def both_paths(monkeypatch, fn, chain_id, vals, commit, lb):
    fast = outcomes(fn, chain_id, vals, commit.block_id, lb.height, commit,
                    backend="cpu")
    monkeypatch.setattr(V, "_dense_verify", lambda *a, **k: False)
    slow = outcomes(fn, chain_id, vals, commit.block_id, lb.height, commit,
                    backend="cpu")
    monkeypatch.undo()
    return fast, slow


@pytest.mark.parametrize("fn", [V.VerifyCommit, V.VerifyCommitLight,
                                V.VerifyCommitLightAllSignatures])
def test_parity_valid_commit(monkeypatch, chain, fn):
    fast, slow = both_paths(monkeypatch, fn, "light-chain",
                            chain.validators, chain.commit, chain)
    assert fast == slow == (None, None)


@pytest.mark.parametrize("fn", [V.VerifyCommit, V.VerifyCommitLight,
                                V.VerifyCommitLightAllSignatures])
@pytest.mark.parametrize("bad_idx", [0, 17, 39])
def test_parity_bad_signature(monkeypatch, chain, fn, bad_idx):
    c = copy.deepcopy(chain.commit)
    c.signatures[bad_idx].signature = bytes(64)
    fast, slow = both_paths(monkeypatch, fn, "light-chain",
                            chain.validators, c, chain)
    assert fast == slow
    # early-exit variants may or may not reach the lane; when they raise,
    # both must name the same lane
    if fast[0] is not None:
        assert fast[0] is V.ErrInvalidSignature and fast[1] == bad_idx


def test_parity_nil_and_absent_lanes(monkeypatch, chain):
    c = copy.deepcopy(chain.commit)
    # nil-ify some lanes (their sigs no longer match -> VerifyCommit,
    # which checks nil sigs, must fail; Light skips them)
    for i in (3, 5):
        c.signatures[i].block_id_flag = BLOCK_ID_FLAG_NIL
    for i in (7,):
        c.signatures[i].block_id_flag = BLOCK_ID_FLAG_ABSENT
        c.signatures[i].signature = b""
        c.signatures[i].validator_address = b""
    for fn in (V.VerifyCommit, V.VerifyCommitLight,
               V.VerifyCommitLightAllSignatures):
        fast, slow = both_paths(monkeypatch, fn, "light-chain",
                                chain.validators, c, chain)
        assert fast == slow, fn.__name__
    # VerifyCommit must reject (nil lanes signed the commit block id, so
    # their sigs don't verify against the nil-variant sign bytes)
    assert outcomes(V.VerifyCommit, "light-chain", chain.validators,
                    c.block_id, chain.height, c,
                    backend="cpu")[0] is V.ErrInvalidSignature


def test_light_early_exit_skips_trailing_bad_sig(monkeypatch, chain):
    """A bad signature in the last lane is never verified by Light once
    2/3 is already tallied — on BOTH paths."""
    c = copy.deepcopy(chain.commit)
    c.signatures[-1].signature = bytes(64)
    fast, slow = both_paths(monkeypatch, V.VerifyCommitLight, "light-chain",
                            chain.validators, c, chain)
    assert fast == slow == (None, None)
    # the all-signatures variant does verify it
    fast, slow = both_paths(monkeypatch, V.VerifyCommitLightAllSignatures,
                            "light-chain", chain.validators, c, chain)
    assert fast == slow and fast[0] is V.ErrInvalidSignature


def test_not_enough_power_parity(monkeypatch, chain):
    c = copy.deepcopy(chain.commit)
    for cs in c.signatures[: len(c.signatures) * 2 // 3 + 1]:
        cs.block_id_flag = BLOCK_ID_FLAG_ABSENT
        cs.signature = b""
        cs.validator_address = b""
    for fn in (V.VerifyCommit, V.VerifyCommitLight):
        fast, slow = both_paths(monkeypatch, fn, "light-chain",
                                chain.validators, c, chain)
        assert fast == slow and fast[0] is V.ErrNotEnoughVotingPower


def test_dense_cache_invalidation():
    from cometbft_tpu.types.validator_set import Validator

    lb = make_light_chain(1, n_vals=8)[0]
    vals = lb.validators.copy()
    d1 = vals.dense()
    assert d1 is not None and d1[0].shape == (8, 32)
    grown = vals.validators[0].copy()
    grown.voting_power += 5
    vals.update_with_change_set([grown])
    d2 = vals.dense()
    assert d2 is not None
    assert d2[1][[v.address for v in vals.validators].index(
        grown.address)] == grown.voting_power


def test_dense_not_applicable_odd_sig_size(monkeypatch, chain):
    """A 63-byte signature disables the dense path; outcomes still match."""
    c = copy.deepcopy(chain.commit)
    c.signatures[2].signature = c.signatures[2].signature[:63]
    assert c.dense_columns() is None
    fast, slow = both_paths(monkeypatch, V.VerifyCommit, "light-chain",
                            chain.validators, c, chain)
    assert fast == slow and fast[0] is V.ErrInvalidSignature
