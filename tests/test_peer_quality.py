"""Peer quality subsystem: scorer decay/threshold math, timed addrbook
bans (+persistence), the Switch-level disconnect → ban → readmission
lifecycle over a real TCP net, the blocksync double-ban path, the RPC
admission gate (503 + Retry-After while /status stays up), and mempool
gossip backpressure."""

import asyncio
import json
import time

import msgpack
import pytest

from cometbft_tpu.p2p.addrbook import AddrBook
from cometbft_tpu.p2p.quality import EVENT_WEIGHTS, PeerScorer

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ------------------------------------------------------------- scorer math

def test_scorer_thresholds_and_actions():
    s = PeerScorer(disconnect_score=5.0, ban_score=10.0,
                   half_life_s=1000.0, ban_ttl_s=5.0)
    # invalid_vote weighs 2.0: two tolerated, third crosses disconnect
    assert s.report("p1", "invalid_vote") is None
    assert s.report("p1", "invalid_vote") is None
    assert s.report("p1", "invalid_vote") == "disconnect"
    assert s.score("p1") == pytest.approx(6.0, rel=0.01)
    # two bad blocks (5.0 each) cross the ban threshold
    assert s.report("p2", "bad_block") == "disconnect"
    assert s.report("p2", "bad_block") == "ban"
    assert s.is_banned("p2")
    assert not s.is_banned("p1")
    # a ban resets the score: readmission starts clean
    assert s.score("p2") == 0.0


def test_scorer_decay():
    s = PeerScorer(disconnect_score=5.0, ban_score=10.0,
                   half_life_s=0.05)
    s.report("p1", "invalid_vote")
    s.report("p1", "invalid_vote")
    assert s.score("p1") > 3.0
    time.sleep(0.12)                      # > 2 half-lives
    assert s.score("p1") < 1.5
    # decayed past the threshold: the same event no longer disconnects
    assert s.report("p1", "invalid_vote") is None


def test_scorer_ban_ttl_escalates_per_repeat():
    s = PeerScorer(disconnect_score=5.0, ban_score=5.0,
                   half_life_s=1000.0, ban_ttl_s=10.0, ban_ttl_max_s=25.0)
    assert s.report("p1", "bad_block") == "ban"
    assert s._bans["p1"]["ttl_s"] == 10.0
    assert s.report("p1", "bad_block") == "ban"
    assert s._bans["p1"]["ttl_s"] == 20.0
    assert s.report("p1", "bad_block") == "ban"
    assert s._bans["p1"]["ttl_s"] == 25.0       # capped
    info = s.peer_info("p1")
    assert info["ban_count"] == 3
    bans = s.bans_snapshot()
    assert bans and bans[0]["node_id"] == "p1"


def test_scorer_persistent_peers_never_banned():
    s = PeerScorer(disconnect_score=2.0, ban_score=4.0,
                   half_life_s=1000.0)
    for _ in range(10):
        action = s.report("pin", "bad_block", persistent=True)
        assert action == "disconnect"     # never "ban"
    assert not s.is_banned("pin")


def test_scorer_unknown_event_and_ledger_bound():
    s = PeerScorer(half_life_s=1000.0, max_tracked=4)
    s.report("px", "brand_new_event")     # DEFAULT_WEIGHT, no crash
    assert s.score("px") == pytest.approx(1.0, rel=0.01)
    for i in range(10):
        s.report(f"peer-{i}", "invalid_tx")
    assert len(s._peers) <= 4


def test_scorer_writes_timed_ban_into_addrbook(tmp_path):
    book = AddrBook(str(tmp_path / "book.json"))
    nid = "ab" * 20
    book.add(nid, "1.2.3.4:26656")
    s = PeerScorer(addr_book=book, disconnect_score=2.0, ban_score=3.0,
                   half_life_s=1000.0, ban_ttl_s=0.1)
    assert s.report(nid, "bad_block") == "ban"
    assert book.is_banned(nid) and s.is_banned(nid)
    assert not book.add(nid, "1.2.3.4:26656")    # refused while banned
    time.sleep(0.12)
    assert not s.is_banned(nid)                  # TTL expired: readmitted
    assert book.add(nid, "1.2.3.4:26656")


# --------------------------------------------------------- addrbook bans

def nid(i):
    return f"{i:040d}"


def test_addrbook_ban_expires_and_readmits():
    book = AddrBook()
    book.mark_bad(nid(1), ttl=0.05)
    assert book.is_banned(nid(1))
    assert not book.add(nid(1), "1.1.1.1:1")
    time.sleep(0.06)
    assert not book.is_banned(nid(1))
    assert book.add(nid(1), "1.1.1.1:1")


def test_addrbook_ban_expiry_persists_across_restart(tmp_path):
    path = str(tmp_path / "book.json")
    book = AddrBook(path)
    book.mark_bad(nid(1), ttl=3600.0)
    book.mark_bad(nid(2), ttl=0.01)
    time.sleep(0.02)
    book.save()
    with open(path) as f:
        raw = json.load(f)
    # schema: {node_id: expiry}; the already-expired ban is not written
    assert isinstance(raw["banned"], dict)
    assert nid(1) in raw["banned"] and nid(2) not in raw["banned"]
    book2 = AddrBook(path)
    assert book2.is_banned(nid(1))
    assert not book2.is_banned(nid(2))
    assert dict(book2.banned()).keys() == {nid(1)}


# ------------------------------------------------- blocksync double ban

def test_blockpool_redo_double_ban_and_refetch():
    """reactor.py's _RedoBlock path calls redo_request(h) AND
    redo_request(h+1): BOTH serving peers must be penalized with a
    bad_block event and both heights re-requested from a fresh peer."""
    from cometbft_tpu.blocksync.pool import BlockPool

    class Blk:
        def __init__(self, h):
            self.header = type("H", (), {"height": h})()

    async def main():
        requests = []           # (peer_id, height)
        errors = []             # (peer_id, reason, event)
        pool = BlockPool(
            1, lambda p, h: requests.append((p, h)),
            lambda p, r, e: errors.append((p, r, e)))
        pool.set_peer_range("A", 1, 10)
        pool.set_peer_range("B", 1, 10)
        pool.start()
        try:
            # wait for requesters at h1/h2 to pick peers and feed them
            deadline = time.monotonic() + 5
            while not ({h for _, h in requests} >= {1, 2}):
                assert time.monotonic() < deadline, requests
                await asyncio.sleep(0.01)
            served = {h: p for p, h in requests}
            assert served[1] != served[2], \
                "test needs distinct serving peers"
            pool.add_block(served[1], Blk(1))
            pool.add_block(served[2], Blk(2))
            await asyncio.sleep(0.05)
            assert len(pool.peek_window(2)) == 2

            # downstream verification failed at h1: double redo
            requests.clear()
            pool.set_peer_range("C", 1, 10)   # the fresh peer
            assert pool.redo_request(1) == served[1]
            assert pool.redo_request(2) == served[2]
            # both penalized with the typed bad_block event
            assert sorted((p, e) for p, _, e in errors) == \
                sorted([(served[1], "bad_block"), (served[2], "bad_block")])
            assert served[1] not in pool.peers
            assert served[2] not in pool.peers
            # both heights re-requested from the remaining fresh peer
            deadline = time.monotonic() + 5
            while not ({h for p, h in requests if p == "C"} >= {1, 2}):
                assert time.monotonic() < deadline, requests
                await asyncio.sleep(0.01)
            assert pool.peek_window(2) == []   # held blocks discarded
        finally:
            await pool.stop()
            await asyncio.sleep(0.05)   # let cancelled requesters settle

    run(main())


def test_blockpool_plain_removal_is_not_scored():
    """A peer that merely disconnects (switch-initiated removal) must
    not be reported as misbehavior."""
    from cometbft_tpu.blocksync.pool import BlockPool

    async def main():
        errors = []
        pool = BlockPool(1, lambda p, h: None,
                         lambda p, r, e: errors.append((p, r, e)))
        pool.set_peer_range("A", 1, 10)
        pool.remove_peer("A", "peer left")       # event=None default
        assert errors == []

    run(main())


# ------------------------------------------------- reactor event mapping

def test_consensus_reactor_maps_handler_errors_to_events():
    from cometbft_tpu.consensus.reactor import ConsensusReactor
    from cometbft_tpu.types.part_set import PartSetError
    from cometbft_tpu.types.vote_set import VoteSetError

    class StubCS:
        name = "stub"
        rs = None
        state = None

    class StubSwitch:
        def __init__(self):
            self.reports = []

        def report_peer(self, pid, event, detail="", **kw):
            self.reports.append((pid, event))

    async def main():
        r = ConsensusReactor(StubCS())
        sw = StubSwitch()
        r.set_switch(sw)
        r._on_peer_misbehavior("p1", "vote", VoteSetError("bad sig"))
        r._on_peer_misbehavior("p1", "part", PartSetError("bad proof"))
        r._on_peer_misbehavior("p1", "proposal",
                               VoteSetError("bad proposal sig"))
        # NON-validation failures raised while processing the message
        # (app socket flaps, storage hiccups) must NOT blame the sender
        r._on_peer_misbehavior("p1", "vote", ConnectionResetError())
        r._on_peer_misbehavior("p1", "vote", ValueError("app burp"))
        assert [e for _, e in sw.reports] == \
            ["invalid_vote", "invalid_part", "invalid_proposal"]

    run(main())


def test_evidence_reactor_not_applicable_is_not_scored(monkeypatch):
    from cometbft_tpu.evidence.reactor import EvidenceReactor
    from cometbft_tpu.types import codec
    from cometbft_tpu.types.evidence import (EvidenceError,
                                             EvidenceNotApplicableError)
    import msgpack as _mp

    class StubPool:
        on_evidence_added = None

        def __init__(self, exc):
            self.exc = exc

        def add_evidence(self, ev):
            raise self.exc

    class StubSwitch:
        def __init__(self):
            self.reports = []

        def report_peer(self, pid, event, detail="", **kw):
            self.reports.append((pid, event))

    class FakePeer:
        id = "peer-e"

    monkeypatch.setattr(codec, "unpack", lambda b: object())
    msg = _mp.packb({"@": "ev", "e": b"x"}, use_bin_type=True)

    # expired / below-base / no-state evidence: dropped without blame
    r = EvidenceReactor(StubPool(EvidenceNotApplicableError("too old")))
    sw = StubSwitch()
    r.set_switch(sw)
    r.receive(0x38, FakePeer(), msg)
    assert sw.reports == []
    # actually-invalid evidence: heavy score + disconnect
    r2 = EvidenceReactor(StubPool(EvidenceError("bad signature")))
    sw2 = StubSwitch()
    r2.set_switch(sw2)
    r2.receive(0x38, FakePeer(), msg)
    assert sw2.reports == [("peer-e", "bad_evidence")]


def test_statesync_sender_ban_feeds_metrics_and_scorer():
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.statesync.syncer import Syncer, _ss_metrics

    class StubSwitch:
        def __init__(self):
            self.reports = []

        def report_peer(self, pid, event, detail="", **kw):
            self.reports.append((pid, event, kw.get("disconnect")))

    class StubReactor:
        switch = StubSwitch()

    sy = Syncer(None, None, reactor=StubReactor(), name="ssq")
    before = m.counter("statesync_senders_banned_total").value(node="ssq")
    sy._note_sender_banned("evil-peer")
    assert "evil-peer" in sy._banned
    assert m.counter("statesync_senders_banned_total") \
        .value(node="ssq") == before + 1
    assert StubReactor.switch.reports == \
        [("evil-peer", "bad_snapshot_chunk", True)]
    assert _ss_metrics().formats_rejected is not None


def test_switch_late_report_honors_persistent_exemption():
    """Misbehavior reports landing AFTER a persistent peer disconnected
    (queued consensus messages, in-flight CheckTx) must not ban it —
    the exemption rides the remembered persistent id, not the live
    Peer object."""
    from cometbft_tpu.p2p import NodeKey, Switch, Transport

    async def main():
        sw = Switch(Transport(NodeKey.from_secret(b"late-report"),
                              lambda: None))
        pid = "ff" * 20
        sw._persistent_ids.add(pid)       # as _add_peer(persistent=True)
        # two bad blocks would ban (5+5 >= 10) a normal peer...
        assert sw.report_peer(pid, "bad_block") == "disconnect"
        assert sw.report_peer(pid, "bad_block") == "disconnect"
        assert not sw.scorer.is_banned(pid)
        # ...and does ban an unpinned one
        assert sw.report_peer("aa" * 20, "bad_block") == "disconnect"
        assert sw.report_peer("aa" * 20, "bad_block") == "ban"

    run(main())


# --------------------------------------------------- live-net lifecycle

async def _mk_quality_node(i, doc, pv, *, tweak=None):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey

    cfg = Config(consensus=test_consensus_config())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = ""
    cfg.base.signature_backend = "cpu"
    cfg.instrumentation.watchdog_stall_threshold_s = 0.0
    if tweak is not None:
        tweak(cfg)
    node = await Node.create(
        doc, KVStoreApplication(), priv_validator=pv, config=cfg,
        node_key=NodeKey.from_secret(b"pq-%d" % i), name=f"pq{i}")
    await node.start()
    return node


def test_switch_ban_lifecycle_over_real_net():
    """report_peer escalation on a live 2-node TCP net: score -> timed
    ban -> redial refused -> TTL expiry -> readmitted.  Also checks the
    /net_info quality/bans surfaces and the ban counter."""
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.rpc.core import Environment, net_info
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    pvs = [MockPV.from_secret(b"pq-val-%d" % i) for i in range(2)]
    doc = GenesisDoc(chain_id="pq-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])

    def tweak(cfg):
        # one bad_block (5.0) disconnects, the second bans
        cfg.p2p.quality_disconnect_score = 4.0
        cfg.p2p.quality_ban_score = 8.0
        cfg.p2p.quality_ban_ttl_s = 0.8
        cfg.p2p.quality_half_life_s = 600.0

    async def main():
        a = await _mk_quality_node(0, doc, pvs[0], tweak=tweak)
        b = await _mk_quality_node(1, doc, pvs[1], tweak=tweak)
        try:
            await b.switch.dial_peer(a.listen_addr, persistent=False)
            # wait for A to see B
            deadline = time.monotonic() + 10
            while b.node_key.id not in a.switch.peers:
                assert time.monotonic() < deadline
                await asyncio.sleep(0.02)
            bid = b.node_key.id
            bans_before = m.counter("p2p_peer_bans_total").value(
                node=a.node_key.id[:8], reason="bad_block")
            # quality visible per-peer in the snapshot
            snap = a.switch.peer_snapshot()
            assert all("quality" in p for p in snap)

            a.switch.report_peer(bid, "bad_block", detail="test bad block")
            assert a.switch.report_peer(
                bid, "bad_block", detail="again") == "ban"
            assert a.switch.scorer.is_banned(bid)
            assert m.counter("p2p_peer_bans_total").value(
                node=a.node_key.id[:8], reason="bad_block") == \
                bans_before + 1
            deadline = time.monotonic() + 10
            while bid in a.switch.peers:
                assert time.monotonic() < deadline
                await asyncio.sleep(0.02)
            # /net_info carries the active ban
            ni = await net_info(Environment(a))
            assert any(x["node_id"] == bid for x in ni["bans"])
            # A refuses the banned peer at the door — outbound (raises
            # on OUR side) and inbound (B's dial lands no peer on A)
            with pytest.raises(Exception, match="banned"):
                await a.switch.dial_peer(b.listen_addr, persistent=False)
            try:
                await b.switch.dial_peer(a.listen_addr, persistent=False)
            except Exception:
                pass                 # A may close mid-handshake
            await asyncio.sleep(0.2)
            assert bid not in a.switch.peers
            # ... and admitted again once the TTL expires
            await asyncio.sleep(0.9)
            assert not a.switch.scorer.is_banned(bid)
            await a.switch.dial_peer(b.listen_addr, persistent=False)
            assert bid in a.switch.peers
        finally:
            for n in (a, b):
                try:
                    await n.stop()
                except Exception:
                    pass

    run(main())


# ------------------------------------------------------ rpc admission gate

def test_rpc_gate_sheds_503_while_status_stays_up():
    from cometbft_tpu.config import Config
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.rpc.server import RPCServer

    release = asyncio.Event()

    async def slow(env):
        await release.wait()
        return {"done": True}

    async def fast_status(env):
        return {"ok": True}

    class StubNode:
        config = Config()
        config.rpc.max_concurrent_requests = 1
        config.rpc.max_queued_requests = 0
        config.rpc.shed_retry_after_s = 2.0

    async def http_get(host, port, path):
        r, w = await asyncio.open_connection(host, port)
        w.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                "Connection: close\r\n\r\n".encode())
        await w.drain()
        raw = await r.read()
        w.close()
        head, _, body = raw.partition(b"\r\n\r\n")
        lines = head.decode().split("\r\n")
        status = int(lines[0].split(" ")[1])
        headers = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
        return status, headers, body

    async def main():
        srv = RPCServer(StubNode(),
                        routes={"slow": slow, "status": fast_status})
        host, port = await srv.listen("127.0.0.1", 0)
        try:
            shed_before = m.counter("rpc_requests_shed_total").value()
            t1 = asyncio.create_task(http_get(host, port, "/slow"))
            # let the first request occupy the single gate slot
            for _ in range(200):
                await asyncio.sleep(0.01)
                if srv._gate_active >= 1:
                    break
            assert srv._gate_active == 1
            # second gated request: queue depth 0 -> immediate 503
            st2, hdr2, body2 = await http_get(host, port, "/slow")
            assert st2 == 503
            assert hdr2.get("retry-after") == "2"
            assert b"overloaded" in body2
            assert m.counter("rpc_requests_shed_total").value() == \
                shed_before + 1
            # the diagnostic route bypasses the gate entirely
            st3, _, body3 = await http_get(host, port, "/status")
            assert st3 == 200 and b"ok" in body3
            release.set()
            st1, _, _ = await asyncio.wait_for(t1, 10)
            # gate drained: the next request is admitted again
            st4, _, _ = await http_get(host, port, "/slow")
            assert st1 == 200 and st4 == 200
            assert srv._gate_active == 0
        finally:
            await srv.close()

    run(main())


# ------------------------------------------------ mempool backpressure

def test_mempool_full_gossip_skips_checktx():
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.mempool.clist_mempool import CListMempool
    from cometbft_tpu.mempool.reactor import (MEMPOOL_CHANNEL,
                                              MempoolReactor)

    class Res:
        is_ok = True
        code = 0
        log = ""
        gas_wanted = 1

    class CountingApp:
        def __init__(self):
            self.calls = 0

        async def check_tx(self, tx, recheck=False):
            self.calls += 1
            return Res()

    class FakePeer:
        id = "peer-x"

    async def main():
        app = CountingApp()
        mp = CListMempool(app, max_txs=1, metrics_node="mpq")
        await mp.check_tx(b"tx-one")            # fill to capacity
        assert app.calls == 1 and mp.size() == 1
        reactor = MempoolReactor(mp)
        skips = m.counter("mempool_gossip_full_skips_total")
        before = skips.value(node="mpq")
        reactor.receive(MEMPOOL_CHANNEL, FakePeer(),
                        msgpack.packb({"txs": [b"tx-two", b"tx-three"]},
                                      use_bin_type=True))
        await asyncio.sleep(0.05)               # any spawned task runs
        assert app.calls == 1, "full mempool must not invoke CheckTx"
        assert skips.value(node="mpq") == before + 2

    run(main())


def test_mempool_invalid_gossip_scores_sender():
    from cometbft_tpu.mempool.clist_mempool import CListMempool
    from cometbft_tpu.mempool.reactor import (MEMPOOL_CHANNEL,
                                              MempoolReactor)

    class Res:
        is_ok = False
        code = 7
        log = "nope"
        gas_wanted = 0

    class RejectingApp:
        async def check_tx(self, tx, recheck=False):
            return Res()

    class StubSwitch:
        def __init__(self):
            self.reports = []

        def report_peer(self, pid, event, detail="", **kw):
            self.reports.append((pid, event))

    class FakePeer:
        id = "peer-y"

    async def main():
        mp = CListMempool(RejectingApp(), max_txs=100,
                          metrics_node="mpq2")
        reactor = MempoolReactor(mp)
        sw = StubSwitch()
        reactor.set_switch(sw)
        reactor.receive(MEMPOOL_CHANNEL, FakePeer(),
                        msgpack.packb({"txs": [b"bad-tx"]},
                                      use_bin_type=True))
        deadline = time.monotonic() + 5
        while not sw.reports:
            assert time.monotonic() < deadline
            await asyncio.sleep(0.01)
        assert sw.reports == [("peer-y", "invalid_tx")]

    run(main())
