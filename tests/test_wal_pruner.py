"""WAL segment rotation + background pruner (reference:
``internal/autofile/group_test.go``, ``state/pruner.go``)."""

import asyncio
import os

import pytest

from cometbft_tpu.consensus.wal import WAL

pytestmark = pytest.mark.timeout(60)


def test_wal_rotates_and_replays_across_segments(tmp_path):
    path = str(tmp_path / "cs.wal")
    wal = WAL(path, max_segment_bytes=2048)
    # no sentinels yet: nothing may be pruned, so rotation is observable
    wal.write_sync({"#": "endheight", "h": 0})  # raw record, no pruning
    for h in (3, 4, 5):
        for i in range(20):
            wal.write({"#": "vote", "peer": "", "data": {"h": h, "i": i,
                                                         "pad": "x" * 64}})
        wal.write({"#": "endheight", "h": h})
    wal.flush_and_sync()
    segs = wal._segments()
    assert len(segs) > 1, "no rotation happened"
    # replay after height 3 sees exactly the height 4+5 records,
    # crossing segment boundaries
    recs = wal.records_after_height(3)
    hs = {r["data"]["h"] for r in recs}
    assert hs == {4, 5}, hs
    wal.close()

    # reopen: same answer (cross-segment iteration from disk)
    wal2 = WAL(path, max_segment_bytes=2048)
    recs2 = wal2.records_after_height(3)
    assert len(recs2) == len(recs)
    # checkpointing now prunes segments wholly before the last sentinel
    wal2.write_end_height(6)
    assert len(wal2._segments()) < len(segs) + 1
    assert wal2.records_after_height(6) == []
    wal2.close()


def test_wal_prunes_old_segments(tmp_path):
    path = str(tmp_path / "cs.wal")
    wal = WAL(path, max_segment_bytes=1024)
    for h in range(1, 12):
        for i in range(10):
            wal.write({"#": "vote", "peer": "",
                       "data": {"h": h, "pad": "y" * 64}})
        wal.write_end_height(h)
    # old segments were dropped by the end-height checkpointing, but
    # replay after the LAST height still works
    assert wal.records_after_height(11) == []
    n_before = len(wal._segments())
    assert n_before < 11
    wal.close()


def test_wal_endheight_search_reads_only_tail_segments(tmp_path):
    """VERDICT r4 next 7: ``records_after_height`` binary-searches the
    segment list (autofile group.go:34-54 SearchForEndHeight parity)
    instead of decoding every record of every segment — a long-lived
    validator restarting with a big WAL must read O(log n) segment
    heads plus the tail, not the whole log."""
    path = str(tmp_path / "wal.log")
    wal = WAL(path, max_segment_bytes=1500)
    # many heights, padded records so segments rotate often; pruning is
    # deliberately defeated by reopening (prune boundary unknown) so the
    # full history stays on disk
    for h in range(1, 41):
        wal.write({"h": h, "pad": "x" * 300})
        wal.write({"h": h, "msg": "vote", "pad": "y" * 300})
        wal.write_sync({"#": "endheight", "h": h})
        wal._prev_sentinel_seg = None      # keep every segment
    segs = wal._segments()
    assert len(segs) >= 10, f"need many segments, got {len(segs)}"

    read_paths: list[str] = []
    orig = WAL._iter_segment

    def spy(self, p):
        read_paths.append(p)
        return orig(self, p)

    WAL._iter_segment = spy
    try:
        recs = wal.records_after_height(39)
    finally:
        WAL._iter_segment = orig
    # correctness: exactly height 40's records follow EndHeight(39)
    assert [r["h"] for r in recs] == [40, 40]
    # efficiency: probes + tail scan, strictly less than the full log
    assert len(set(read_paths)) < len(segs), (
        f"read {len(set(read_paths))}/{len(segs)} segments")
    import math
    assert len(set(read_paths)) <= 2 * math.ceil(math.log2(len(segs))) + 3
    # the earliest segments were never touched
    assert segs[0] not in read_paths and segs[1] not in read_paths
    # and the verdict matches a full scan
    full = [r for r in wal.iter_records()]
    after = []
    seen = False
    for r in full:
        if r.get("#") == "endheight":
            seen = r["h"] == 39 or (seen and r["h"] > 39)
            continue
        if seen:
            after.append(r)
    assert recs == after
    wal.close()


def test_wal_torn_tail_truncated_on_reopen(tmp_path):
    path = str(tmp_path / "cs.wal")
    wal = WAL(path)
    wal.write_sync({"#": "vote", "peer": "", "data": 1})
    wal.write_end_height(1)
    wal.close()
    with open(path, "ab") as f:
        f.write(b"\x13\x37garbage-torn-tail")
    wal2 = WAL(path)
    recs = list(wal2.iter_records())
    assert len(recs) == 2
    wal2.close()


def _tear_next_write(path, spec, record, **wal_kw):
    """Arm the ``wal.write.torn`` chaos site, write one record (which
    tears), and return the reopened WAL."""
    from cometbft_tpu.consensus.wal import WAL, WALError
    from cometbft_tpu.libs import failures as F

    wal = WAL(path, **wal_kw)
    F.configure(enabled=True, seed=13, faults=[spec])
    try:
        with pytest.raises(WALError):
            wal.write(record)
        # fsyncgate: the torn handle is dead
        with pytest.raises(WALError):
            wal.write({"#": "vote", "n": -1})
    finally:
        F.reset()
        try:
            wal.close()
        except OSError:
            pass
    return WAL(path, **wal_kw)


@pytest.mark.parametrize("cut", ["header", "body"])
def test_wal_torn_write_truncated_on_reopen(tmp_path, cut):
    """Injected truncation mid-header and mid-record (wal.write.torn
    site): reopen keeps every intact record, drops the torn tail, and
    the WAL stays appendable."""
    from cometbft_tpu.consensus.wal import WAL

    path = str(tmp_path / "cs.wal")
    wal = WAL(path)
    for i in range(5):
        wal.write_sync({"#": "vote", "n": i, "pad": "x" * 40})
    wal.close()
    size_before = os.path.getsize(path)

    wal2 = _tear_next_write(path, f"wal.write.torn:at=1:cut={cut}",
                            {"#": "vote", "n": 99, "pad": "y" * 40})
    # the torn bytes hit the disk, but reopen truncated them: only the
    # 5 intact records remain and the file is back to its clean length
    recs = list(wal2.iter_records())
    assert [r["n"] for r in recs] == [0, 1, 2, 3, 4]
    assert os.path.getsize(path) == size_before
    wal2.write_sync({"#": "vote", "n": 100})
    assert [r["n"] for r in wal2.iter_records()][-1] == 100
    wal2.close()


def test_wal_torn_write_across_segment_boundary(tmp_path):
    """A torn record in a freshly-rotated segment: reopen truncates ONLY
    the new segment's tail; every earlier segment and the replay index
    (records_after_height) stay intact."""
    from cometbft_tpu.consensus.wal import WAL

    path = str(tmp_path / "cs.wal")
    wal = WAL(path, max_segment_bytes=1024)
    for h in (1, 2):
        for i in range(12):
            wal.write({"#": "vote", "peer": "",
                       "data": {"h": h, "i": i, "pad": "z" * 48}})
        wal.write_sync({"#": "endheight", "h": h})
        wal._prev_sentinel_seg = None       # keep every segment
    wal.flush_and_sync()
    segs = wal._segments()
    assert len(segs) > 1, "no rotation happened"
    wal.close()

    wal2 = _tear_next_write(path, "wal.write.torn:at=1:cut=body",
                            {"#": "vote", "peer": "", "data": {"h": 3}},
                            max_segment_bytes=1024)
    # replay after height 1 still yields exactly height 2's records,
    # crossing the intact segment boundary; the torn record is gone
    recs = wal2.records_after_height(1)
    assert {r["data"]["h"] for r in recs if "data" in r} == {2}
    assert wal2.records_after_height(2) == []
    # the earlier segments were untouched by the truncation
    assert wal2._segments()[:len(segs) - 1] == segs[:len(segs) - 1]
    wal2.close()


def test_wal_fsync_eio_site_kills_handle_not_file(tmp_path):
    """``wal.fsync.eio``: the failing fsync raises OSError(EIO), every
    later operation on the handle raises WALError (fsyncgate: no retry
    on the same fd), and a fresh open replays everything that landed."""
    import errno

    from cometbft_tpu.consensus.wal import WAL, WALError
    from cometbft_tpu.libs import failures as F

    path = str(tmp_path / "cs.wal")
    wal = WAL(path)
    wal.write_sync({"#": "vote", "n": 1})
    F.configure(enabled=True, seed=3, faults=["wal.fsync.eio:at=1"])
    try:
        with pytest.raises(OSError) as ei:
            wal.write_sync({"#": "vote", "n": 2})
        assert ei.value.errno == errno.EIO
        for op in (lambda: wal.flush_and_sync(),
                   lambda: wal.write({"#": "vote", "n": 3})):
            with pytest.raises(WALError):
                op()
    finally:
        F.reset()
    wal2 = WAL(path)
    # record 2's buffered write landed before the injected fsync failure
    assert [r["n"] for r in wal2.iter_records()] == [1, 2]
    wal2.close()


def test_pruner_honors_min_of_app_and_companion(tmp_path):
    from cometbft_tpu.sm.pruner import Pruner
    from cometbft_tpu.storage import BlockStore, MemDB, StateStore
    from cometbft_tpu.testing import make_light_chain
    from cometbft_tpu.types import codec
    from cometbft_tpu.types.part_set import PartSet

    bstore = BlockStore(MemDB())
    sstore = StateStore(MemDB())
    # synthesize a stored chain (structure only; pruning needs no sigs)
    from cometbft_tpu.types.header import Block, Data

    chain = make_light_chain(10, n_vals=2)
    prev_commit = None
    for lb in chain:
        block = Block(header=lb.header, data=Data(txs=[]),
                      evidence=[], last_commit=prev_commit)
        parts = PartSet.from_data(codec.pack(block))
        bstore.save_block(block, parts, lb.commit)
        prev_commit = lb.commit

    pruner = Pruner(sstore, bstore)
    assert bstore.base() == 1
    pruner.set_app_retain_height(8)
    assert pruner.prune_once() == 0 or bstore.base() == 8
    # companion lags at 5: effective retain is min(8, 5)
    bstore2 = bstore
    pruner.set_companion_retain_height(5)
    assert pruner.effective_retain_height() == 5
    pruner.set_companion_retain_height(0)        # companion detaches
    pruner.set_app_retain_height(9)
    pruned = pruner.prune_once()
    assert bstore2.base() == 9
    assert bstore2.load_block(8) is None
    assert bstore2.load_block(9) is not None


def test_pruner_via_rpc_route():
    from cometbft_tpu.rpc.core import (retain_heights,
                                       set_companion_retain_height,
                                       Environment)

    class FakePruner:
        def __init__(self):
            self.app, self.dc = 7, 0

        def retain_heights(self):
            return self.app, self.dc

        def effective_retain_height(self):
            return min(self.app, self.dc) if self.app and self.dc \
                else self.app or self.dc

        def set_companion_retain_height(self, h):
            self.dc = h

    class FakeStore:
        def base(self):
            return 3

    class FakeNode:
        pruner = FakePruner()
        block_store = FakeStore()

    env = Environment(FakeNode())

    async def main():
        r = await retain_heights(env)
        assert r["app_retain_height"] == 7 and r["store_base"] == 3
        await set_companion_retain_height(env, height=4)
        r2 = await retain_heights(env)
        assert r2["data_companion_retain_height"] == 4
        assert r2["effective"] == 4
        return True

    assert asyncio.run(main())
