"""Crash-point recovery matrix: kill a real node process at EVERY commit
-path fail point and assert the restarted process recovers and keeps
committing (reference: ``internal/fail`` + ``internal/consensus/
replay_test.go``'s crash table — 8 sites across state.go:1867-1936 and
state/execution.go:261-311)."""

import asyncio
import os
import signal
import subprocess
import sys
import time

import pytest

# crash/restart matrix over every commit failpoint: ~2 min of node
# restarts — tier-2 on the small CPU image.
pytestmark = [pytest.mark.timeout(400), pytest.mark.slow]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 28760

# one crash per commit-path stage (order of fail_point() calls per height:
# cs:before-save-block, cs:after-save-block, cs:after-wal-endheight,
# exec:after-finalize-block, exec:after-save-response,
# exec:after-app-commit, exec:after-state-save, cs:after-apply-block)
N_FAIL_POINTS = 8
# crash during the SECOND height's commit so there is real state to recover
FAIL_BASE = N_FAIL_POINTS


def _spawn(home, fail_index=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    if fail_index is not None:
        env["CMT_FAIL_INDEX"] = str(fail_index)
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "start"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)


def test_recovery_from_every_commit_crash_point(tmp_path):
    from cometbft_tpu.config import Config
    from cometbft_tpu.libs.fail import EXIT_CODE

    home = str(tmp_path / "solo")
    res = subprocess.run(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "init",
         "--chain-id", "crash-matrix"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
    assert res.returncode == 0, res.stderr
    cfgp = f"{home}/config/config.toml"
    cfg = Config.load(cfgp)
    cfg.consensus.timeout_propose = 200_000_000
    cfg.consensus.timeout_prevote = 100_000_000
    cfg.consensus.timeout_precommit = 100_000_000
    cfg.consensus.timeout_commit = 100_000_000
    cfg.base.signature_backend = "cpu"
    cfg.p2p.laddr = f"tcp://127.0.0.1:{BASE_PORT}"
    cfg.rpc.laddr = f"tcp://127.0.0.1:{BASE_PORT + 1}"
    cfg.save(cfgp)

    for stage in range(N_FAIL_POINTS):
        fail_index = FAIL_BASE + stage
        proc = _spawn(home, fail_index=fail_index)
        rc = proc.wait(timeout=120)
        assert rc == EXIT_CODE, (
            f"stage {stage}: expected fail-point exit {EXIT_CODE}, "
            f"got {rc}:\n{proc.stdout.read()[-2000:]}")

        # restart WITHOUT the fail point: must recover and commit further
        proc = _spawn(home)
        try:
            asyncio.run(_assert_recovers_and_progresses(stage))
        except BaseException:
            proc.send_signal(signal.SIGTERM)
            try:
                out = proc.communicate(timeout=10)[0]
            except subprocess.TimeoutExpired:
                proc.kill()
                out = ""
            print(f"--- stage {stage} node output:\n{out[-3000:]}")
            raise
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


async def _assert_recovers_and_progresses(stage):
    sys.path.insert(0, REPO)
    from cometbft_tpu.rpc import HTTPClient, RPCError

    cli = HTTPClient("127.0.0.1", BASE_PORT + 1)
    deadline = time.monotonic() + 90
    first_h = None
    while True:
        try:
            st = await cli.call("status")
            h = st["sync_info"]["latest_block_height"]
            if first_h is None:
                first_h = h
            if h >= max(first_h + 2, 3):
                break
        except (OSError, RPCError, asyncio.TimeoutError):
            pass
        assert time.monotonic() < deadline, \
            f"stage {stage}: node did not recover/progress"
        await asyncio.sleep(0.3)
    # the app and the chain agree after recovery
    info = await cli.call("abci_info")
    assert info["response"]["last_block_height"] >= first_h - 1


def test_app_ahead_crash_window_recovers_without_reexecution():
    """ADVICE r4 (medium): crash between app Commit and state save
    (exec:after-app-commit) leaves app_height == store_height ==
    state + 1 for a PERSISTENT app.  The handshake must advance state
    from the persisted finalize response — sending the app NOTHING (a
    re-execution would double-apply the block) — mirroring the
    reference's mock-app replayBlock case (replay.go ReplayBlocks)."""
    from cometbft_tpu.abci.client import LocalClient
    from cometbft_tpu.consensus.replay import Handshaker
    from cometbft_tpu.mempool.clist_mempool import CListMempool
    from cometbft_tpu.proxy.multi_app_conn import AppConns
    from cometbft_tpu.sm.execution import BlockExecutor
    from cometbft_tpu.storage.statestore import rollback_state
    from cometbft_tpu.testing import make_inproc_network
    from cometbft_tpu.types.genesis import GenesisDoc

    async def main():
        net = await make_inproc_network(1)
        await net.start()
        await net.wait_for_height(5)
        await net.stop()
        node = net.nodes[0]

        # crash window: state back to H-1; block store AND the live
        # persistent app both remain at H
        rollback_state(node.state_store, node.block_store)
        state = node.state_store.load()
        store_h = node.block_store.height()
        assert store_h == state.last_block_height + 1
        app = node.app                 # persistent: already committed H
        assert app.height == store_h
        want_app_hash = app.app_hash

        calls: list[str] = []
        orig_fin, orig_commit = app.finalize_block, app.commit
        app.finalize_block = lambda req: (
            calls.append(f"finalize:{req.height}") or orig_fin(req))
        app.commit = lambda: calls.append("commit") or orig_commit()

        async def creator():
            return LocalClient(app)

        conns = AppConns(creator)
        await conns.start()
        execu = BlockExecutor(node.state_store, node.block_store,
                              conns.consensus,
                              CListMempool(LocalClient(app)),
                              backend="cpu")
        hs = Handshaker(node.state_store, node.block_store,
                        GenesisDoc(chain_id="test-net", validators=[]))
        new_state = await hs.handshake(state, conns, execu)

        assert calls == [], f"app must not re-execute: {calls}"
        assert new_state.last_block_height == store_h
        assert new_state.app_hash == want_app_hash
        # the persisted state matches the returned one (restart-safe)
        assert node.state_store.load().last_block_height == store_h
        return True

    assert asyncio.run(main())


def test_crash_window_replay_applies_each_block_exactly_once():
    """Regression for the recovery-ordering bug: with the block store one
    ahead of state (crash between SaveBlock and ApplyBlock) and the app
    several blocks behind (fresh in-process app), the handshake must
    feed the app every block EXACTLY once and in order.  The old code
    ran the pending-block recovery before the catch-up replay and reused
    the pre-recovery app height, double-executing the pending block —
    masked by idempotent apps, fatal for stateful ones."""
    from cometbft_tpu.abci.client import LocalClient
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.replay import Handshaker
    from cometbft_tpu.mempool.clist_mempool import CListMempool
    from cometbft_tpu.proxy.multi_app_conn import AppConns
    from cometbft_tpu.sm.execution import BlockExecutor
    from cometbft_tpu.storage.statestore import rollback_state
    from cometbft_tpu.testing import make_inproc_network
    from cometbft_tpu.types.genesis import GenesisDoc

    async def main():
        net = await make_inproc_network(1)
        await net.start()
        await net.wait_for_height(5)
        await net.stop()
        node = net.nodes[0]

        # crash window: state back to H-1 while the block store keeps H
        rollback_state(node.state_store, node.block_store)
        state = node.state_store.load()
        store_h = node.block_store.height()
        assert store_h == state.last_block_height + 1

        seen: list[int] = []

        class SpyApp(KVStoreApplication):
            async def finalize_block(self, req):
                seen.append(req.height)
                return await super().finalize_block(req)

        app = SpyApp()                 # fresh: behind by the whole chain

        async def creator():
            return LocalClient(app)

        conns = AppConns(creator)
        await conns.start()
        execu = BlockExecutor(node.state_store, node.block_store,
                              conns.consensus,
                              CListMempool(LocalClient(app)),
                              backend="cpu")
        # genesis doc is only consulted for the state-height-0 branch,
        # which this scenario never takes
        hs = Handshaker(node.state_store, node.block_store,
                        GenesisDoc(chain_id="test-net", validators=[]))
        new_state = await hs.handshake(state, conns, execu)

        # every height 1..store_h exactly once, ascending
        assert seen == list(range(1, store_h + 1)), seen
        assert new_state.last_block_height == store_h
        assert new_state.app_hash == app.app_hash
        return True

    assert asyncio.run(main())
