"""Tier-1 consensus tests: in-process multi-validator ensembles
(the reference's internal/consensus/*_test.go strategy, SURVEY.md §4)."""

import asyncio

import pytest

from cometbft_tpu.testing import make_inproc_network

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_four_validators_commit_blocks():
    """THE milestone: 4 in-proc validators committing kvstore blocks."""

    async def main():
        net = await make_inproc_network(4)
        try:
            await net.start()
            # inject transactions on every node's mempool
            for i, node in enumerate(net.nodes):
                await node.mempool.check_tx(b"k%d=v%d" % (i, i))
            # a full proposer rotation so every node proposes at least once
            await net.wait_for_height(6, timeout=60)
            # all nodes agree on every block hash
            for h in range(1, 7):
                hashes = {n.block_store.load_block(h).hash()
                          for n in net.nodes}
                assert len(hashes) == 1, f"fork at height {h}"
            committed = set()
            for n in net.nodes:
                for h in range(1, n.block_store.height() + 1):
                    for tx in n.block_store.load_block(h).data.txs:
                        committed.add(bytes(tx))
            # every injected tx rode in on its owner's proposal turn
            want = {b"k%d=v%d" % (i, i) for i in range(4)}
            assert want <= committed, committed
            # the app executed them: key present in every app's state
            for n in net.nodes:
                if n.block_store.height() >= 6:
                    assert n.app.state.get(b"k0") == b"v0"
        finally:
            await net.stop()
        return True

    assert run(main())


def test_progress_with_one_node_down():
    """3 of 4 validators (> 2/3) keep committing; the 4th catches up via
    late vote delivery when healed (liveness under crash fault)."""

    async def main():
        net = await make_inproc_network(4)
        try:
            net.isolate("node3")
            await net.start()
            await net.wait_for_height(2, timeout=60, nodes=net.nodes[:3])
            assert net.nodes[3].block_store.height() == 0
        finally:
            await net.stop()
        return True

    assert run(main())


def test_no_progress_without_quorum():
    """With 2 of 4 isolated there is no +2/3: no blocks may be committed."""

    async def main():
        net = await make_inproc_network(4)
        try:
            net.isolate("node2")
            net.isolate("node3")
            await net.start()
            await asyncio.sleep(2.0)
            assert all(n.block_store.height() == 0 for n in net.nodes)
        finally:
            await net.stop()
        return True

    assert run(main())


def test_vote_extensions_enabled():
    """Extensions enabled from height 1: extended commits carry extension
    signatures and verify."""

    async def main():
        net = await make_inproc_network(4, vote_extensions_height=1)
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
            node = net.nodes[0]
            ext = node.block_store.load_block_extended_commit(1)
            assert ext is not None
            assert ext.ensure_extensions(True)
            n_with_ext = sum(1 for e in ext.extended_signatures
                             if e.commit_sig.is_commit()
                             and e.extension_signature)
            assert n_with_ext >= 3          # +2/3 signed extensions
        finally:
            await net.stop()
        return True

    assert run(main())


def test_wal_crash_recovery(tmp_path):
    """Kill a node mid-flight; restart from WAL + stores; it rejoins and
    the network continues (crash/recovery tier of SURVEY §4)."""

    async def main():
        net = await make_inproc_network(4, wal_dir=str(tmp_path))
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
            # hard-stop node0 (no graceful anything)
            victim = net.nodes[0]
            await victim.consensus.stop()
            net.isolate("node0")
            await net.wait_for_height(
                victim.block_store.height() + 1, timeout=60,
                nodes=net.nodes[1:])

            # restart consensus over the same stores + WAL
            from cometbft_tpu.config import test_consensus_config
            from cometbft_tpu.consensus.state import ConsensusState
            from cometbft_tpu.consensus.wal import WAL

            state = victim.state_store.load()
            cs2 = ConsensusState(
                test_consensus_config(), state,
                victim.consensus.block_exec, victim.block_store,
                wal=WAL(victim.wal_path), priv_validator=victim.pv,
                event_bus=victim.event_bus, name="node0r")
            victim.consensus = cs2
            net._wire(victim)
            net.heal("node0")
            await cs2.start()
            target = max(n.block_store.height() for n in net.nodes) + 2
            await net.wait_for_height(target, timeout=60)
            hashes = {n.block_store.load_block(target).hash()
                      for n in net.nodes}
            assert len(hashes) == 1
        finally:
            await net.stop()
        return True

    assert run(main())
