"""Tier-1 consensus tests: in-process multi-validator ensembles
(the reference's internal/consensus/*_test.go strategy, SURVEY.md §4)."""

import asyncio

import pytest

from cometbft_tpu.testing import make_inproc_network

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_four_validators_commit_blocks():
    """THE milestone: 4 in-proc validators committing kvstore blocks."""

    async def main():
        net = await make_inproc_network(4)
        try:
            await net.start()
            # inject transactions on every node's mempool
            for i, node in enumerate(net.nodes):
                await node.mempool.check_tx(b"k%d=v%d" % (i, i))
            # a full proposer rotation so every node proposes at least once
            await net.wait_for_height(6, timeout=60)
            # all nodes agree on every block hash
            for h in range(1, 7):
                hashes = {n.block_store.load_block(h).hash()
                          for n in net.nodes}
                assert len(hashes) == 1, f"fork at height {h}"
            committed = set()
            for n in net.nodes:
                for h in range(1, n.block_store.height() + 1):
                    for tx in n.block_store.load_block(h).data.txs:
                        committed.add(bytes(tx))
            # every injected tx rode in on its owner's proposal turn
            want = {b"k%d=v%d" % (i, i) for i in range(4)}
            assert want <= committed, committed
            # the app executed them: key present in every app's state
            for n in net.nodes:
                if n.block_store.height() >= 6:
                    assert n.app.state.get(b"k0") == b"v0"
        finally:
            await net.stop()
        return True

    assert run(main())


def test_progress_with_one_node_down():
    """3 of 4 validators (> 2/3) keep committing; the 4th catches up via
    late vote delivery when healed (liveness under crash fault)."""

    async def main():
        net = await make_inproc_network(4)
        try:
            net.isolate("node3")
            await net.start()
            await net.wait_for_height(2, timeout=60, nodes=net.nodes[:3])
            assert net.nodes[3].block_store.height() == 0
        finally:
            await net.stop()
        return True

    assert run(main())


def test_no_progress_without_quorum():
    """With 2 of 4 isolated there is no +2/3: no blocks may be committed."""

    async def main():
        net = await make_inproc_network(4)
        try:
            net.isolate("node2")
            net.isolate("node3")
            await net.start()
            await asyncio.sleep(2.0)
            assert all(n.block_store.height() == 0 for n in net.nodes)
        finally:
            await net.stop()
        return True

    assert run(main())


def test_vote_extensions_enabled():
    """Extensions enabled from height 1: extended commits carry extension
    signatures and verify."""

    async def main():
        net = await make_inproc_network(4, vote_extensions_height=1)
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
            node = net.nodes[0]
            ext = node.block_store.load_block_extended_commit(1)
            assert ext is not None
            assert ext.ensure_extensions(True)
            n_with_ext = sum(1 for e in ext.extended_signatures
                             if e.commit_sig.is_commit()
                             and e.extension_signature)
            assert n_with_ext >= 3          # +2/3 signed extensions
        finally:
            await net.stop()
        return True

    assert run(main())


def test_wal_crash_recovery(tmp_path):
    """Kill a node mid-flight; restart from WAL + stores; it rejoins and
    the network continues (crash/recovery tier of SURVEY §4)."""

    async def main():
        net = await make_inproc_network(4, wal_dir=str(tmp_path))
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
            # hard-stop node0 (no graceful anything)
            victim = net.nodes[0]
            await victim.consensus.stop()
            net.isolate("node0")
            await net.wait_for_height(
                victim.block_store.height() + 1, timeout=60,
                nodes=net.nodes[1:])

            # restart consensus over the same stores + WAL
            from cometbft_tpu.config import test_consensus_config
            from cometbft_tpu.consensus.state import ConsensusState
            from cometbft_tpu.consensus.wal import WAL

            state = victim.state_store.load()
            cs2 = ConsensusState(
                test_consensus_config(), state,
                victim.consensus.block_exec, victim.block_store,
                wal=WAL(victim.wal_path), priv_validator=victim.pv,
                event_bus=victim.event_bus, name="node0r")
            victim.consensus = cs2
            net._wire(victim)
            net.heal("node0")
            await cs2.start()
            target = max(n.block_store.height() for n in net.nodes) + 2
            await net.wait_for_height(target, timeout=60)
            hashes = {n.block_store.load_block(target).hash()
                      for n in net.nodes}
            assert len(hashes) == 1
        finally:
            await net.stop()
        return True

    assert run(main())


def test_pbts_enabled_network_commits():
    """Proposer-based timestamps from height 1: blocks carry proposer wall
    time, validated against synchrony bounds (PBTS path end-to-end)."""

    async def main():
        net = await make_inproc_network(4, pbts_height=1)
        try:
            await net.start()
            await net.wait_for_height(4, timeout=60)
            blocks = [net.nodes[0].block_store.load_block(h)
                      for h in range(1, 5)]
            for a, b in zip(blocks, blocks[1:]):
                assert b.header.time_ns > a.header.time_ns
        finally:
            await net.stop()
        return True

    assert run(main())


def test_invalid_proposal_is_rejected_and_chain_continues():
    """A forged proposal from the legitimate round-0 proposer carrying a
    garbage block gets nil prevotes; the chain still commits the height in
    a later round via honest proposers (the reference's invalid-proposal
    suite, internal/consensus/invalid_test.go)."""

    async def main():
        from cometbft_tpu.types import codec
        from cometbft_tpu.types.block_id import BlockID
        from cometbft_tpu.types.header import Block, Data, Header
        from cometbft_tpu.types.part_set import PartSet
        from cometbft_tpu.types.vote import Proposal

        net = await make_inproc_network(4)
        try:
            # figure out who proposes height 1 round 0 and silence them
            cs0 = net.nodes[0].consensus
            proposer_addr = cs0.state.validators.get_proposer().address
            byz = next(n for n in net.nodes
                       if n.pv.get_pub_key().address() == proposer_addr)
            net.isolate(byz.name)
            await net.start()

            # forge a structurally-valid but semantically-garbage block
            # signed by the legitimate proposer's key
            header = Header(chain_id="test-net", height=1, time_ns=1,
                            validators_hash=b"\x11" * 32,
                            next_validators_hash=b"\x22" * 32,
                            proposer_address=proposer_addr)
            bad = Block(header=header, data=Data(txs=[b"evil"]),
                        evidence=[], last_commit=None)
            bad.fill_hashes()
            parts = PartSet.from_data(codec.pack(bad))
            bid = BlockID(bad.hash(), parts.header())
            prop = Proposal(height=1, round=0, pol_round=-1, block_id=bid,
                            timestamp_ns=bad.header.time_ns)
            await byz.pv.sign_proposal("test-net", prop)
            for node in net.nodes:
                if node is byz:
                    continue
                node.consensus.feed_proposal(prop, "byz")
                for i in range(parts.total):
                    node.consensus.feed_block_part(1, 0, parts.get_part(i),
                                                   "byz")

            # the chain must still commit height 2+ (in round >= 1), and
            # the garbage block must never appear
            await net.wait_for_height(2, timeout=60,
                                      nodes=[n for n in net.nodes
                                             if n is not byz])
            for node in net.nodes:
                if node is byz:
                    continue
                blk1 = node.block_store.load_block(1)
                assert blk1.hash() != bad.hash(), "garbage block committed!"
                assert b"evil" not in [bytes(t) for t in blk1.data.txs]
        finally:
            await net.stop()
        return True

    assert run(main())


def test_mixed_key_validator_set_commits():
    """A validator set mixing ed25519 and secp256k1 keys commits blocks:
    the TpuBatchVerifier's mixed routing (ed25519 lanes batched, secp on
    the host route) runs inside live consensus — the reference refuses to
    batch mixed sets (types/validation.go:13-19); here it just works."""
    from cometbft_tpu.crypto.secp256k1 import Secp256k1PrivKey
    from cometbft_tpu.testing import make_inproc_network
    from cometbft_tpu.types.priv_validator import MockPV

    def pv_factory(i):
        if i == 0:
            return MockPV(Secp256k1PrivKey.from_secret(b"mixsecp%d" % i))
        return MockPV.from_secret(b"mixed%d" % i)

    async def main():
        net = await make_inproc_network(4, chain_id="mixed-net",
                                        pv_factory=pv_factory)
        try:
            await net.start()
            await net.wait_for_height(3, timeout=60)
            node = net.nodes[0]
            # the secp validator's signature is in committed commits
            commit = node.block_store.load_block(3).last_commit
            types = {node.state_store.load_validators(2)
                     .get_by_index(i).pub_key.type()
                     for i, cs in enumerate(commit.signatures)
                     if cs.is_commit()}
            assert "secp256k1" in types and "ed25519" in types, types
        finally:
            await net.stop()
        return True

    asyncio.run(main())


def test_create_empty_blocks_disabled_waits_for_txs():
    """config create_empty_blocks=false (state.go:1110 waitForTxs): after
    the proof block, the chain parks until a tx arrives, commits a block
    containing it (plus the follow-up proof block for the new app hash),
    then parks again."""
    from cometbft_tpu.config import test_consensus_config

    async def main():
        cfg = test_consensus_config()
        cfg.create_empty_blocks = False
        net = await make_inproc_network(4, config=cfg)
        try:
            await net.start()
            await net.wait_for_height(1, timeout=10)
            h0 = max(n.block_store.height() for n in net.nodes)
            await asyncio.sleep(1.0)           # many rounds worth of time
            h1 = max(n.block_store.height() for n in net.nodes)
            # parked: at most one extra proof block, no stream of empties
            assert h1 - h0 <= 1, f"empty blocks kept flowing: {h0}->{h1}"

            # no mempool gossip in the tier-1 harness: feed every node,
            # as the mempool reactor would
            for n in net.nodes:
                await n.mempool.check_tx(b"wake=up")
            await net.wait_for_height(h1 + 1, timeout=10)
            # the tx is in a committed block
            found = None
            for h in range(h0, net.nodes[0].block_store.height() + 1):
                blk = net.nodes[0].block_store.load_block(h)
                if blk is not None and b"wake=up" in blk.data.txs:
                    found = h
            assert found, "tx never committed"

            await asyncio.sleep(0.5)
            h2 = max(n.block_store.height() for n in net.nodes)
            await asyncio.sleep(1.0)
            h3 = max(n.block_store.height() for n in net.nodes)
            assert h3 - h2 <= 1, f"chain did not re-park: {h2}->{h3}"
        finally:
            await net.stop()
        return True

    assert run(main())


def test_skip_timeout_commit_fast_heights():
    """skip_timeout_commit (state.go:2325,2489): with every precommit in
    hand the next height starts immediately, so block production is not
    bound by timeout_commit."""
    from cometbft_tpu.config import test_consensus_config

    async def main():
        cfg = test_consensus_config()
        cfg.timeout_commit = 2_000_000_000        # 2s: would dominate
        cfg.skip_timeout_commit = True
        net = await make_inproc_network(4, config=cfg)
        try:
            await net.start()
            t0 = asyncio.get_event_loop().time()
            await net.wait_for_height(5, timeout=30)
            elapsed = asyncio.get_event_loop().time() - t0
            # the genesis start_time wait (~2s) is un-skippable by design
            # (updateToState); heights 2-5 commit within ~0.1s each when
            # skipping, so one lost skip (+2s) must trip the bound
            assert elapsed < 4.0, f"timeout_commit not skipped: {elapsed}"
        finally:
            await net.stop()
        return True

    assert run(main())
