"""Remote signer e2e across OS processes: a validator's key lives in a
separate `signer` daemon that dials the node's priv_validator_laddr
(reference topology: ``privval/signer_listener_endpoint.go`` on the node,
``signer_dialer_endpoint.go`` + ``signer_server.go`` in the signer)."""

import asyncio
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.timeout(150)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 29260
SIGNER_PORT = 29280


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "cometbft_tpu", *args],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)


def _spawn(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", *args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO)


def test_remote_signer_validator_commits(tmp_path):
    """2-of-2 validator net where node1 signs through the remote signer
    daemon: blocks can only commit if the remote signing path works."""
    from cometbft_tpu.config import Config

    base = str(tmp_path / "net")
    res = _run_cli("testnet", "--v", "2", "--output-dir", base,
                   "--base-port", str(BASE_PORT), "--chain-id", "signer-net")
    assert res.returncode == 0, res.stderr

    for i in range(2):
        cfgp = f"{base}/node{i}/config/config.toml"
        cfg = Config.load(cfgp)
        cfg.consensus.timeout_propose = 300_000_000
        cfg.consensus.timeout_prevote = 150_000_000
        cfg.consensus.timeout_precommit = 150_000_000
        cfg.consensus.timeout_commit = 100_000_000
        cfg.base.signature_backend = "cpu"
        if i == 1:
            cfg.base.priv_validator_laddr = \
                f"tcp://127.0.0.1:{SIGNER_PORT}"
        cfg.save(cfgp)

    procs = []
    try:
        procs.append(_spawn("--home", f"{base}/node0", "start"))
        procs.append(_spawn("--home", f"{base}/node1", "start"))
        # the signer daemon holds node1's key and dials the node
        procs.append(_spawn("--home", f"{base}/node1", "signer",
                            "--address", f"tcp://127.0.0.1:{SIGNER_PORT}"))

        async def scenario():
            from cometbft_tpu.rpc import HTTPClient, RPCError

            clis = [HTTPClient("127.0.0.1", BASE_PORT + 2 * i + 1)
                    for i in range(2)]

            async def call(cli, method, timeout=90.0, **kw):
                deadline = time.monotonic() + timeout
                while True:
                    try:
                        return await cli.call(method, **kw)
                    except (OSError, RPCError, asyncio.TimeoutError):
                        if time.monotonic() > deadline:
                            raise
                        await asyncio.sleep(0.3)

            res = await call(clis[0], "broadcast_tx_commit",
                             tx=b"sgk=sgv".hex())
            assert res["tx_result"]["code"] == 0
            h = res["height"]
            for cli in clis:
                while True:
                    st = await call(cli, "status")
                    if st["sync_info"]["latest_block_height"] >= h:
                        break
                    await asyncio.sleep(0.3)
            b0 = await call(clis[0], "block", height=h)
            b1 = await call(clis[1], "block", height=h)
            assert b0["block_id"]["hash"] == b1["block_id"]["hash"]

        asyncio.run(scenario())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
