"""Manifest-driven e2e runner (reference: ``test/e2e/runner`` +
``networks/ci.toml``): roles, late joiners, perturbation schedule, load,
and end-state invariants, all through the public Runner API."""

import asyncio

import pytest

from cometbft_tpu.e2e import (ManifestError, Runner, manifest_from_dict)
from cometbft_tpu.e2e.runner import RunnerError

pytestmark = pytest.mark.timeout(240)


def test_manifest_validation():
    with pytest.raises(ManifestError):
        manifest_from_dict({})                     # no nodes
    with pytest.raises(ManifestError):
        manifest_from_dict({"node": {"a": {"mode": "blimp"}}})
    with pytest.raises(ManifestError):
        manifest_from_dict({"node": {"a": {"perturb": ["explode:3"]}}})
    m = manifest_from_dict({"node": {"a": {}, "b": {"mode": "full"}}})
    assert m.validator_powers() == {"a": 100}      # manifest.go:28 default


@pytest.mark.slow   # live multi-node run
def test_e2e_validator_updates(tmp_path):
    """Manifest validator_update (manifest.go:34): a full node is voted
    in as a validator mid-run and another validator's power changes; the
    live validator set must match the folded updates."""
    m = manifest_from_dict({
        "chain_id": "e2e-valup",
        "final_height": 10,
        "validators": {"v1": 10, "v2": 10, "v3": 10},
        "node": {
            "v1": {}, "v2": {}, "v3": {},
            "joiner": {"mode": "full"},
        },
        "validator_update": {
            "3": {"joiner": 15},        # full node becomes a validator
            "5": {"v3": 25},            # power change
        },
        "load": {"rate": 0.0, "duration": 0.0},
    })
    runner = Runner(m, str(tmp_path / "net"), base_port=30160,
                    log=lambda *a: None)
    runner.setup()
    try:
        report = asyncio.run(runner.run(deadline_s=180.0))
    finally:
        runner.stop()
    assert report["validators"] == {"v1": 10, "v2": 10, "v3": 25,
                                    "joiner": 15}
    assert all(h >= 10 for h in report["heights"].values())


@pytest.mark.slow   # live multi-node run
def test_e2e_seed_discovery(tmp_path):
    """Seed topology: validators have NO persistent peers — they learn
    the network through the seed via PEX (manifest.go seed semantics),
    then commit blocks."""
    m = manifest_from_dict({
        "chain_id": "e2e-seed",
        "final_height": 4,
        "node": {
            "v1": {}, "v2": {}, "v3": {},
            "seed1": {"mode": "seed"},
        },
        "load": {"rate": 0.0, "duration": 0.0},
    })
    runner = Runner(m, str(tmp_path / "net"), base_port=29960,
                    log=lambda *a: None)
    runner.setup()
    # the topology really is seed-only: validators have no wired peers
    from cometbft_tpu.config import Config

    cfg = Config.load(str(tmp_path / "net" / "v1" / "config" /
                          "config.toml"))
    assert cfg.p2p.persistent_peers == ""
    assert "29966" in cfg.p2p.seeds or cfg.p2p.seeds  # seed1's port
    try:
        report = asyncio.run(runner.run(deadline_s=120.0))
    finally:
        runner.stop()
    assert all(h >= 4 for h in report["heights"].values())


@pytest.mark.slow   # live multi-node run
def test_e2e_manifest_network(tmp_path):
    m = manifest_from_dict({
        "chain_id": "e2e-pytest",
        "final_height": 8,
        "validators": {"v1": 10, "v2": 10, "v3": 10, "v4": 10},
        "node": {
            "v1": {},
            "v2": {"perturb": ["kill:4", "restart:6"]},
            "v3": {},
            "v4": {},
            "full1": {"mode": "full", "start_at": 3},
            "light1": {"mode": "light", "start_at": 5},
        },
        "load": {"rate": 10.0, "duration": 10.0},
    })
    runner = Runner(m, str(tmp_path / "net"), base_port=29860,
                    log=lambda *a: None)
    runner.setup()
    try:
        report = asyncio.run(runner.run(deadline_s=180.0))
    finally:
        runner.stop()
    assert report["final_height"] == 8
    assert set(report["heights"]) == {"v1", "v2", "v3", "v4", "full1"}
    assert all(h >= 8 for h in report["heights"].values())
    assert report["agreement_hash"]
    assert report["light_verified"] == {"light1": True}


def test_generator_determinism_and_round_trip():
    """The same seed always produces byte-identical TOML, and parsing it
    back yields the same manifest (generator.go's reproducibility
    contract: a CI failure reproduces from the seed alone)."""
    from cometbft_tpu.e2e.generator import generate_manifest
    from cometbft_tpu.e2e.manifest import loads_toml, manifest_to_toml

    for seed in range(1, 30):
        m = generate_manifest(seed, compact=True)
        s = manifest_to_toml(m)
        assert manifest_to_toml(generate_manifest(seed, compact=True)) == s
        m2 = manifest_from_dict(loads_toml(s))
        assert manifest_to_toml(m2) == s
    # the sweep actually varies the axes across seeds
    axes = set()
    for seed in range(1, 30):
        m = generate_manifest(seed, compact=True)
        for n in m.nodes.values():
            axes.add(("db", n.database))
            axes.add(("abci", n.abci_protocol))
            axes.add(("key", n.key_type))
    assert {("db", "logdb"), ("db", "native"), ("db", "memdb"),
            ("abci", "builtin"), ("abci", "socket"),
            ("key", "secp256k1")} <= axes


@pytest.mark.slow   # live multi-node run
@pytest.mark.parametrize("seed", [2, 4])
def test_e2e_generated_seed_runs_green(tmp_path, seed):
    """Two generated seeds run end-to-end: seed 2 sweeps memdb + socket
    ABCI (external app processes), seed 4 adds native db + a kill/restart
    perturbation + a late-start light client."""
    from cometbft_tpu.e2e.generator import generate_manifest

    m = generate_manifest(seed, compact=True)
    m.load.duration = 5.0              # keep CI wall-clock in check
    runner = Runner(m, str(tmp_path / "net"), base_port=30480 + seed * 40,
                    log=lambda *a: None)
    runner.setup()
    try:
        report = asyncio.run(runner.run(deadline_s=200.0))
    finally:
        runner.stop()
    assert all(h >= m.final_height for h in report["heights"].values())


def test_runner_detects_port_squatter():
    """A status response from a node OTHER than the one the runner
    generated must raise, naming the foreign id: stale nodes from a
    killed previous run squat the same ports, serve the same chain id
    and monikers, and poisoned runs with another chain's blocks (the
    'app hash mismatch after replay' flake this guard closes)."""
    m = manifest_from_dict({
        "chain_id": "squat-net",
        "validators": {"v1": 10},
        "node": {"v1": {}},
    })
    r = Runner(m, "/tmp/e2e-squat-test-unused", base_port=29990,
               log=lambda *a: None)
    r.node_ids = {"v1": "aabbccddeeff00112233"}
    ok_st = {"node_info": {"id": "aabbccddeeff00112233", "moniker": "v1"}}
    r._check_identity("v1", ok_st)          # matching id: fine
    r._check_identity("v1", {})             # no node_info: tolerated
    r._check_identity("v2", ok_st)          # unknown name: tolerated
    foreign = {"node_info": {"id": "ffffffffffffffffffff", "moniker": "v1"}}
    with pytest.raises(RunnerError, match="FOREIGN node"):
        r._check_identity("v1", foreign)
