"""Mempool internals (r16): shard routing + merged-reap FIFO, the
CheckTx coalescer's per-item demux, batched recheck drop semantics,
gossip bookkeeping pruning, byte-cap admission, and the
content-addressed announce/fetch protocol (round trip, timeout
re-request, old-protocol interop)."""

import asyncio
import time

import msgpack
import pytest

from cometbft_tpu.abci.types import CheckTxResponse
from cometbft_tpu.mempool.clist_mempool import (CListMempool,
                                                MempoolFullError,
                                                TxRejectedError)
from cometbft_tpu.mempool.mempool import TxKey
from cometbft_tpu.mempool.reactor import MEMPOOL_CHANNEL, MempoolReactor

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class ScriptedApp:
    """CheckTx verdicts by tx prefix: b"bad..." rejects, b"drop..." is
    accepted on admission but rejected on RECHECK (post-block state
    change), everything else accepted.  Records call concurrency."""

    def __init__(self):
        self.calls = 0
        self.recheck_calls = 0
        self.inflight = 0
        self.max_inflight = 0

    async def check_tx(self, tx: bytes, recheck: bool = False):
        self.calls += 1
        self.inflight += 1
        self.max_inflight = max(self.max_inflight, self.inflight)
        await asyncio.sleep(0)
        self.inflight -= 1
        if recheck:
            self.recheck_calls += 1
            if tx.startswith(b"drop"):
                return CheckTxResponse(code=1, log="stale")
        if tx.startswith(b"bad"):
            return CheckTxResponse(code=7, log="scripted reject")
        return CheckTxResponse(code=0, gas_wanted=1)


# ------------------------------------------------------------- sharding


def test_shard_routing_spreads_and_accounts():
    async def main():
        mp = CListMempool(ScriptedApp(), shards=4, coalesce_ms=0)
        txs = [b"tx-%d" % i for i in range(64)]
        await asyncio.gather(*(mp.check_tx(t) for t in txs))
        occupied = [n for n in mp.stats()["shards"] if n]
        assert len(occupied) > 1, "64 txs all landed in one shard"
        assert sum(mp.stats()["shards"]) == 64 == mp.size()
        # shard routing is by tx-hash prefix, consistent with get_tx
        for t in txs:
            assert mp.get_tx(TxKey(t)) == t
        return True

    assert run(main())


def test_merged_reap_preserves_arrival_fifo_across_shards():
    async def main():
        mp = CListMempool(ScriptedApp(), shards=8, coalesce_ms=0)
        txs = [b"fifo-%03d" % i for i in range(100)]
        for t in txs:                       # sequential: strict arrival
            await mp.check_tx(t)
        assert mp.reap_max_txs(1000) == txs
        assert mp.contents() == txs
        assert mp.reap_max_bytes_max_gas(-1, -1) == txs
        assert [k for k, _ in mp.items()] == [TxKey(t) for t in txs]
        return True

    assert run(main())


def test_merged_reap_fifo_under_concurrent_admission():
    """Concurrent admissions across shards still reap in arrival-seq
    order (seq is assigned before the app round trip)."""

    async def main():
        mp = CListMempool(ScriptedApp(), shards=4, coalesce_ms=0.5,
                          coalesce_max=16)
        txs = [b"conc-%03d" % i for i in range(60)]
        await asyncio.gather(*(mp.check_tx(t) for t in txs))
        assert mp.reap_max_txs(1000) == txs
        return True

    assert run(main())


# ------------------------------------------------------------ coalescer


def test_coalesced_checktx_demuxes_mixed_verdicts():
    """One coalesced burst carries accepts AND rejects; every caller
    gets ITS verdict (per-item demux, no batch poisoning)."""

    async def main():
        app = ScriptedApp()
        mp = CListMempool(app, shards=1, coalesce_ms=5.0,
                          coalesce_max=64)
        txs = [b"ok-%d" % i for i in range(6)] + \
              [b"bad-%d" % i for i in range(6)]
        results = await asyncio.gather(
            *(mp.check_tx(t) for t in txs), return_exceptions=True)
        oks = [r for r in results if r is None]
        rejects = [r for r in results if isinstance(r, TxRejectedError)]
        assert len(oks) == 6 and len(rejects) == 6
        assert all(r.code == 7 for r in rejects)
        assert mp.size() == 6
        assert app.max_inflight >= 12, \
            "burst did not pipeline concurrently"
        return True

    assert run(main())


def test_coalescer_size_flush_snaps_to_lane_bucket():
    from cometbft_tpu.crypto.plan import snap_lane_cap

    mp = CListMempool(ScriptedApp(), shards=1, coalesce_max=100)
    assert mp._shards[0].checker.max_lanes == snap_lane_cap(100)


# ------------------------------------------------------ batched recheck


def test_batched_recheck_drops_stale_survivors():
    async def main():
        app = ScriptedApp()
        mp = CListMempool(app, shards=4, coalesce_ms=0)
        keep = [b"keep-%d" % i for i in range(10)]
        drop = [b"drop-%d" % i for i in range(10)]
        committed = [b"block-tx"]
        for t in keep + drop + committed:
            await mp.check_tx(t)
        assert mp.size() == 21
        removed_seen = []
        mp.on_txs_removed = removed_seen.extend
        async with mp.lock():
            await mp.update(2, committed, [])
        assert mp.size() == 10
        assert sorted(mp.contents()) == sorted(keep)
        # committed + recheck-dropped keys all reported for pruning
        assert sorted(removed_seen) == sorted(
            TxKey(t) for t in committed + drop)
        # bytes accounting survived the drops
        assert mp.size_bytes() == sum(len(t) for t in keep)
        assert mp.height == 2
        return True

    assert run(main())


def test_recheck_disabled_keeps_survivors():
    async def main():
        mp = CListMempool(ScriptedApp(), shards=2, coalesce_ms=0,
                          recheck=False)
        for t in (b"drop-a", b"drop-b"):
            await mp.check_tx(t)
        async with mp.lock():
            await mp.update(2, [], [])
        assert mp.size() == 2      # recheck off: nothing re-evaluated
        return True

    assert run(main())


# ------------------------------------------------------------- capacity


def test_byte_cap_admission():
    async def main():
        mp = CListMempool(ScriptedApp(), shards=2, coalesce_ms=0,
                          max_txs=1000, max_txs_bytes=100)
        await mp.check_tx(b"x" * 60)
        assert mp.size_bytes() == 60
        with pytest.raises(MempoolFullError):
            await mp.check_tx(b"y" * 60)      # 120 > 100: byte-capped
        await mp.check_tx(b"z" * 30)          # 90 <= 100: fits
        assert mp.size() == 2 and mp.size_bytes() == 90
        # removal releases byte budget
        async with mp.lock():
            await mp.update(2, [b"x" * 60], [])
        assert mp.size_bytes() == 30
        await mp.check_tx(b"w" * 60)
        assert mp.size_bytes() == 90
        return True

    assert run(main())


def test_size_bytes_is_running_total():
    async def main():
        mp = CListMempool(ScriptedApp(), shards=4, coalesce_ms=0)
        total = 0
        for i in range(20):
            tx = b"b" * (i + 1)
            await mp.check_tx(tx)
            total += len(tx)
        assert mp.size_bytes() == total
        await mp.flush()
        assert mp.size_bytes() == 0 == mp.size()
        return True

    assert run(main())


# ------------------------------------------------------ reactor helpers


class FakePeer:
    def __init__(self, pid="peer-a", accept=True):
        self.id = pid
        self.accept = accept
        self.frames: list[dict] = []

    def send(self, channel_id, msg):
        if not self.accept:
            return False
        self.frames.append(msgpack.unpackb(msg, raw=False))
        return True

    def sent_kinds(self):
        return [next(iter(set(f) & {"ann", "req", "txs", "hi"}))
                for f in self.frames]


def mk_pool_reactor(app=None, mode="announce", **kw):
    mp = CListMempool(app or ScriptedApp(), coalesce_ms=0, **kw)
    return mp, MempoolReactor(mp, gossip_sleep=0.01, gossip_mode=mode,
                              fetch_timeout_s=0.2)


# ------------------------------------------------------ senders pruning


def test_senders_pruned_on_update_and_bounded():
    async def main():
        mp, reactor = mk_pool_reactor()
        peer = FakePeer("p1")
        tx = b"gossiped-tx"
        reactor.receive(MEMPOOL_CHANNEL, peer,
                        msgpack.packb({"txs": [tx]}, use_bin_type=True))
        await asyncio.sleep(0.05)
        key = TxKey(tx)
        assert peer.id in reactor._senders.get(key, ())
        async with mp.lock():
            await mp.update(2, [tx], [])   # committed: leaves the pool
        assert key not in reactor._senders, \
            "_senders entry leaked past removal"
        # bound: the map can never exceed its cap even for never-admitted
        # junk (rejected txs used to pin a set forever)
        reactor._map_bound = 64
        for i in range(200):
            reactor._bounded_add(reactor._senders, b"h%03d" % i, "px")
        assert len(reactor._senders) <= 64
        return True

    assert run(main())


# ------------------------------------------- full-pool shedding counter


def test_full_pool_announce_skips_fetch():
    async def main():
        mp, reactor = mk_pool_reactor(max_txs=1)
        await mp.check_tx(b"occupies-the-pool")
        peer = FakePeer("flood")
        before = reactor.tallies["full_skips"]
        reactor.receive(MEMPOOL_CHANNEL, peer, msgpack.packb(
            {"hi": 1, "ann": [b"\x01" * 32, b"\x02" * 32]},
            use_bin_type=True))
        assert reactor.tallies["full_skips"] == before + 2
        assert reactor.tallies["fetch_requests"] == 0
        assert not any("req" in f for f in peer.frames), \
            "full pool bought the flood a fetch round trip"
        return True

    assert run(main())


# ------------------------------------------------------- announce/fetch


def test_announce_fetch_round_trip_between_reactors():
    """Two real reactors linked by hand-delivered frames: A announces,
    B requests, A serves the body, B admits it via CheckTx."""

    async def main():
        mp_a, ra = mk_pool_reactor()
        mp_b, rb = mk_pool_reactor()
        tx = b"round-trip-tx"
        await mp_a.check_tx(tx)

        a_view_of_b = FakePeer("node-b")    # what A sends toward B
        b_view_of_a = FakePeer("node-a")    # what B sends toward A
        # capability exchange (add_peer hello)
        ra.receive(MEMPOOL_CHANNEL, a_view_of_b,
                   msgpack.packb({"hi": 1}, use_bin_type=True))
        rb.receive(MEMPOOL_CHANNEL, b_view_of_a,
                   msgpack.packb({"hi": 1}, use_bin_type=True))
        assert "node-b" in ra._capable and "node-a" in rb._capable

        # A's broadcast routine would announce; hand-build the frame
        keys = [k for k, _ in mp_a.items()]
        rb.receive(MEMPOOL_CHANNEL, b_view_of_a,
                   msgpack.packb({"ann": keys}, use_bin_type=True))
        # B requested the missing body from A
        req_frames = [f for f in b_view_of_a.frames if "req" in f]
        assert req_frames and req_frames[0]["req"] == [TxKey(tx)]
        assert rb.tallies["fetch_requests"] == 1
        # serve the request through A's reactor
        ra.receive(MEMPOOL_CHANNEL, a_view_of_b,
                   msgpack.packb(req_frames[0], use_bin_type=True))
        body_frames = [f for f in a_view_of_b.frames if "txs" in f]
        assert body_frames and body_frames[0]["txs"] == [tx]
        # deliver the body to B: fulfills the fetch, admits the tx
        rb.receive(MEMPOOL_CHANNEL, b_view_of_a,
                   msgpack.packb(body_frames[0], use_bin_type=True))
        await asyncio.sleep(0.05)
        assert rb.tallies["fetch_fulfilled"] == 1
        assert mp_b.get_tx(TxKey(tx)) == tx
        # duplicate announce is pure dedup now
        rb.receive(MEMPOOL_CHANNEL, b_view_of_a,
                   msgpack.packb({"ann": keys}, use_bin_type=True))
        assert rb.tallies["ann_dedup"] >= 1
        return True

    assert run(main())


def test_fetch_timeout_rerequests_from_another_announcer():
    async def main():
        mp, reactor = mk_pool_reactor()
        dead = FakePeer("announcer-dead")
        alive = FakePeer("announcer-alive")

        class SwitchStub:
            peers = {"announcer-alive": alive, "announcer-dead": dead}

        reactor.set_switch(SwitchStub())
        # both peers "connected" as far as the reactor knows
        reactor._peer_tasks["announcer-dead"] = None
        reactor._peer_tasks["announcer-alive"] = None
        reactor._sweep_task = asyncio.ensure_future(
            reactor._sweep_requests())
        h = TxKey(b"never-served-tx")
        # dead announces first -> initial request goes to dead
        reactor.receive(MEMPOOL_CHANNEL, dead, msgpack.packb(
            {"ann": [h]}, use_bin_type=True))
        reactor.receive(MEMPOOL_CHANNEL, alive, msgpack.packb(
            {"ann": [h]}, use_bin_type=True))
        assert any("req" in f for f in dead.frames)
        assert not any("req" in f for f in alive.frames)
        # dead never serves: the sweeper re-requests from alive
        deadline = time.monotonic() + 5
        while not any("req" in f for f in alive.frames):
            assert time.monotonic() < deadline, \
                "timeout never re-requested from the other announcer"
            await asyncio.sleep(0.02)
        assert reactor.tallies["fetch_timeouts"] >= 1
        assert reactor.tallies["fetch_requests"] >= 2
        reactor._sweep_task.cancel()
        return True

    assert run(main())


def test_old_protocol_interop_gets_full_bodies():
    """A peer that never says hi (pre-r16 reactor) is gossiped full tx
    bodies, many per frame; an announce-capable peer gets hashes."""

    async def main():
        mp, reactor = mk_pool_reactor()
        for i in range(5):
            await mp.check_tx(b"interop-%d" % i)
        old_peer = FakePeer("old-proto")
        new_peer = FakePeer("new-proto")
        reactor.receive(MEMPOOL_CHANNEL, new_peer,
                        msgpack.packb({"hi": 1}, use_bin_type=True))
        reactor.add_peer(old_peer)
        reactor.add_peer(new_peer)
        try:
            deadline = time.monotonic() + 5
            while not (any("txs" in f for f in old_peer.frames)
                       and any("ann" in f for f in new_peer.frames)):
                assert time.monotonic() < deadline, (
                    old_peer.frames, new_peer.frames)
                await asyncio.sleep(0.02)
            # old peer: one frame carries ALL pending bodies (batched)
            body_frame = next(f for f in old_peer.frames if "txs" in f)
            assert len(body_frame["txs"]) == 5
            # old peer never receives announces
            assert not any("ann" in f for f in old_peer.frames)
            # new peer: hashes only, no unsolicited bodies
            ann_frame = next(f for f in new_peer.frames if "ann" in f)
            assert sorted(ann_frame["ann"]) == sorted(
                k for k, _ in mp.items())
            assert not any("txs" in f for f in new_peer.frames)
        finally:
            await reactor.stop()
        return True

    assert run(main())


def test_gossip_mode_full_never_announces():
    async def main():
        mp, reactor = mk_pool_reactor(mode="full")
        await mp.check_tx(b"full-mode-tx")
        peer = FakePeer("p-full")
        # even a capable peer gets bodies when WE are in full mode
        reactor.receive(MEMPOOL_CHANNEL, peer,
                        msgpack.packb({"hi": 1}, use_bin_type=True))
        reactor.add_peer(peer)
        try:
            deadline = time.monotonic() + 5
            while not any("txs" in f for f in peer.frames):
                assert time.monotonic() < deadline
                await asyncio.sleep(0.02)
            assert not any("hi" in f for f in peer.frames)
            assert not any("ann" in f for f in peer.frames)
        finally:
            await reactor.stop()
        return True

    assert run(main())


# ------------------------------------------------- scenario-lab flood


def test_txflood_scenario_sheds_and_bans_replay_identical():
    """The tx-flood adversary through the scenario lab: the flooder is
    scored and banned, victims shed (full-pool skips) instead of
    collapsing, the net stays fork-free, and the whole verdict replays
    bit-identically across two seeded runs."""
    import json

    from cometbft_tpu.sim.node import SimTuning
    from cometbft_tpu.sim.scenario import Scenario, run_scenario

    scn = Scenario(
        name="t-txflood-shed", seed=61, n_nodes=5, out_links=2,
        target_height=8, max_virtual_s=900.0,
        byzantine={4: "flooder"},
        tuning=SimTuning(ban_ttl_s=2.0, mempool_size=8,
                         mempool_gossip_sleep=0.1))
    v1 = run_scenario(scn)
    v2 = run_scenario(scn)
    assert json.dumps(v1, sort_keys=True) == \
        json.dumps(v2, sort_keys=True), "verdict not replay-identical"
    assert v1["reached_target"] and v1["fork_free"]
    assert v1["misbehavior_events"].get("invalid_tx", 0) > 0
    assert v1["bans"]["banned_nodes"] == ["sim004"]
    mp = v1["mempool"]
    assert mp["full_skips"] > 0, "tiny pool never shed the flood"
    assert mp["fetch_requests"] > 0 and mp["fetch_fulfilled"] > 0, \
        "announce/fetch path never exercised"
