"""Storage (KV/block/state stores) and ABCI (clients, server, kvstore app)."""

import asyncio

import pytest

from cometbft_tpu.abci import (CODE_TYPE_OK, FinalizeBlockRequest,
                               InitChainRequest, PrepareProposalRequest,
                               ProcessProposalRequest,
                               PROCESS_PROPOSAL_ACCEPT,
                               PROCESS_PROPOSAL_REJECT, ValidatorUpdate,
                               OFFER_SNAPSHOT_ACCEPT, APPLY_CHUNK_ACCEPT)
from cometbft_tpu.abci.client import LocalClient, SocketClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.abci.server import ABCIServer
from cometbft_tpu.proxy import AppConns, local_client_creator
from cometbft_tpu.storage import (BlockStore, LogDB, MemDB, State, StateStore)
from cometbft_tpu.types import (BlockID, Commit, CommitSig, PartSetHeader,
                                Validator, ValidatorSet)
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.header import Block, Data, Header
from cometbft_tpu.types.part_set import PartSet
from cometbft_tpu.types.priv_validator import MockPV
from cometbft_tpu.types import codec


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


# ---------------------------------------------------------------------- db

def test_logdb_crash_recovery(tmp_path):
    path = str(tmp_path / "kv.log")
    db = LogDB(path)
    db.set(b"a", b"1")
    db.set(b"b", b"2")
    db.set(b"a", b"3")
    db.delete(b"b")
    db.close()

    db2 = LogDB(path)
    assert db2.get(b"a") == b"3" and db2.get(b"b") is None
    # torn tail: append garbage, must be truncated on reopen
    db2.close()
    with open(path, "ab") as f:
        f.write(b"\x01\x02\x03garbage-partial-record")
    db3 = LogDB(path)
    assert db3.get(b"a") == b"3"
    db3.set(b"c", b"4")
    db3.close()
    db4 = LogDB(path)
    assert db4.get(b"c") == b"4"
    assert list(db4.iterate(b"a", b"c")) == [(b"a", b"3")]
    db4.close()


def test_logdb_compaction(tmp_path):
    path = str(tmp_path / "kv.log")
    db = LogDB(path)
    for i in range(300):
        db.set(b"key", b"v" * 4096)       # rewrite same key: log grows
    db.set(b"other", b"x")
    import os
    assert os.path.getsize(path) < 1 << 21   # compaction kept it bounded
    db.close()
    db2 = LogDB(path)
    assert db2.get(b"key") == b"v" * 4096 and db2.get(b"other") == b"x"
    db2.close()


# -------------------------------------------------------------- blockstore

def make_block_at(height, vals, pvs, prev_bid):
    h = Header(chain_id="s-chain", height=height, time_ns=height * 10**9,
               last_block_id=prev_bid, validators_hash=vals.hash(),
               next_validators_hash=vals.hash(),
               proposer_address=vals.get_proposer().address)
    commit = None
    if height > 1:
        commit = Commit(height - 1, 0, prev_bid,
                        [CommitSig(2, v.address, 1, b"s" * 64)
                         for v in vals.validators])
    b = Block(header=h, data=Data(txs=[b"tx%d" % height]), last_commit=commit)
    b.fill_hashes()
    return b


def test_blockstore_roundtrip(tmp_path):
    pvs = [MockPV.from_secret(b"b%d" % i) for i in range(3)]
    vals = ValidatorSet([Validator(p.get_pub_key(), 5) for p in pvs])
    store = BlockStore(MemDB())
    prev = BlockID()
    blocks = []
    for height in range(1, 6):
        b = make_block_at(height, vals, pvs, prev)
        parts = PartSet.from_data(codec.pack(b))
        seen = Commit(height, 0, BlockID(b.hash(), parts.header()),
                      [CommitSig(2, v.address, 1, b"s" * 64)
                       for v in vals.validators])
        store.save_block(b, parts, seen)
        prev = BlockID(b.hash(), parts.header())
        blocks.append(b)

    assert store.height() == 5 and store.base() == 1
    got = store.load_block(3)
    assert got.hash() == blocks[2].hash()
    meta = store.load_block_meta(3)
    assert meta.block_id.hash == blocks[2].hash()
    c2 = store.load_block_commit(2)           # from block 3's last_commit
    assert c2.height == 2
    seen = store.load_seen_commit()
    assert seen.height == 5
    with pytest.raises(ValueError):
        store.save_block(blocks[2], PartSet.from_data(b"x"), seen)  # gap
    assert store.prune_blocks(3) == 2
    assert store.base() == 3 and store.load_block(2) is None
    assert store.load_block(3) is not None


# -------------------------------------------------------------- statestore

def test_statestore_roundtrip():
    pvs = [MockPV.from_secret(b"s%d" % i) for i in range(3)]
    doc = GenesisDoc(chain_id="ss-chain",
                     validators=[GenesisValidator(p.get_pub_key(), 7)
                                 for p in pvs])
    st = State.from_genesis(doc)
    store = StateStore(MemDB())
    store.save(st)
    st2 = store.load()
    assert st2.chain_id == "ss-chain"
    assert st2.validators.hash() == st.validators.hash()
    assert st2.next_validators.hash() == st.next_validators.hash()
    assert st2.consensus_params.hash() == st.consensus_params.hash()
    # proposer survives the round trip (consensus-critical)
    assert st2.validators.get_proposer().address == \
        st.validators.get_proposer().address
    vals1 = store.load_validators(1)
    assert vals1 is not None and vals1.hash() == st.validators.hash()


# -------------------------------------------------------------------- abci

def test_kvstore_local_client():
    async def main():
        app = KVStoreApplication()
        client = LocalClient(app)
        await client.init_chain(InitChainRequest(
            chain_id="kv", initial_height=1, time_ns=0,
            validators=[ValidatorUpdate("ed25519", b"\x01" * 32, 10)]))
        info = await client.info()
        assert info.data == "kvstore"

        resp = await client.check_tx(b"name=satoshi")
        assert resp.is_ok
        assert not (await client.check_tx(b"garbage")).is_ok

        pp = await client.prepare_proposal(PrepareProposalRequest(
            max_tx_bytes=1 << 20, txs=[b"a=1", b"b=2"], height=1, time_ns=0))
        assert pp.txs == [b"a=1", b"b=2"]
        assert (await client.process_proposal(ProcessProposalRequest(
            txs=pp.txs, height=1, time_ns=0))) == PROCESS_PROPOSAL_ACCEPT
        assert (await client.process_proposal(ProcessProposalRequest(
            txs=[b"bad"], height=1, time_ns=0))) == PROCESS_PROPOSAL_REJECT

        fin = await client.finalize_block(FinalizeBlockRequest(
            txs=pp.txs, height=1, time_ns=0))
        assert all(r.is_ok for r in fin.tx_results)
        assert fin.app_hash
        await client.commit()

        q = await client.query("/key", b"a", 0, False)
        assert q.value == b"1"

        ext = await client.extend_vote(1, 0, b"h" * 32)
        ok = await client.verify_vote_extension(1, 0, b"a" * 20, b"h" * 32,
                                                ext.vote_extension)
        assert ok.accepted
        bad = await client.verify_vote_extension(2, 0, b"a" * 20, b"h" * 32,
                                                 ext.vote_extension)
        assert not bad.accepted
        return True

    assert run(main())


def test_kvstore_snapshots_restore():
    async def main():
        app = KVStoreApplication()
        c = LocalClient(app)
        await c.finalize_block(FinalizeBlockRequest(
            txs=[b"x=%d" % i for i in range(50)], height=1, time_ns=0))
        await c.commit()
        snaps = await c.list_snapshots()
        assert snaps and snaps[0].height == 1

        app2 = KVStoreApplication()
        c2 = LocalClient(app2)
        assert (await c2.offer_snapshot(snaps[0], b"")) == \
            OFFER_SNAPSHOT_ACCEPT
        for i in range(snaps[0].chunks):
            chunk = await c.load_snapshot_chunk(1, 1, i)
            assert (await c2.apply_snapshot_chunk(i, chunk, "p")) == \
                APPLY_CHUNK_ACCEPT
        assert app2.state == app.state and app2.height == app.height
        assert app2.app_hash == app.app_hash
        return True

    assert run(main())


def test_socket_server_roundtrip():
    async def main():
        app = KVStoreApplication()
        server = ABCIServer(app, port=0)
        await server.start()
        client = await SocketClient.connect(port=server.port)
        assert (await client.echo("hello")) == "hello"
        fin = await client.finalize_block(FinalizeBlockRequest(
            txs=[b"k=v"], height=1, time_ns=0,
            misbehavior=[]))
        assert fin.tx_results[0].is_ok and fin.app_hash == app.app_hash
        # pipelining: concurrent calls resolve correctly
        import asyncio as aio
        results = await aio.gather(*[client.query("/k", b"k", 0, False)
                                     for _ in range(10)])
        assert all(r.value == b"v" for r in results)
        await client.close()
        await server.stop()
        return True

    assert run(main())


def test_app_conns():
    async def main():
        app = KVStoreApplication()
        conns = AppConns(local_client_creator(app))
        await conns.start()
        assert (await conns.query.info()).data == "kvstore"
        assert (await conns.mempool.check_tx(b"a=b")).is_ok
        await conns.stop()
        return True

    assert run(main())
