"""Limb-major (20,B) kernel twin: bit-identical accept/reject with the
production batch-major kernel over random batches and ZIP-215 edges."""

import numpy as np
import jax
import pytest

# first compile of each kernel pair dominates; share ONE lane shape (24)
# across the module so later tests hit the in-process jit cache
pytestmark = pytest.mark.timeout(900)

from cometbft_tpu.ops import ed25519, limb_major
from cometbft_tpu.testing import dense_signature_batch


def test_limb_major_matches_production_on_random_batch():
    args, _ = dense_signature_batch(24, msg_len=80, seed=99)
    want = np.asarray(jax.jit(ed25519.verify_padded)(*args))
    got = np.asarray(jax.jit(limb_major.verify_padded_lm)(*args))
    assert want.all()
    assert (got == want).all()


def test_limb_major_rejects_what_production_rejects():
    args, _ = dense_signature_batch(24, msg_len=80, seed=7)
    pub, rb, sb, blocks, active = args
    # tamper a scatter of lanes across every input surface
    sb = np.asarray(sb).copy(); sb[3, 0] ^= 1          # bad S
    rb = np.asarray(rb).copy(); rb[7, 31] ^= 0x40      # bad R encoding
    pub2 = np.asarray(pub).copy(); pub2[11, 5] ^= 2    # bad A
    blocks2 = np.asarray(blocks).copy()
    blocks2[13, 0, 0] ^= 1                             # bad message
    args2 = (pub2, rb, sb, blocks2, active)
    want = np.asarray(jax.jit(ed25519.verify_padded)(*args2))
    got = np.asarray(jax.jit(limb_major.verify_padded_lm)(*args2))
    assert not want[3] and not want[7] and not want[11] and not want[13]
    assert (got == want).all()


def test_limb_major_zip215_edge_corpus():
    """ZIP-215 edge encodings (non-canonical y, sign-bit families,
    S >= L) must get the same verdict from the limb-major twin as from
    the production kernel — which is itself pinned to the Python oracle
    in test_ed25519_kernel.py, so agreement here is transitive."""
    # build a batch whose lanes hit edge encodings via sign/high bits
    args, _ = dense_signature_batch(24, msg_len=80, seed=31)
    pub, rb, sb, blocks, active = [np.asarray(a).copy() for a in args]
    pub[0, 31] |= 0x80      # sign-bit x=0 family
    rb[1, 31] |= 0x80
    pub[2] = 0; pub[2, 0] = 1                      # y = 0 + sign 0
    rb[3] = 255                                    # non-canonical y >= p
    sb[4] = 255                                    # S >= L (must reject)
    args2 = (pub, rb, sb, blocks, active)
    want = np.asarray(jax.jit(ed25519.verify_padded)(*args2))
    got = np.asarray(jax.jit(limb_major.verify_padded_lm)(*args2))
    assert not want[4]                             # sanity: S>=L rejected
    assert (got == want).all()
