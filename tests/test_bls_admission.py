"""BLS key-admission (proof of possession) and BFT-time authentication.

The aggregate-commit fast path uses the IETF Basic ciphersuite over a
SHARED zero-timestamp message, which is exactly the rogue-key setting:
admission of any BLS pubkey without a verified proof of possession lets
an attacker forge aggregate lanes for cohorts it does not control.
These tests pin the three admission gates (genesis validation, ABCI
validator updates, InitChain response) and the companion BFT-time rule:
BLS lanes' commit timestamps are unauthenticated (the signature covers
the zero-timestamp domain), so ``median_time`` must never read them.
"""

import pytest

from cometbft_tpu.crypto import bls12381 as bls

pytestmark = pytest.mark.skipif(not bls.ENABLED,
                                reason="no BLS backend in this build")

CHAIN = "pop-chain"


def _bls_sk(tag: bytes):
    return bls.Bls12381PrivKey.from_secret(tag)


# ----------------------------------------------------------------- genesis


def _bls_genesis(pop: bytes):
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    sk = _bls_sk(b"genesis-val")
    return GenesisDoc(chain_id=CHAIN,
                      validators=[GenesisValidator(sk.pub_key(), 10,
                                                   "v0", pop)])


def test_genesis_requires_pop(monkeypatch):
    from cometbft_tpu.types.genesis import GenesisError

    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    with pytest.raises(GenesisError, match="proof of possession"):
        _bls_genesis(b"").validate_and_complete()


def test_genesis_rejects_wrong_pop(monkeypatch):
    from cometbft_tpu.types.genesis import GenesisError

    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    wrong = bls.pop_prove(_bls_sk(b"some-other-key").bytes())
    with pytest.raises(GenesisError, match="failed to verify"):
        _bls_genesis(wrong).validate_and_complete()


def test_genesis_pop_roundtrips_and_verifies(monkeypatch):
    from cometbft_tpu.types.genesis import GenesisDoc

    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    sk = _bls_sk(b"genesis-val")
    doc = _bls_genesis(bls.pop_prove(sk.bytes()))
    doc.validate_and_complete()
    doc2 = GenesisDoc.from_json(doc.to_json())     # from_json re-validates
    assert doc2.validators[0].pop == doc.validators[0].pop
    assert doc2.validators[0].pub_key == sk.pub_key()


def test_genesis_from_json_drops_pop_refused(monkeypatch):
    """A hand-edited genesis.json that strips the pop must be refused."""
    import json

    from cometbft_tpu.types.genesis import GenesisDoc, GenesisError

    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    sk = _bls_sk(b"genesis-val")
    doc = _bls_genesis(bls.pop_prove(sk.bytes()))
    d = json.loads(doc.to_json())
    del d["validators"][0]["pop"]
    with pytest.raises(GenesisError, match="proof of possession"):
        GenesisDoc.from_json(json.dumps(d))


# ------------------------------------------------- ABCI validator updates


def _exec_state(monkeypatch):
    from cometbft_tpu.storage.statestore import State
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    pvs = [MockPV.from_secret(b"upd%d" % i) for i in range(2)]
    doc = GenesisDoc(chain_id=CHAIN,
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])
    doc.consensus_params.validator.pub_key_types = ["ed25519", "bls12_381"]
    return State.from_genesis(doc)


def _apply_updates(state, updates):
    from cometbft_tpu.abci.types import FinalizeBlockResponse
    from cometbft_tpu.sm.execution import BlockExecutor
    from cometbft_tpu.types.block_id import BlockID
    from cometbft_tpu.types.header import Block, Data, Header

    execu = BlockExecutor(None, None, None, None)
    block = Block(header=Header(chain_id=CHAIN, height=1, time_ns=1),
                  data=Data(txs=[]))
    resp = FinalizeBlockResponse(validator_updates=updates)
    return execu._update_state(state, BlockID(), block, resp)


def test_update_admitting_bls_key_requires_pop(monkeypatch):
    from cometbft_tpu.abci.types import ValidatorUpdate
    from cometbft_tpu.sm.validation import BlockValidationError

    state = _exec_state(monkeypatch)
    sk = _bls_sk(b"new-bls-val")
    with pytest.raises(BlockValidationError, match="proof of possession"):
        _apply_updates(state, [ValidatorUpdate(
            "bls12_381", sk.pub_key().bytes(), 5)])
    wrong = bls.pop_prove(_bls_sk(b"unrelated").bytes())
    with pytest.raises(BlockValidationError, match="failed to verify"):
        _apply_updates(state, [ValidatorUpdate(
            "bls12_381", sk.pub_key().bytes(), 5, pop=wrong)])


def test_update_with_valid_pop_admits(monkeypatch):
    from cometbft_tpu.abci.types import ValidatorUpdate

    state = _exec_state(monkeypatch)
    sk = _bls_sk(b"new-bls-val")
    new_state = _apply_updates(state, [ValidatorUpdate(
        "bls12_381", sk.pub_key().bytes(), 5,
        pop=bls.pop_prove(sk.bytes()))])
    assert new_state.next_validators.has_address(sk.pub_key().address())


def test_update_of_admitted_key_needs_no_fresh_pop(monkeypatch):
    """Power changes and removals of an already-admitted BLS key carry
    no proof — the address IS the hash of the proven pubkey."""
    from cometbft_tpu.abci.types import ValidatorUpdate

    state = _exec_state(monkeypatch)
    sk = _bls_sk(b"new-bls-val")
    pk = sk.pub_key()
    state = _apply_updates(state, [ValidatorUpdate(
        "bls12_381", pk.bytes(), 5, pop=bls.pop_prove(sk.bytes()))])
    # next height: bump power with no pop, then remove with no pop
    state = _apply_updates(state, [ValidatorUpdate(
        "bls12_381", pk.bytes(), 9)])
    _, val = state.next_validators.get_by_address(pk.address())
    assert val is not None and val.voting_power == 9
    state = _apply_updates(state, [ValidatorUpdate(
        "bls12_381", pk.bytes(), 0)])
    assert not state.next_validators.has_address(pk.address())


def test_init_chain_response_admission_checked(monkeypatch):
    """An app's InitChain response replaces the valset wholesale — BLS
    entries there are admissions and must carry a verifying pop."""
    import asyncio

    from cometbft_tpu.abci.types import (InitChainResponse, ValidatorUpdate)
    from cometbft_tpu.consensus.replay import Handshaker, HandshakeError
    from cometbft_tpu.storage.statestore import State, StateStore
    from cometbft_tpu.storage.db import MemDB
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    pv = MockPV.from_secret(b"ic")
    doc = GenesisDoc(chain_id=CHAIN,
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])
    sk = _bls_sk(b"app-admitted")

    class _Conn:
        async def init_chain(self, req):
            return InitChainResponse(validators=[ValidatorUpdate(
                "bls12_381", sk.pub_key().bytes(), 10, pop=self.pop)])

    class _Conns:
        consensus = _Conn()

    hs = Handshaker(StateStore(MemDB()), None, doc)
    conns = _Conns()

    conns.consensus.pop = b""
    with pytest.raises(HandshakeError, match="proof of possession"):
        asyncio.run(hs._init_chain(State.from_genesis(doc), conns))

    conns.consensus.pop = bls.pop_prove(sk.bytes())
    st = asyncio.run(hs._init_chain(State.from_genesis(doc), conns))
    assert st.validators.has_address(sk.pub_key().address())


# ------------------------------------------------------------- BFT time


def test_median_time_excludes_unauthenticated_bls_lanes():
    """BLS validators sign the zero-timestamp domain, so their CommitSig
    timestamps are proposer-editable and must not move block time."""
    from cometbft_tpu.sm.validation import median_time
    from cometbft_tpu.types.block_id import BlockID
    from cometbft_tpu.types.commit import (BLOCK_ID_FLAG_AGGREGATE,
                                           BLOCK_ID_FLAG_COMMIT, Commit,
                                           CommitSig)
    from cometbft_tpu.types.priv_validator import MockPV
    from cometbft_tpu.types.validator_set import Validator, ValidatorSet

    kts = ["ed25519", "bls12_381", "ed25519", "bls12_381"]
    pvs = [MockPV.from_secret(b"mt%d" % i, key_type=kt)
           for i, kt in enumerate(kts)]
    vals = ValidatorSet([Validator(pv.get_pub_key(), 10) for pv in pvs])

    ed_ts, bls_ts = 1_000, 999_999_999
    sigs = []
    for v in vals.validators:
        is_bls = v.pub_key.type() == "bls12_381"
        sigs.append(CommitSig(
            BLOCK_ID_FLAG_AGGREGATE if is_bls else BLOCK_ID_FLAG_COMMIT,
            v.address, bls_ts if is_bls else ed_ts, b""))
    commit = Commit(1, 0, BlockID(), sigs)
    # the proposer-controlled BLS timestamps are ignored entirely
    assert median_time(commit, vals) == ed_ts

    # a commit with no authenticated lane yields 0 — callers fall back
    # to the deterministic last_block_time_ns + 1
    only_bls = Commit(1, 0, BlockID(), [
        cs if cs.block_id_flag == BLOCK_ID_FLAG_AGGREGATE
        else CommitSig.absent() for cs in sigs])
    assert median_time(only_bls, vals) == 0


# --------------------------------------------------- device-table cache


def test_valset_update_invalidates_bls_device_table(monkeypatch):
    """update_with_change_set must drop the blsagg device-fold point
    table with the other cached views: a stale table would fold
    rotated-out pubkeys into the aggregate pubkey."""
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.types.priv_validator import MockPV
    from cometbft_tpu.types.validator_set import Validator, ValidatorSet

    monkeypatch.setenv("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS", "1")
    pvs = [MockPV.from_secret(b"dt%d" % i, key_type="bls12_381")
           for i in range(3)]
    vals = ValidatorSet([Validator(pv.get_pub_key(), 10) for pv in pvs])
    vals.__dict__["_bls_dev_tbl"] = ("stale-sentinel",)
    vals.__dict__["_bls_agg_tbl"] = ("stale-sentinel",)
    vals.update_with_change_set(
        [Validator(Ed25519PrivKey.from_secret(b"fresh").pub_key(), 10)])
    assert "_bls_dev_tbl" not in vals.__dict__
    assert "_bls_agg_tbl" not in vals.__dict__
