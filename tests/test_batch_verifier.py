"""BatchVerifier seam tests: CPU + device backends, bucketing, mixed keys,
and the multi-chip sharded path on the virtual 8-device mesh."""

import numpy as np
import pytest

# first run on a cold XLA cache compiles several mesh-sharded kernel
# shapes at ~2 min each on this box; warm runs take seconds
pytestmark = pytest.mark.timeout(1200)

from cometbft_tpu.crypto import _ed25519_py as ref
from cometbft_tpu.crypto.batch import (CpuBatchVerifier, TpuBatchVerifier,
                                       create_batch_verifier,
                                       device_verify_ed25519,
                                       supports_batch_verifier)
from cometbft_tpu.crypto.keys import (Ed25519PrivKey, Ed25519PubKey,
                                      verify_ed25519_zip215)

rng = np.random.default_rng(7)


def make_sigs(n, bad=()):
    items = []
    for i in range(n):
        sk = Ed25519PrivKey.from_secret(b"key%d" % i)
        m = rng.bytes(int(rng.integers(0, 140)))
        s = bytearray(sk.sign(m))
        if i in bad:
            s[10] ^= 4
        items.append((sk.pub_key(), m, bytes(s)))
    return items


def test_single_verify_zip215_fallback():
    # OpenSSL rejects mixed-order/non-canonical inputs; fallback must accept
    # what the oracle accepts.  Reuse a non-canonical identity key case.
    P = ref.P
    r_scalar = 12345
    r_enc = ref.pt_compress(ref.pt_mul(r_scalar, ref.BASE))
    ident_nc = (1 + P).to_bytes(32, "little")
    sig = r_enc + r_scalar.to_bytes(32, "little")
    assert ref.verify_zip215(ident_nc, b"m", sig)
    assert verify_ed25519_zip215(ident_nc, b"m", sig)
    assert Ed25519PubKey(ident_nc).verify_signature(b"m", sig)
    assert not verify_ed25519_zip215(ident_nc, b"m2", sig[:-1] + b"\x01")


def test_cpu_batch_verifier():
    items = make_sigs(7, bad={3})
    bv = CpuBatchVerifier()
    for p, m, s in items:
        bv.add(p, m, s)
    ok, oks = bv.verify()
    assert not ok and oks == [True, True, True, False, True, True, True]


@pytest.mark.slow   # jitted device kernels, ~1 min each on CPU
def test_device_batch_verifier_buckets():
    # odd batch size forces lane padding; verify padding lanes don't leak
    items = make_sigs(21, bad={0, 20})
    bv = TpuBatchVerifier()
    for p, m, s in items:
        bv.add(p, m, s)
    ok, oks = bv.verify()
    assert not ok
    assert oks == [i not in (0, 20) for i in range(21)]

    bv2 = TpuBatchVerifier()
    for p, m, s in make_sigs(5):
        bv2.add(p, m, s)
    ok2, oks2 = bv2.verify()
    assert ok2 and all(oks2)


def test_mixed_key_types_route_to_cpu():
    class FakeKey:
        def type(self):
            return "secp256k1"

        def bytes(self):
            return b"\x02" * 33

        def verify_signature(self, msg, sig):
            return sig == b"ok"

    items = make_sigs(4)
    bv = TpuBatchVerifier()
    bv.add(items[0][0], items[0][1], items[0][2])
    bv.add(FakeKey(), b"m", b"ok")
    bv.add(items[1][0], items[1][1], items[1][2])
    bv.add(FakeKey(), b"m", b"bad")
    ok, oks = bv.verify()
    assert oks == [True, True, True, False] and not ok
    assert supports_batch_verifier(items[0][0])
    assert not supports_batch_verifier(FakeKey())


def test_create_dispatch():
    assert isinstance(create_batch_verifier("cpu"), CpuBatchVerifier)
    assert isinstance(create_batch_verifier("tpu"), TpuBatchVerifier)
    assert isinstance(create_batch_verifier("auto"), CpuBatchVerifier)  # tests run CPU-only


@pytest.mark.slow   # jitted device kernels, ~1 min each on CPU
def test_dense_entry_empty_and_chunked(monkeypatch):
    assert device_verify_ed25519(
        np.zeros((0, 32), np.uint8), np.zeros((0, 32), np.uint8),
        np.zeros((0, 32), np.uint8), np.zeros((0, 1), np.uint8),
        np.zeros((0,), np.int64)).shape == (0,)

    # exercise the lane-chunking path with tiny buckets (the dispatch
    # reads the declarative device plan since r13)
    import dataclasses

    from cometbft_tpu.crypto import plan as plan_mod
    saved = plan_mod.active()
    plan_mod.set_plan(dataclasses.replace(saved, lane_buckets=(4, 8)),
                      push_min_lanes=False)
    try:
        items = make_sigs(21, bad={0, 9, 20})
        bv = TpuBatchVerifier()
        for p, m, s in items:
            bv.add(p, m, s)
        ok, oks = bv.verify()
        assert not ok and oks == [i not in (0, 9, 20) for i in range(21)]
    finally:
        plan_mod.set_plan(saved, push_min_lanes=False)


@pytest.mark.slow   # jitted device kernels, ~1 min each on CPU
def test_oversized_message_exact_bucket():
    # > 16 hash blocks (msg ~2KB) must verify, not crash on bucket overflow
    sk = Ed25519PrivKey.from_secret(b"big")
    m = bytes(rng.integers(0, 256, size=2100, dtype=np.uint8))
    sig = sk.sign(m)
    bad = sig[:10] + bytes([sig[10] ^ 1]) + sig[11:]
    bv = TpuBatchVerifier()
    bv.add(sk.pub_key(), m, sig)
    bv.add(sk.pub_key(), m, bad)
    ok, oks = bv.verify()
    assert oks[0] is True and oks[1] is False


@pytest.mark.slow   # jitted device kernels, ~1 min each on CPU
def test_graft_entry_and_multichip():
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = np.asarray(jax.jit(fn)(*args))
    assert out.shape == (16,) and out.all()

    assert len(jax.devices()) == 8, "conftest should provide 8 virtual devices"
    ge.dryrun_multichip(8)
    ge.dryrun_multichip(4)


def test_init_multihost_single_host_default(monkeypatch):
    """init_multihost without a coordinator is the single-host path: no
    distributed init, a global batch mesh over the local devices (the
    multi-process path needs real hosts; launchers set the JAX_* env)."""
    import pytest

    from cometbft_tpu.parallel import batch_mesh, init_multihost

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    with pytest.raises(ValueError):
        init_multihost(num_processes=4)     # args without a coordinator
    mesh = init_multihost()
    assert mesh.axis_names == ("batch",)
    assert mesh.devices.size == batch_mesh().devices.size


# ------------------------------------------------------- native (C++) RLC

def test_native_ed25519_available():
    """The on-demand g++ build must work on this image (SURVEY §2.9-1:
    the CPU fallback is native, never a Python stand-in)."""
    from cometbft_tpu.crypto import _native_ed25519 as nat

    assert nat.available()


def test_native_single_matches_oracle_on_edges():
    """ZIP-215 edge semantics: non-canonical encodings, small-order
    points, s >= L — native verdicts must equal the pure-Python oracle."""
    from cometbft_tpu.crypto import _native_ed25519 as nat

    P, L = ref.P, ref.L
    msg = b"edge"

    def enc(y, sign):
        return int.to_bytes((y & ((1 << 255) - 1)) | (sign << 255), 32,
                            "little")

    pubs = [enc(y, s) for y in (0, 1, P - 1, P, P + 1, 2**255 - 1, 2)
            for s in (0, 1)]
    rs = pubs[:6]
    svals = (0, 1, L - 1, L, 7)
    checked = 0
    for pub in pubs:
        for r in rs:
            for sv in svals:
                sig = r + sv.to_bytes(32, "little")
                assert nat.verify(pub, msg, sig) == ref.verify_zip215(
                    pub, msg, sig), (pub.hex(), sig.hex())
                checked += 1
    assert checked == len(pubs) * len(rs) * len(svals)


def test_native_batch_verify_and_localization():
    from cometbft_tpu.crypto import _native_ed25519 as nat

    items = make_sigs(33)
    pubs = [p.bytes() for p, _, _ in items]
    msgs = [m for _, m, _ in items]
    sigs = [s for _, _, s in items]
    assert nat.batch_verify(pubs, msgs, sigs) is True
    bad = list(sigs)
    bad[17] = bytes(64)
    assert nat.batch_verify(pubs, msgs, bad) is False
    assert nat.batch_verify([], [], []) is False

    # the seam: CpuBatchVerifier routes through the native batch and
    # localizes failures per lane
    bv = CpuBatchVerifier()
    for (p, m, _), s in zip(items, bad):
        bv.add(p, m, s)
    ok, oks = bv.verify()
    assert not ok
    assert oks == [i != 17 for i in range(33)]


def test_native_batch_accepts_zip215_only_sigs():
    """A batch containing a signature OpenSSL would reject but ZIP-215
    accepts (non-canonical A) must still pass as a whole — parity with
    the oracle, not with OpenSSL."""
    from cometbft_tpu.crypto import _native_ed25519 as nat

    P = ref.P
    r_scalar = 12345
    r_enc = ref.pt_compress(ref.pt_mul(r_scalar, ref.BASE))
    ident_nc = (1 + P).to_bytes(32, "little")     # non-canonical identity
    odd_sig = r_enc + r_scalar.to_bytes(32, "little")
    assert ref.verify_zip215(ident_nc, b"m", odd_sig)

    items = make_sigs(4)
    pubs = [p.bytes() for p, _, _ in items] + [ident_nc]
    msgs = [m for _, m, _ in items] + [b"m"]
    sigs = [s for _, _, s in items] + [odd_sig]
    assert nat.batch_verify(pubs, msgs, sigs) is True


def _drain_device_worker():
    """Wait out any dispatch a PRIOR test left on the single device-owner
    thread: _device_call sees an unfinished in-flight future and silently
    host-falls-back, which would make the sharded-jit assertions below
    fail for reasons unrelated to the code under test."""
    import cometbft_tpu.crypto.batch as B

    fut = B._DEVICE_INFLIGHT
    if fut is not None and not fut.done():
        try:
            fut.result(timeout=600)
        except Exception:      # any outcome is fine — it just must END
            pass


@pytest.mark.slow   # jitted device kernels, ~1 min each on CPU
def test_production_verifier_shards_over_mesh(monkeypatch):
    """VERDICT r2 item 5: the PRODUCTION TpuBatchVerifier (not a demo)
    shards over a multi-device mesh and agrees with single-device
    results.  Runs on the conftest's virtual 8-CPU-device mesh."""
    # a prior test that STARTED A NODE applies its config's
    # min_device_lanes (64) process-wide; these small batches must
    # still exercise the device route
    import cometbft_tpu.crypto.batch as _B

    monkeypatch.setattr(_B.TpuBatchVerifier, 'MIN_DEVICE_LANES', 1)
    _drain_device_worker()
    import jax

    import cometbft_tpu.crypto.batch as B

    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provide the 8-device CPU mesh"

    calls = []
    real = B._compiled_verify_sharded

    def spy(devices):
        calls.append(devices)
        return real(devices)

    monkeypatch.setattr(B, "_compiled_verify_sharded", spy)
    monkeypatch.setattr(B, "_DEVICE_WAIT_S", 600.0)
    B.set_devices(devs[:8])
    try:
        items = make_sigs(21, bad={0, 20})
        bv = B.create_batch_verifier("jax")
        assert isinstance(bv, B.TpuBatchVerifier)
        for p, m, s in items:
            bv.add(p, m, s)
        ok, oks = bv.verify()
    finally:
        B.set_devices(None)
    assert calls and len(calls[0]) == 8, "sharded jit was not used"
    assert not ok
    assert oks == [i not in (0, 20) for i in range(21)]

    # single-device agreement on the same items
    bv1 = B.TpuBatchVerifier(devs[0])
    for p, m, s in items:
        bv1.add(p, m, s)
    ok1, oks1 = bv1.verify()
    assert (ok1, oks1) == (ok, oks)


def test_verify_dense_shards_over_mesh(monkeypatch):
    """The dense VerifyCommit dispatch rides the same sharded path."""
    # a prior test that STARTED A NODE applies its config's
    # min_device_lanes (64) process-wide; these small batches must
    # still exercise the device route
    import cometbft_tpu.crypto.batch as _B

    monkeypatch.setattr(_B.TpuBatchVerifier, 'MIN_DEVICE_LANES', 1)
    _drain_device_worker()
    import jax
    import numpy as np

    import cometbft_tpu.crypto.batch as B
    from cometbft_tpu.crypto import _native_ed25519 as nat
    from cometbft_tpu.types.canonical import (SIGNED_MSG_TYPE_PRECOMMIT,
                                              CanonicalVoteEncoder)
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader

    devs = jax.devices()
    calls = []
    real = B._compiled_verify_sharded
    monkeypatch.setattr(B, "_compiled_verify_sharded",
                        lambda d: (calls.append(d), real(d))[1])
    monkeypatch.setattr(B, "_DEVICE_WAIT_S", 600.0)

    bid = BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32))
    enc = CanonicalVoteEncoder("sh-chain", SIGNED_MSG_TYPE_PRECOMMIT, 3, 0,
                               bid)
    items = []
    for i in range(24):
        sk = Ed25519PrivKey.from_secret(b"shard%d" % i)
        m = enc.sign_bytes(1_700_000_000_000_000_000 + i)
        items.append((sk.pub_key().bytes(), m, sk.sign(m)))
    pubs = np.frombuffer(b"".join(p for p, _, _ in items),
                         np.uint8).reshape(24, 32)
    sigs = np.frombuffer(b"".join(s for _, _, s in items),
                         np.uint8).reshape(24, 64)
    width = max(len(m) for _, m, _ in items)
    msgs = np.zeros((24, width), np.uint8)
    lens = np.zeros((24,), np.int64)
    for i, (_, m, _) in enumerate(items):
        msgs[i, :len(m)] = np.frombuffer(m, np.uint8)
        lens[i] = len(m)
    B.set_devices(devs[:8])
    try:
        res = B.verify_dense("jax", pubs, sigs, msgs, lens)
    finally:
        B.set_devices(None)
    assert res is not None
    ok, oks = res
    assert ok and oks.all() and len(oks) == 24
    assert calls and len(calls[0]) == 8


def test_valset_table_cache_path():
    """device_verify_ed25519_cached: per-valset [j](-A) tables are built
    once, reused across batches (cache hit by matrix identity), and give
    identical verdicts to the uncached kernel — incl. partial scopes
    (Light early exit) and bad lanes."""
    import numpy as np

    import cometbft_tpu.crypto.batch as B
    from cometbft_tpu.testing import dense_signature_batch

    _, host_items = dense_signature_batch(12, msg_len=40, seed=23)
    pubs = np.frombuffer(b"".join(p for p, _, _ in host_items),
                         np.uint8).reshape(-1, 32)
    sigs = np.frombuffer(b"".join(s for _, _, s in host_items),
                         np.uint8).reshape(-1, 64)
    msgs = np.zeros((12, 40), np.uint8)
    lens = np.full((12,), 40, np.int64)
    for i, (_, m, _) in enumerate(host_items):
        msgs[i] = np.frombuffer(m, np.uint8)
    rs = np.ascontiguousarray(sigs[:, :32])
    ss = np.ascontiguousarray(sigs[:, 32:])

    scope = np.arange(12, dtype=np.int64)
    B._VALSET_TABLES.clear()
    out = B.device_verify_ed25519_cached(pubs, scope, pubs, rs, ss,
                                         msgs, lens, None)
    assert out.all() and len(out) == 12
    assert len(B._VALSET_TABLES) == 1
    ref = B.device_verify_ed25519(pubs, rs, ss, msgs, lens, None)
    assert (out == ref).all()

    # cache hit on a second batch from the same valset, partial scope
    sub = np.arange(3, 9, dtype=np.int64)
    bad_ss = ss.copy()
    bad_ss[5] ^= 1
    out2 = B.device_verify_ed25519_cached(pubs, sub, pubs[sub], rs[sub],
                                          bad_ss[sub], msgs[sub],
                                          lens[sub], None)
    assert len(B._VALSET_TABLES) == 1      # same entry, no rebuild
    assert not out2[2] and out2.sum() == 5  # lane 5 == sub position 2


def test_bucket_policy_caps_lanes_but_grows_tables():
    """Lane buckets cap at 4096 (TPU v5e measured sweet spot — bigger
    batches chunk), while valset TABLE rows keep bucketing upward: the
    cached gather table must hold every validator and cannot chunk."""
    import cometbft_tpu.crypto.batch as B

    assert B._LANE_BUCKETS[-1] == 4096
    assert B.bucket_for_lanes(10000) == 4096
    assert B.buckets_for_batch(9000) == (1024, 4096)
    # a 10k-validator table pads to 16384 rows, not 10000 exactly —
    # warmup at valset_sizes=(10000,) compiles the SAME shape the first
    # real commit will hit
    assert B._bucket(10000, B._TABLE_BUCKETS) == 16384
    assert B._bucket(4096, B._TABLE_BUCKETS) == 4096


def test_warmup_covers_valset_table_shapes():
    """warmup_device(valset_sizes=...) drives the cached-gather route at
    real valset scale: table built at the TABLE bucket, then dropped
    (warmup matrices are not real valsets)."""
    import cometbft_tpu.crypto.batch as B

    B._VALSET_TABLES.clear()
    done = B.warmup_device(lane_buckets=(), block_buckets=(2,),
                           valset_sizes=(20,))
    assert done == 1
    assert not B._VALSET_TABLES          # cleared after warmup


# ------------------------------------------------ RLC routing regression
# Measured-routing pins for the sharded-RLC gate (ISSUE 3 satellite):
# every verdict jit is mocked so no kernel compiles — only the DISPATCH
# decisions in _device_verify_chunk / device_verify_ed25519_cached are
# under test.  The sharded RLC's own correctness is covered by the
# slow-tier differential (compile-heavy); these pins keep the gate's
# shape honest in tier-1.

def _fake_verdict_fns(monkeypatch, rlc_verdict=True):
    """Mock every compiled-verdict factory in crypto.batch; returns the
    call log {name: [devices_or_(), ...]}."""
    import cometbft_tpu.crypto.batch as B

    calls = {}

    def factory(name, fn):
        def make(*key):
            calls.setdefault(name, []).append(key[0] if key else ())
            return fn
        return make

    ones = lambda *a: np.ones(np.asarray(a[0]).shape[0], bool)  # noqa: E731
    verdict = lambda *a: np.bool_(rlc_verdict)                  # noqa: E731
    monkeypatch.setattr(B, "_compiled_rlc_sharded", factory(
        "rlc_sharded", verdict))
    monkeypatch.setattr(B, "_compiled_rlc", factory("rlc", verdict))
    monkeypatch.setattr(B, "_compiled_verify_sharded", factory(
        "verify_sharded", ones))
    monkeypatch.setattr(B, "_compiled_verify", factory("verify", ones))
    monkeypatch.setattr(B, "_compiled_rlc_gather_sharded", factory(
        "rlc_gather_sharded", verdict))
    monkeypatch.setattr(B, "_compiled_rlc_gather", factory(
        "rlc_gather", verdict))
    monkeypatch.setattr(
        B, "_compiled_verify_gather",
        factory("verify_gather", lambda tab, ok, *a:
                np.ones(np.asarray(a[0]).shape[0], bool)))
    return calls


def _dense_rows(b, width=40):
    r = np.random.default_rng(b)
    return (r.integers(0, 256, (b, 32), np.uint8),
            r.integers(0, 256, (b, 32), np.uint8),
            r.integers(0, 256, (b, 32), np.uint8),
            r.integers(0, 256, (b, width), np.uint8),
            np.full((b,), width, np.int64))


def test_rlc_sharded_gate_routing(monkeypatch):
    """Multi-device + >= _RLC_MIN_LANES lanes must try the lane-sharded
    RLC verdict FIRST (the gate the old code forbade); a reject falls
    through to the per-lane sharded jit for localization; sub-threshold
    batches keep the per-lane path with no RLC attempt."""
    import jax

    import cometbft_tpu.crypto.batch as B

    devs = tuple(jax.devices()[:8])
    assert len(devs) == 8, "conftest must provide the 8-device CPU mesh"
    pubs, rs, ss, msgs, lens = _dense_rows(130)

    calls = _fake_verdict_fns(monkeypatch)
    out = B._device_verify_chunk(pubs, rs, ss, msgs, lens, None)
    # single default device: plain RLC, never the sharded variants
    assert list(calls) == ["rlc"] and out.all() and out.shape == (130,)

    B.set_devices(devs)
    try:
        calls = _fake_verdict_fns(monkeypatch)
        out = B._device_verify_chunk(pubs, rs, ss, msgs, lens, None)
        assert list(calls) == ["rlc_sharded"], \
            f"accepted big batch must stop at the sharded RLC: {calls}"
        assert calls["rlc_sharded"] == [devs]
        assert out.all() and out.shape == (130,)

        # a sharded-RLC reject must localize through the per-lane jit
        calls = _fake_verdict_fns(monkeypatch, rlc_verdict=False)
        out = B._device_verify_chunk(pubs, rs, ss, msgs, lens, None)
        assert list(calls) == ["rlc_sharded", "verify_sharded"]
        assert out.shape == (130,)

        # below the gate: straight to the per-lane sharded jit
        calls = _fake_verdict_fns(monkeypatch)
        small = _dense_rows(24)
        out = B._device_verify_chunk(*small, None)
        assert list(calls) == ["verify_sharded"] and out.shape == (24,)
    finally:
        B.set_devices(None)


def test_rlc_sharded_gate_routing_cached(monkeypatch):
    """The cached-valset route rides the gather-sharded RLC on a mesh
    and the plain gather RLC on one device, same gate threshold."""
    import jax

    import cometbft_tpu.crypto.batch as B

    devs = tuple(jax.devices()[:8])
    monkeypatch.setattr(B, "_valset_tables",
                        lambda pubs_full, devices: (object(), object(), 256))
    valset, rs, ss, msgs, lens = _dense_rows(130)
    scope = np.arange(130, dtype=np.int64)

    calls = _fake_verdict_fns(monkeypatch)
    out = B.device_verify_ed25519_cached(valset, scope, valset, rs, ss,
                                         msgs, lens, None)
    assert list(calls) == ["rlc_gather"] and out.all()

    B.set_devices(devs)
    try:
        calls = _fake_verdict_fns(monkeypatch)
        out = B.device_verify_ed25519_cached(valset, scope, valset, rs, ss,
                                             msgs, lens, None)
        assert list(calls) == ["rlc_gather_sharded"]
        assert calls["rlc_gather_sharded"] == [devs]
        assert out.all() and out.shape == (130,)

        # reject: localization through the gather per-lane jit
        calls = _fake_verdict_fns(monkeypatch, rlc_verdict=False)
        out = B.device_verify_ed25519_cached(valset, scope, valset, rs, ss,
                                             msgs, lens, None)
        assert list(calls) == ["rlc_gather_sharded", "verify_gather"]
    finally:
        B.set_devices(None)
