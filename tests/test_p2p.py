"""P2P stack tests: SecretConnection self-interop over real TCP,
MConnection multiplexing/priority/ping, transport upgrade validation,
Switch peer lifecycle + broadcast + persistent reconnect
(reference test strategy: p2p/conn/*_test.go, p2p/switch_test.go)."""

import asyncio

import pytest

from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.p2p import (ChannelDescriptor, NodeInfo, NodeKey, Reactor,
                              Switch, Transport)
from cometbft_tpu.p2p.conn import MConnection
from cometbft_tpu.p2p.secret_connection import (SecretConnectionError,
                                                handshake)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def _tcp_pair():
    """Two connected (reader, writer) pairs over a real localhost socket."""
    accepted = asyncio.get_running_loop().create_future()

    async def on_conn(r, w):
        accepted.set_result((r, w))

    server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
    host, port = server.sockets[0].getsockname()[:2]
    r1, w1 = await asyncio.open_connection(host, port)
    r2, w2 = await accepted
    return server, (r1, w1), (r2, w2)


# ---------------------------------------------------------------- secretconn

def test_secret_connection_roundtrip():
    async def main():
        server, (r1, w1), (r2, w2) = await _tcp_pair()
        k1, k2 = Ed25519PrivKey.generate(), Ed25519PrivKey.generate()
        c1, c2 = await asyncio.gather(handshake(r1, w1, k1),
                                      handshake(r2, w2, k2))
        # identities proven mutually
        assert c1.remote_pub_key.bytes() == k2.pub_key().bytes()
        assert c2.remote_pub_key.bytes() == k1.pub_key().bytes()
        # bidirectional data, including > frame-size messages
        big = bytes(range(256)) * 40        # 10240 bytes, > 10 frames
        await c1.write_msg(b"hello")
        await c2.write_msg(big)
        assert await c2.read_msg() == b"hello"
        assert await c1.read_msg() == big
        c1.close(), c2.close()
        server.close()
        return True

    assert run(main())


def test_secret_connection_tamper_detected():
    async def main():
        server, (r1, w1), (r2, w2) = await _tcp_pair()
        k1, k2 = Ed25519PrivKey.generate(), Ed25519PrivKey.generate()
        c1, c2 = await asyncio.gather(handshake(r1, w1, k1),
                                      handshake(r2, w2, k2))
        # flip one ciphertext bit on the wire: receiver must reject
        from cometbft_tpu.p2p import secret_connection as sc

        frame = bytearray()
        orig_write = w1.write

        def corrupt_write(data):
            b = bytearray(data)
            b[5] ^= 0x01
            orig_write(bytes(b))

        w1.write = corrupt_write
        await c1.write_msg(b"attack at dawn")
        with pytest.raises(SecretConnectionError):
            await c2.read_msg()
        c1.close(), c2.close()
        server.close()
        return True

    assert run(main())


# --------------------------------------------------------------- mconnection

def _mconn_pair(c1, c2, descs, recv1, recv2, **kw):
    m1 = MConnection(c1, descs, lambda ch, m: recv1.append((ch, m)),
                     lambda e: recv1.append(("err", e)), **kw)
    m2 = MConnection(c2, descs, lambda ch, m: recv2.append((ch, m)),
                     lambda e: recv2.append(("err", e)), **kw)
    m1.start(), m2.start()
    return m1, m2


def test_mconnection_multiplex_and_reassembly():
    async def main():
        server, (r1, w1), (r2, w2) = await _tcp_pair()
        k1, k2 = Ed25519PrivKey.generate(), Ed25519PrivKey.generate()
        c1, c2 = await asyncio.gather(handshake(r1, w1, k1),
                                      handshake(r2, w2, k2))
        descs = [ChannelDescriptor(0x20, priority=5),
                 ChannelDescriptor(0x30, priority=1)]
        got1, got2 = [], []
        m1, m2 = _mconn_pair(c1, c2, descs, got1, got2)
        big = b"B" * 5000                   # spans multiple packets
        assert m1.send(0x20, b"vote")
        assert m1.send(0x30, big)
        assert m2.send(0x30, b"tx1")
        for _ in range(200):
            if len(got2) >= 2 and len(got1) >= 1:
                break
            await asyncio.sleep(0.01)
        assert (0x20, b"vote") in got2
        assert (0x30, big) in got2
        assert (0x30, b"tx1") in got1
        await m1.stop(), await m2.stop()
        server.close()
        return True

    assert run(main())


def test_mconnection_unknown_channel_refused():
    async def main():
        server, (r1, w1), (r2, w2) = await _tcp_pair()
        k1, k2 = Ed25519PrivKey.generate(), Ed25519PrivKey.generate()
        c1, c2 = await asyncio.gather(handshake(r1, w1, k1),
                                      handshake(r2, w2, k2))
        m1, _ = _mconn_pair(c1, c2, [ChannelDescriptor(0x20)], [], [])
        assert not m1.send(0x99, b"nope")
        await m1.stop()
        server.close()
        return True

    assert run(main())


# ----------------------------------------------------------------- transport

def _make_switch(network="net1", secret=None, **kw):
    nk = NodeKey.from_secret(secret) if secret else NodeKey.generate()
    info_holder = {}

    def node_info():
        return NodeInfo(node_id=nk.id,
                        listen_addr=info_holder.get("addr", ""),
                        network=network,
                        channels=info_holder.get("channels", b""))

    tr = Transport(nk, node_info)
    sw = Switch(tr, **kw)
    info_holder["sw"] = sw

    async def listen():
        addr = await tr.listen("127.0.0.1", 0)
        info_holder["addr"] = addr
        info_holder["channels"] = sw.channel_ids
        return addr

    return sw, listen


class EchoReactor(Reactor):
    CHAN = 0x42

    def __init__(self):
        super().__init__()
        self.received = []
        self.peers = []
        self.removed = []

    def get_channels(self):
        return [ChannelDescriptor(self.CHAN, priority=3)]

    def add_peer(self, peer):
        self.peers.append(peer)

    def remove_peer(self, peer, reason=None):
        self.removed.append(peer.id)

    def receive(self, chan, peer, msg):
        self.received.append((peer.id, msg))
        if msg.startswith(b"ping:"):
            peer.send(chan, b"echo:" + msg[5:])


def test_switch_connect_and_broadcast():
    async def main():
        sw1, listen1 = _make_switch(secret=b"sw1")
        sw2, listen2 = _make_switch(secret=b"sw2")
        e1, e2 = EchoReactor(), EchoReactor()
        sw1.add_reactor("echo", e1)
        sw2.add_reactor("echo", e2)
        addr1 = await listen1()
        await listen2()
        await sw1.start(), await sw2.start()
        peer = await sw2.dial_peer(addr1)
        for _ in range(200):            # accept side registers async
            if sw1.n_peers() == 1:
                break
            await asyncio.sleep(0.01)
        assert sw1.n_peers() == 1 and sw2.n_peers() == 1
        assert e2.peers and e1.peers
        peer.send(EchoReactor.CHAN, b"ping:hi")
        for _ in range(200):
            if e2.received:
                break
            await asyncio.sleep(0.01)
        assert e2.received == [(sw1.transport.node_key.id, b"echo:hi")]
        # broadcast from sw1 reaches sw2
        sw1.broadcast(EchoReactor.CHAN, b"announce")
        for _ in range(200):
            if any(m == b"announce" for _, m in e2.received):
                break
            await asyncio.sleep(0.01)
        assert any(m == b"announce" for _, m in e2.received)
        await sw1.stop(), await sw2.stop()
        return True

    assert run(main())


def test_switch_rejects_wrong_network():
    async def main():
        sw1, listen1 = _make_switch(network="chain-A", secret=b"swa")
        sw2, listen2 = _make_switch(network="chain-B", secret=b"swb")
        addr1 = await listen1()
        await sw1.start(), await sw2.start()
        with pytest.raises(Exception):
            await sw2.dial_peer(addr1)
        assert sw1.n_peers() == 0 and sw2.n_peers() == 0
        await sw1.stop(), await sw2.stop()
        return True

    assert run(main())


def test_mconnection_telemetry_counters():
    """Per-channel bytes/msgs both directions, queue-full drops, and the
    telemetry() snapshot shape — the raw material of /net_info."""
    async def main():
        server, (r1, w1), (r2, w2) = await _tcp_pair()
        k1, k2 = Ed25519PrivKey.generate(), Ed25519PrivKey.generate()
        c1, c2 = await asyncio.gather(handshake(r1, w1, k1),
                                      handshake(r2, w2, k2))
        descs = [ChannelDescriptor(0x20, priority=5, name="state",
                                   send_queue_capacity=2),
                 ChannelDescriptor(0x30, priority=1, name="bulk")]
        got1, got2 = [], []
        m1, m2 = _mconn_pair(c1, c2, descs, got1, got2)
        big = b"B" * 5000                   # spans multiple packets
        assert m1.send(0x20, b"vote")
        assert m1.send(0x30, big)
        for _ in range(200):
            if len(got2) >= 2:
                break
            await asyncio.sleep(0.01)
        t1, t2 = m1.telemetry(), m2.telemetry()
        assert t1["channels"]["state"]["sent_msgs"] == 1
        assert t1["channels"]["state"]["sent_bytes"] == len(b"vote")
        assert t1["channels"]["bulk"]["sent_msgs"] == 1
        assert t1["channels"]["bulk"]["sent_bytes"] == len(big)
        assert t2["channels"]["state"]["recv_msgs"] == 1
        assert t2["channels"]["bulk"]["recv_bytes"] == len(big)
        assert t2["recv_bytes_total"] > len(big)      # framing overhead
        assert t1["channels"]["state"]["send_queue_capacity"] == 2
        assert t1["age_s"] >= 0 and t2["last_recv_age_s"] >= 0
        # queue-full drops are counted per channel (capacity 2, stopped
        # send routine cannot drain under a fast enough fill)
        drops_before = m1.channels[0x20].queue_full_drops
        sent = sum(1 for _ in range(50) if m1.send(0x20, b"x" * 900))
        assert m1.channels[0x20].queue_full_drops == \
            drops_before + (50 - sent)
        assert sent < 50
        t1b = m1.telemetry()
        assert t1b["channels"]["state"]["queue_full_drops"] >= 1
        await m1.stop(), await m2.stop()
        server.close()
        return True

    assert run(main())


def test_switch_peer_gauges_and_telemetry_flush():
    """Direction-labeled peer gauges, per-peer Prometheus series after a
    sampler flush, peer_snapshot() for /net_info, and gauge cleanup when
    the peer leaves."""
    async def main():
        from cometbft_tpu.p2p.metrics import p2p_metrics, peer_label

        sw1, listen1 = _make_switch(secret=b"tm1")
        sw2, listen2 = _make_switch(secret=b"tm2")
        e1, e2 = EchoReactor(), EchoReactor()
        sw1.add_reactor("echo", e1)
        sw2.add_reactor("echo", e2)
        addr1 = await listen1()
        await listen2()
        await sw1.start(), await sw2.start()
        peer = await sw2.dial_peer(addr1)
        for _ in range(200):
            if sw1.n_peers() == 1:
                break
            await asyncio.sleep(0.01)
        mets = p2p_metrics()
        assert mets.peers.value(node=sw2._m_node,
                                direction="outbound") == 1
        assert mets.peers.value(node=sw2._m_node,
                                direction="inbound") == 0
        assert mets.peers.value(node=sw1._m_node,
                                direction="inbound") == 1
        # handshake latency was observed on both sides
        assert mets.handshake_seconds.count(
            node=sw2._m_node, direction="outbound") >= 1
        assert mets.handshake_seconds.count(
            node=sw1._m_node, direction="inbound") >= 1

        peer.send(EchoReactor.CHAN, b"ping:hello")
        for _ in range(200):
            if e2.received:
                break
            await asyncio.sleep(0.01)
        # reactor dispatch counted on the receiving switch
        assert mets.reactor_msgs.value(reactor="echo",
                                       node=sw1._m_node) >= 1

        # per-peer series appear after an explicit sampler flush
        sw2.flush_peer_telemetry()
        pl = peer_label(sw1.transport.node_key.id)
        assert mets.peer_send_bytes.value(
            node=sw2._m_node, peer=pl, channel="0x42") > 0
        assert mets.peer_recv_bytes.value(
            node=sw2._m_node, peer=pl, channel="0x42") > 0
        # the same totals feed peer_snapshot / net_info
        snap = sw2.peer_snapshot()
        assert len(snap) == 1
        chan = snap[0]["connection_status"]["channels"]["0x42"]
        assert chan["sent_msgs"] >= 1 and chan["recv_msgs"] >= 1
        assert snap[0]["gossip"]["useful_votes"] == 0
        assert sw2.quietest_peer_recv_age_s() is not None

        # on disconnect the peer's gauges are dropped, counters remain
        mets.peer_queue_depth.set(1, node=sw2._m_node, peer=pl,
                                  channel="0x42")
        await sw2.stop_peer_gracefully(peer)
        assert mets.peer_queue_depth.value(
            node=sw2._m_node, peer=pl, channel="0x42") == 0.0
        assert mets.peers.value(node=sw2._m_node, direction="outbound") == 0
        assert sw2.quietest_peer_recv_age_s() is None
        await sw1.stop(), await sw2.stop()
        return True

    assert run(main())


def test_dial_failure_counted():
    async def main():
        from cometbft_tpu.p2p.metrics import p2p_metrics

        sw, listen = _make_switch(secret=b"df1")
        await listen()
        await sw.start()
        before = p2p_metrics().dial_failures.value(node=sw._m_node)
        with pytest.raises(Exception):
            await sw.dial_peer("127.0.0.1:1")     # nothing listens there
        assert p2p_metrics().dial_failures.value(
            node=sw._m_node) == before + 1
        await sw.stop()
        return True

    assert run(main())


def test_switch_persistent_reconnect():
    async def main():
        sw1, listen1 = _make_switch(secret=b"p1")
        sw2, listen2 = _make_switch(secret=b"p2")
        e1, e2 = EchoReactor(), EchoReactor()
        sw1.add_reactor("echo", e1)
        sw2.add_reactor("echo", e2)
        addr1 = await listen1()
        await listen2()
        await sw1.start(), await sw2.start()
        peer = await sw2.dial_peer(addr1, persistent=True)
        # kill the connection from sw2's side via error path
        await sw2.stop_peer_for_error(peer, RuntimeError("injected"))
        # sw1 should see the drop; sw2 should reconnect automatically
        for _ in range(600):
            if sw2.n_peers() == 1 and sw1.n_peers() == 1 and \
                    len(e2.removed) >= 1:
                break
            await asyncio.sleep(0.01)
        assert sw2.n_peers() == 1, "persistent peer did not reconnect"
        await sw1.stop(), await sw2.stop()
        return True

    assert run(main())
