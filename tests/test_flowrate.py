"""Unit tests for ``libs/flowrate.Monitor`` rate math — the meter behind
every MConnection's send/recv telemetry and rate limiting (it shipped
untested before the network-telemetry PR).  All tests drive an injected
clock with binary-exact step sizes (0.125, 1/64) so period-boundary
comparisons are deterministic, not at the mercy of decimal float error."""

import pytest

from cometbft_tpu.libs.flowrate import Monitor

pytestmark = pytest.mark.timeout(60)

PERIOD = 0.125                  # binary-exact sample period
STEP = 1 / 64                   # binary-exact sub-period step (8 per period)


class FakeClock:
    def __init__(self, t=1024.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _monitor(alpha=0.25):
    clk = FakeClock()
    return Monitor(sample_period=PERIOD, ema_alpha=alpha, now=clk), clk


# ------------------------------------------------------------------- EMA

def test_ema_converges_to_steady_rate():
    """Updating n bytes once per full sample period converges the EMA to
    n/period bytes/sec (1000 B / 0.125 s -> 8 kB/s)."""
    m, clk = _monitor()
    for _ in range(60):
        clk.advance(PERIOD)
        m.update(1000)
    assert m.rate == pytest.approx(8000, rel=0.01)
    assert m.total == 60_000


def test_ema_window_sub_period_updates_accumulate():
    """Updates inside one sample period accumulate into a single sample:
    eight 125-byte updates across one period count the same as one
    1000-byte update (the EMA never sees partial windows)."""
    a, clk_a = _monitor()
    for _ in range(8):
        clk_a.advance(STEP)     # 8 * 1/64 == 0.125 exactly
        a.update(125)
    b, clk_b = _monitor()
    clk_b.advance(PERIOD)
    b.update(1000)
    assert a._rate == b._rate   # both windows closed identically
    assert a.rate == pytest.approx(b.rate)


def test_ema_weights_recent_samples():
    """A burst followed by a trickle moves the EMA toward the new level
    geometrically (alpha per full period)."""
    m, clk = _monitor()
    for _ in range(40):
        clk.advance(PERIOD)
        m.update(10_000)        # 80 kB/s
    fast = m.rate
    for _ in range(5):
        clk.advance(PERIOD)
        m.update(100)           # collapse to 800 B/s
    assert m.rate < fast * 0.3
    assert m.rate > 800         # but not yet fully converged


# ------------------------------------------------------------ idle decay

def test_idle_decay_converges_to_zero():
    """With no updates, ``rate`` decays geometrically per elapsed period
    instead of freezing at the last burst — and reading it does not
    mutate the EMA (no self-accelerating decay)."""
    m, clk = _monitor()
    for _ in range(40):
        clk.advance(PERIOD)
        m.update(10_000)
    busy = m.rate
    assert busy == pytest.approx(80_000, rel=0.05)
    clk.advance(5 * PERIOD)     # 5 idle periods
    idle5 = m.rate
    assert idle5 < busy * 0.5
    assert m.rate == pytest.approx(idle5)     # repeated reads identical
    clk.advance(45 * PERIOD)    # 50 idle periods total
    assert m.rate < busy * 0.001
    # a new burst recovers (the update path was untouched by the reads)
    for _ in range(40):
        clk.advance(PERIOD)
        m.update(10_000)
    assert m.rate == pytest.approx(80_000, rel=0.05)


def test_rate_inside_first_period_is_last_ema():
    """Within one sample period of the last closed window the EMA is
    returned as-is (no decay, no partial-window fold)."""
    m, clk = _monitor()
    clk.advance(PERIOD)
    m.update(1000)
    ema = m._rate
    clk.advance(PERIOD / 2)
    assert m.rate == ema


# -------------------------------------------------- startup / limit edges

def test_limit_at_startup_grants_one_period_burst():
    """The monotonic-clock edge at startup: at t == start (zero elapsed)
    the budget is one sample period's allowance, not 0 — otherwise every
    fresh rate-limited connection's first packet would always back off."""
    m, clk = _monitor()
    assert m.limit(500, 10_000) == 500          # one period = 1250 bytes
    assert m.limit(5000, 10_000) == 1250        # capped at the burst
    # unlimited rate passes through untouched, even at t == start
    assert m.limit(12345, None) == 12345
    assert m.limit(12345, 0) == 12345


def test_limit_enforces_average_rate():
    """Total transfer stays within max_rate * elapsed (+ the one-period
    startup burst) when the caller obeys limit() — and is not starved."""
    m, clk = _monitor()
    max_rate = 10_000
    sent = 0
    steps = 1000
    for _ in range(steps):
        allowed = m.limit(400, max_rate)
        if allowed:
            m.update(allowed)
            sent += allowed
        clk.advance(STEP)
    elapsed = steps * STEP
    assert sent <= max_rate * (elapsed + PERIOD) + 400
    assert sent >= max_rate * elapsed * 0.9


def test_update_at_exact_period_boundary():
    """elapsed == period closes the sample window (>= comparison): the
    sample state resets and the EMA folds the full sample in."""
    m, clk = _monitor()
    clk.advance(PERIOD)
    m.update(300)
    assert m._sample_bytes == 0                 # window closed
    assert m._rate == pytest.approx(0.25 * (300 / PERIOD))


def test_status_reports_totals_and_decayed_rate():
    m, clk = _monitor()
    clk.advance(PERIOD)
    m.update(1000)
    clk.advance(1.0 - PERIOD)
    st = m.status()
    assert st["bytes"] == 1000
    assert st["duration_s"] == pytest.approx(1.0)
    assert st["avg_rate"] == pytest.approx(1000.0)
    assert st["inst_rate"] == m.rate            # decayed, not frozen EMA
