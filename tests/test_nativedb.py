"""Native C++ KV engine: KVStore-interface conformance, crash safety,
compaction, LogDB file compatibility, and a live node running on it
(SURVEY §2.9-3's native storage backend)."""

import asyncio
import os

import pytest

from cometbft_tpu.storage.db import LogDB
from cometbft_tpu.storage.nativedb import NativeDB

pytestmark = pytest.mark.timeout(120)


def test_basic_ops_and_iteration(tmp_path):
    db = NativeDB(str(tmp_path / "kv.db"))
    for i in range(100):
        db.set(b"k%03d" % i, b"v%d" % i)
    db.delete(b"k050")
    assert db.get(b"k000") == b"v0"
    assert db.get(b"k050") is None
    assert db.get(b"missing") is None
    assert db.size() == 99
    rng = list(db.iterate(b"k048", b"k053"))
    assert [k for k, _ in rng] == [b"k048", b"k049", b"k051", b"k052"]
    # open-ended iteration is sorted
    allk = [k for k, _ in db.iterate()]
    assert allk == sorted(allk)
    db.close()


def test_batch_is_atomic_and_survives_reopen(tmp_path):
    path = str(tmp_path / "kv.db")
    db = NativeDB(path)
    db.set_batch({b"a": b"1", b"b": b"2", b"c": None})
    db.set(b"c", b"3")
    db.set_batch({b"c": None, b"d": b"4"})
    db.close()
    db2 = NativeDB(path)
    assert db2.get(b"a") == b"1" and db2.get(b"b") == b"2"
    assert db2.get(b"c") is None and db2.get(b"d") == b"4"
    db2.close()


def test_torn_tail_truncated(tmp_path):
    path = str(tmp_path / "kv.db")
    db = NativeDB(path)
    db.set(b"good", b"record")
    db.close()
    with open(path, "ab") as f:
        f.write(b"\xde\xad\xbe\xefgarbage")
    db2 = NativeDB(path)
    assert db2.get(b"good") == b"record"
    assert db2.size() == 1
    db2.set(b"after", b"crash")
    db2.close()
    db3 = NativeDB(path)
    assert db3.get(b"after") == b"crash"
    db3.close()


def test_compaction_shrinks_log(tmp_path):
    path = str(tmp_path / "kv.db")
    db = NativeDB(path)
    blob = b"x" * 4096
    for round_ in range(3):
        for i in range(200):
            db.set(b"key%03d" % i, blob)
    size_before_close = os.path.getsize(path)
    # 3 rounds x 200 x 4k = ~2.4 MB written; live set is ~800 KB, so
    # compaction must have rewritten the log at least once
    assert size_before_close < 2 * 200 * (4096 + 32)
    db.close()
    db2 = NativeDB(path)
    assert db2.size() == 200
    assert db2.get(b"key000") == blob
    db2.close()


def test_file_compatible_with_logdb(tmp_path):
    path = str(tmp_path / "kv.db")
    ldb = LogDB(path)
    ldb.set(b"from", b"python")
    ldb.set_batch({b"batch": b"write", b"gone": None})
    ldb.close()
    ndb = NativeDB(path)
    assert ndb.get(b"from") == b"python"
    assert ndb.get(b"batch") == b"write"
    ndb.set(b"back", b"native")
    ndb.close()
    ldb2 = LogDB(path)
    assert ldb2.get(b"back") == b"native"
    ldb2.close()


def test_node_runs_on_native_backend(tmp_path):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config
    from cometbft_tpu.config import test_consensus_config as _tcc
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    def cfg():
        c = Config(consensus=_tcc())
        c.p2p.laddr = "tcp://127.0.0.1:0"
        c.rpc.laddr = "tcp://127.0.0.1:0"
        c.storage.db_backend = "native"
        # this test exercises the DB backend, not device warmup — a
        # warmup compile left running on the device-owner thread makes
        # LATER tests' dispatches silently host-fallback (the bounded
        # wait sees an in-flight future)
        c.base.device_warmup = False
        return c

    async def main():
        pvs = [MockPV.from_secret(b"ndb%d" % i) for i in range(3)]
        doc = GenesisDoc(chain_id="ndb-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            n = await Node.create(doc, KVStoreApplication(),
                                  priv_validator=pv, config=cfg(),
                                  node_key=NodeKey.from_secret(b"nk%d" % i),
                                  home=str(tmp_path / f"n{i}"),
                                  name=f"ndb{i}")
            nodes.append(n)
            await n.start()
        for i, a in enumerate(nodes):
            for b in nodes[i + 1:]:
                await a.dial_peer(b.listen_addr, persistent=True)
        try:
            async def reach(h):
                while not all(n.height() >= h for n in nodes):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(4), 60)
            hashes = {n.block_store.load_block(3).hash() for n in nodes}
            assert len(hashes) == 1
            assert os.path.exists(tmp_path / "n0" / "data" /
                                  "blockstore.db")
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_native_merkle_root_matches_python():
    """The C++ RFC-6962 root (kv_merkle_root) is byte-identical to the
    Python tree across sizes, including the power-of-two split edges."""
    import hashlib

    from cometbft_tpu.crypto import merkle

    lib = merkle._native_root_fn()
    assert lib is not None, "native kvstore lib should build on this image"
    import ctypes

    def native_root(items):
        buf = b"".join(items)
        offs = (ctypes.c_uint64 * (len(items) + 1))()
        pos = 0
        for i, it in enumerate(items):
            offs[i] = pos
            pos += len(it)
        offs[len(items)] = pos
        out = ctypes.create_string_buffer(32)
        lib.kv_merkle_root(buf, offs, len(items), out)
        return out.raw

    for n in (0, 1, 2, 3, 63, 64, 65, 200, 1000):
        items = [hashlib.sha256(b"%d" % i).digest()[: (i % 40) + 1]
                 for i in range(n)]
        assert native_root(items) == merkle.hash_from_byte_slices(items), n
    # and the dispatching wrapper agrees with the pure tree
    big = [b"leaf-%d" % i for i in range(500)]
    assert merkle.hash_from_byte_slices_fast(big) == \
        merkle.hash_from_byte_slices(big)
