"""Height-timeline attribution (``libs/timeline``): folding the flight
recorder into per-height commit-latency waterfalls — phase ordering,
exact bucket decomposition, multi-round and aggregate-catch-up edge
cases, eviction tolerance, interleaved heights — plus the emitter attr
contract (every consensus record stamps node+height, steps stamp round)
checked against a live in-proc ensemble, and the /consensus_timeline
projection."""

import asyncio

import pytest

from cometbft_tpu.libs import timeline, tracing

pytestmark = pytest.mark.timeout(120)

S = 1_000_000_000          # 1 virtual second, in ns
WALL = 1_800_000_000 * S   # arbitrary wall epoch for synthetic rings


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.fixture(autouse=True)
def _tracer_hygiene():
    tracing.configure(enabled=False, ring_size=8192)
    tracing.clear()
    yield
    tracing.configure(enabled=False, ring_size=8192)
    tracing.clear()


# ------------------------------------------------- synthetic ring records

_ids = iter(range(1, 1 << 20))


def ev(sub, name, t, **attrs):
    return ("event", next(_ids), 0, sub, name, WALL + t, t, t, attrs)


def sp(sub, name, t0, t1, **attrs):
    return ("span", next(_ids), 0, sub, name, WALL + t0, t0, t1, attrs)


def height_records(node="n0", h=5, t0=0, round_=0):
    """A complete, well-formed height: NewHeight at t0, proposal at
    +1s, parts at +2s, +2/3 prevotes at +3s, +2/3 precommits at +4s,
    commit at +5s."""
    a = dict(node=node, height=h)
    return [
        sp("consensus", "step", t0, t0 + 1 * S,
           step="NewHeight", round=round_, **a),
        ev("consensus", "proposal_received", t0 + 1 * S, round=round_, **a),
        sp("consensus", "step", t0 + 1 * S, t0 + 3 * S,
           step="Propose", round=round_, **a),
        ev("consensus", "block_assembled", t0 + 2 * S, **a),
        sp("consensus", "step", t0 + 3 * S, t0 + 4 * S,
           step="Precommit", round=round_, **a),
        sp("consensus", "step", t0 + 4 * S, t0 + 5 * S,
           step="Commit", round=round_, **a),
        ev("consensus", "commit", t0 + 5 * S, round=round_, **a),
    ]


# ---------------------------------------------------------- basic folding


def test_basic_waterfall_phases_ordered_and_buckets_sum_to_total():
    wfs = timeline.fold(height_records())
    assert len(wfs) == 1
    wf = wfs[0]
    assert wf["node"] == "n0" and wf["height"] == 5
    assert wf["complete"] and not wf["catchup"]
    assert wf["total_s"] == 5.0
    # all five phases present, in taxonomy order, contiguous
    assert [p["phase"] for p in wf["phases"]] == list(timeline.PHASES)
    cursor = 0.0
    for p in wf["phases"]:
        assert p["start_s"] == cursor
        cursor += p["dur_s"]
    assert cursor == wf["total_s"]
    # marks are height-relative seconds
    assert wf["marks"]["proposal_received"] == 1.0
    assert wf["marks"]["parts_complete"] == 2.0
    assert wf["marks"]["prevote_23"] == 3.0
    assert wf["marks"]["precommit_23"] == 4.0
    assert wf["marks"]["commit"] == 5.0
    # buckets decompose the same total exactly
    assert sum(wf["buckets"].values()) == pytest.approx(wf["total_s"])
    assert set(wf["buckets"]) == set(timeline.BUCKETS)


def test_abci_wal_dispatch_buckets_clip_into_budget():
    recs = height_records()
    # 0.5s of app time inside the height, node-attributed
    recs.append(sp("abci", "call", 4 * S, int(4.5 * S),
                   method="finalize_block", height=5, node="n0"))
    # a wal fsync joined on height only
    recs.append(ev("wal", "fsync", int(4.6 * S), height=5,
                   dur_us=100_000))
    # a verify micro-batch whose window overlaps heights 4..6, plus a
    # BLS aggregate pairing check stamped with this height exactly
    recs.append(sp("crypto.sched", "dispatch", 3 * S, int(3.25 * S),
                   h_lo=4, h_hi=6, n=64))
    recs.append(sp("crypto.agg", "verify", int(3.5 * S), int(3.6 * S),
                   height=5, lanes=7, ok=True))
    wf = timeline.fold(recs)[0]
    assert wf["buckets"]["app"] == pytest.approx(0.5)
    assert wf["buckets"]["wal"] == pytest.approx(0.1)
    assert wf["buckets"]["verify"] == pytest.approx(0.35)
    assert wf["marks"]["finalize"] == pytest.approx(4.5)
    assert wf["marks"]["fsync"] == pytest.approx(4.6)
    assert sum(wf["buckets"].values()) == pytest.approx(wf["total_s"])


def test_oversized_bucket_values_never_exceed_total():
    recs = height_records()
    # an absurd fsync duration (clock glitch / bad attr) must clip
    recs.append(ev("wal", "fsync", int(4.5 * S), height=5,
                   dur_us=3_600_000_000))
    wf = timeline.fold(recs)[0]
    assert sum(wf["buckets"].values()) == pytest.approx(wf["total_s"])
    assert wf["buckets"]["wal"] <= wf["total_s"]


# ------------------------------------------------------------- edge cases


def test_multi_round_height_uses_commit_round_marks():
    """A height that failed round 0 and committed in round 1: the vote-
    phase marks must come from the committing round's step entries, not
    the stale round-0 ones."""
    a = dict(node="n0", height=9)
    recs = [
        sp("consensus", "step", 0, 1 * S, step="NewHeight", round=0, **a),
        ev("consensus", "proposal_received", 1 * S, round=0, **a),
        ev("consensus", "block_assembled", 2 * S, **a),
        sp("consensus", "step", 3 * S, 4 * S, step="Precommit",
           round=0, **a),
        # round 0 dies; round 1 runs the ladder again
        sp("consensus", "step", 5 * S, 6 * S, step="NewRound",
           round=1, **a),
        sp("consensus", "step", 7 * S, 8 * S, step="Precommit",
           round=1, **a),
        sp("consensus", "step", 8 * S, 9 * S, step="Commit",
           round=1, **a),
        ev("consensus", "commit", 9 * S, round=1, **a),
    ]
    wf = timeline.fold(recs)[0]
    assert wf["rounds"] == 1 and wf["complete"]
    assert wf["marks"]["prevote_23"] == 7.0     # round 1's, not 3.0
    assert wf["marks"]["precommit_23"] == 8.0
    assert wf["total_s"] == 9.0
    cursor = 0.0
    for p in wf["phases"]:
        assert p["start_s"] == cursor
        cursor += p["dur_s"]
    assert cursor == wf["total_s"]


def test_catchup_commit_skips_vote_phases():
    """An aggregate/blocksync catch-up commit never enters Prevote or
    Precommit: the waterfall folds with the vote marks absent rather
    than inventing zero-length phases from stale data."""
    a = dict(node="n3", height=12)
    recs = [
        sp("consensus", "step", 0, 1 * S, step="NewHeight", round=0, **a),
        ev("consensus", "proposal_received", 1 * S, round=0, **a),
        ev("consensus", "block_assembled", 2 * S, **a),
        ev("consensus", "commit", 3 * S, round=0, catchup=True, **a),
    ]
    wf = timeline.fold(recs)[0]
    assert wf["catchup"] and wf["complete"]
    assert [p["phase"] for p in wf["phases"]] == \
        ["propose", "gossip", "prevote"]
    assert wf["marks"]["prevote_23"] is None
    assert wf["marks"]["precommit_23"] is None
    assert wf["total_s"] == 3.0
    assert sum(wf["buckets"].values()) == pytest.approx(3.0)


def test_evicted_prefix_and_incomplete_heights_degrade_gracefully():
    # eviction took the NewHeight step and the proposal event: the
    # height anchors at its earliest surviving record
    a = dict(node="n0", height=7)
    partial = [
        sp("consensus", "step", 10 * S, 11 * S, step="Precommit",
           round=0, **a),
        sp("consensus", "step", 11 * S, 12 * S, step="Commit",
           round=0, **a),
        ev("consensus", "commit", 12 * S, round=0, **a),
    ]
    wf = timeline.fold(partial)[0]
    assert wf["complete"] and wf["total_s"] == 2.0
    assert wf["marks"]["proposal_received"] is None
    assert [p["phase"] for p in wf["phases"]] == \
        ["propose", "precommit", "commit"]
    # a height still in flight (no commit yet) is not "complete" and
    # measures up to its last record
    b = dict(node="n0", height=8)
    inflight = [
        sp("consensus", "step", 20 * S, 21 * S, step="NewHeight",
           round=0, **b),
        ev("consensus", "proposal_received", 21 * S, round=0, **b),
    ]
    wf2 = timeline.fold(inflight)[0]
    assert not wf2["complete"]
    assert wf2["total_s"] == 1.0


def test_interleaved_heights_and_nodes_fold_independently():
    recs = []
    # two nodes x two heights, records interleaved as a shared ring
    # would hold them
    quads = [height_records("a", 5, 0), height_records("b", 5, S // 2),
             height_records("a", 6, 6 * S), height_records("b", 6, 7 * S)]
    for i in range(max(len(q) for q in quads)):
        for q in quads:
            if i < len(q):
                recs.append(q[i])
    wfs = timeline.fold(recs)
    assert [(w["node"], w["height"]) for w in wfs] == \
        [("a", 5), ("b", 5), ("a", 6), ("b", 6)]
    assert all(w["complete"] and w["total_s"] == 5.0 for w in wfs)
    # node/height filters and the per-node limit
    assert [(w["node"], w["height"])
            for w in timeline.fold(recs, node="a")] == [("a", 5), ("a", 6)]
    assert [(w["node"], w["height"])
            for w in timeline.fold(recs, height=6)] == [("a", 6), ("b", 6)]
    newest = timeline.fold(recs, limit=1)
    assert [(w["node"], w["height"]) for w in newest] == \
        [("a", 6), ("b", 6)]


def test_attr_contract_violations_are_skipped_not_crashed():
    recs = height_records()
    recs.append(ev("consensus", "commit", 99 * S, height=77))   # no node
    recs.append(ev("consensus", "commit", 99 * S, node="x"))    # no height
    recs.append(sp("abci", "call", 0, S, method="echo"))        # no height
    recs.append(sp("crypto.sched", "dispatch", 0, S, h_lo=0, h_hi=0))
    wfs = timeline.fold(recs)
    assert [(w["node"], w["height"]) for w in wfs] == [("n0", 5)]


# ----------------------------------------------------------- phase stats


def test_phase_stats_percentiles_deterministic_and_skip_incomplete():
    recs = []
    for i in range(10):
        recs += height_records("n0", 10 + i, i * 10 * S)
    # one in-flight height must not contribute samples
    recs.append(sp("consensus", "step", 200 * S, 201 * S, step="NewHeight",
                   round=0, node="n0", height=99))
    st = timeline.phase_stats(timeline.fold(recs, limit=0))
    assert st["samples"] == 10
    assert st["phases"]["total"] == {"n": 10, "p50_s": 5.0, "p99_s": 5.0}
    for p in timeline.PHASES:
        assert st["phases"][p]["n"] == 10
        assert st["phases"][p]["p50_s"] == 1.0
    for b in timeline.BUCKETS:
        assert st["buckets"][b]["n"] == 10
    # nearest-rank: p50 of [1..10] is 5, p99 is 10 (no interpolation)
    xs = sorted(float(i) for i in range(1, 11))
    assert timeline._pctl(xs, 0.50) == 5.0
    assert timeline._pctl(xs, 0.99) == 10.0
    assert timeline._pctl([3.0], 0.99) == 3.0
    empty = timeline.phase_stats([])
    assert empty["samples"] == 0
    assert empty["phases"]["total"]["p50_s"] is None


# ----------------------------------- live attr contract + RPC projection


def test_live_ensemble_attr_contract_and_timeline_projection():
    """Every consensus record a real 4-validator ensemble emits carries
    node+height, step spans carry round — the contract fold() keys on —
    and the folded waterfalls + /consensus_timeline projection agree."""
    from cometbft_tpu.testing import make_inproc_network

    async def main():
        tracing.configure(enabled=True, ring_size=32768)
        net = await make_inproc_network(4)
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
        finally:
            await net.stop()
        return tracing.snapshot()

    recs = run(main())
    cons = [r for r in recs if r[3] == "consensus"]
    assert cons, "no consensus records emitted"
    for r in cons:
        attrs = r[8]
        assert attrs.get("node") is not None, r
        assert attrs.get("height") is not None, r
        if r[4] == "step":
            assert "round" in attrs and "step" in attrs, r
    wfs = timeline.fold(recs)
    done = [w for w in wfs if w["complete"]]
    # 4 nodes x >=2 heights committed
    assert len(done) >= 8
    for wf in done:
        assert [p["phase"] for p in wf["phases"]] == list(timeline.PHASES)
        assert sum(wf["buckets"].values()) == pytest.approx(wf["total_s"])
        cursor = 0.0
        for p in wf["phases"]:
            # start/dur are rounded to 1us independently: contiguous
            # within accumulated rounding, not bit-exact
            assert p["start_s"] == pytest.approx(cursor, abs=1e-5)
            cursor = p["start_s"] + p["dur_s"]
    st = timeline.phase_stats(wfs)
    assert st["samples"] == len(done)
    assert st["phases"]["total"]["p50_s"] > 0

    # the RPC projection serves the same fold off the event loop
    from cometbft_tpu.rpc import core as rpc_core

    out = run(rpc_core.consensus_timeline(None, height=0, n=4))
    assert out["enabled"] is True
    assert out["phases"] == list(timeline.PHASES)
    assert out["buckets"] == list(timeline.BUCKETS)
    assert out["waterfalls"]
    h2 = run(rpc_core.consensus_timeline(None, height=2))
    assert {w["height"] for w in h2["waterfalls"]} == {2}
