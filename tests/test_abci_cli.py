"""abci subcommand group — the reference's standalone abci-cli
(``abci/cmd/abci-cli/abci-cli.go``): one-shot verbs, batch scripts, and
the conformance sequence against the example kvstore server."""

import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.timeout(120)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PORT = 29360
ADDR = f"127.0.0.1:{PORT}"


def _cli(*args, stdin=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "cometbft_tpu", "abci", *args],
        input=stdin, capture_output=True, text=True, env=env, cwd=REPO,
        timeout=60)


@pytest.fixture()
def kvstore_server():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "abci", "kvstore",
         "--port", str(PORT)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    # wait for the listening line (select so a silent hang fails fast)
    import select

    deadline = time.monotonic() + 30
    while True:
        assert time.monotonic() < deadline and proc.poll() is None
        ready, _, _ = select.select([proc.stdout], [], [], 1.0)
        if ready and "listening" in proc.stdout.readline():
            break
    yield proc
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_abci_cli_oneshots(kvstore_server):
    r = _cli("echo", "--address", ADDR, "hello-abci")
    assert r.returncode == 0 and "hello-abci" in r.stdout

    r = _cli("info", "--address", ADDR)
    assert r.returncode == 0 and "kvstore" in r.stdout.lower()

    r = _cli("check_tx", "--address", ADDR, '"ck=cv"')
    assert r.returncode == 0 and "code: 0" in r.stdout

    r = _cli("check_tx", "--address", ADDR, "0xdeadbeef")
    assert r.returncode == 0 and "code: 0" not in r.stdout

    r = _cli("finalize_block", "--address", ADDR, '"fk=fv"')
    assert r.returncode == 0 and "app_hash" in r.stdout
    r = _cli("commit", "--address", ADDR)
    assert r.returncode == 0

    r = _cli("query", "--address", ADDR, '"fk"')
    assert r.returncode == 0 and "value: fv" in r.stdout

    r = _cli("prepare_proposal", "--address", ADDR, '"pk=pv"')
    assert r.returncode == 0 and "tx:" in r.stdout


def test_abci_cli_batch_and_console(kvstore_server):
    script = """
echo batch-hello
check_tx "bk=bv"
finalize_block "bk=bv"
commit
query "bk"
"""
    r = _cli("batch", "--address", ADDR, stdin=script)
    assert r.returncode == 0, r.stderr
    assert "batch-hello" in r.stdout and "value: bv" in r.stdout

    # console is the same loop with prompts; errors don't kill it
    r = _cli("console", "--address", ADDR,
             stdin='echo hi\nbogus_verb\nquit\n')
    assert "hi" in r.stdout and "unknown command" in r.stderr


def test_abci_cli_conformance(kvstore_server):
    r = _cli("test", "--address", ADDR)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK: 0 failure(s)" in r.stdout and "FAIL" not in r.stdout
