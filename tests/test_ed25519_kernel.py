"""Ed25519 kernel tests: scalar mod-L, Edwards ops, and full ZIP-215 verify.

Ground truth is the pure-Python oracle (RFC-8032-checked) plus signatures
produced independently by the `cryptography` library.
"""

import hashlib

import jax
import numpy as np
import pytest

from cometbft_tpu.crypto import _ed25519_py as ref

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey)
except ImportError:
    class Ed25519PrivateKey:
        """Image has no `cryptography`: same tiny API over the pure-Python
        RFC-8032 oracle, which stays independent of the kernel under
        test (it shares no code with ops/)."""

        def __init__(self, seed: bytes):
            self._seed = seed

        @classmethod
        def generate(cls) -> "Ed25519PrivateKey":
            return cls(rng.bytes(32))

        def public_key(self) -> "Ed25519PrivateKey":
            return self

        def public_bytes_raw(self) -> bytes:
            return ref.public_key_from_seed(self._seed)

        def sign(self, msg: bytes) -> bytes:
            return ref.sign(self._seed, msg)


# Full kernel execution over many shapes (~3 min on a small CPU box) —
# tier-2 with the other kernel suites (test_kernel_layouts, test_rlc);
# tier-1 keeps the kernel golden/routing pins in test_batch_verifier.
pytestmark = pytest.mark.slow
from cometbft_tpu.ops import ed25519, edwards, fe, scalar, sha512

rng = np.random.default_rng(42)
L = scalar.L_INT
P = fe.P_INT

j_reduce512 = jax.jit(scalar.reduce512)
j_lt_l = jax.jit(scalar.lt_l)
j_nibbles = jax.jit(lambda b: scalar.nibbles(scalar.bytes32_to_limbs(b)))


def bytes_arr(bs_list):
    return np.stack([np.frombuffer(b, np.uint8) for b in bs_list]).astype(np.int32)


# ---------------------------------------------------------------- scalar mod L

def test_reduce512():
    vals = [0, 1, L - 1, L, L + 1, 2**256 - 1, 2**512 - 1, 2**511, 13 * L**2 + 7]
    vals += [int.from_bytes(rng.bytes(64), "little") for _ in range(55)]
    arr = bytes_arr([v.to_bytes(64, "little") for v in vals])
    out = np.asarray(j_reduce512(arr))
    for i, v in enumerate(vals):
        got = fe.int_from_limbs(out[i])
        assert got < 2**256 and got % L == v % L, (i, v)


def test_lt_l_and_nibbles():
    vals = [0, 1, L - 1, L, L + 1, 2**252, 2**256 - 1]
    vals += [int.from_bytes(rng.bytes(32), "little") for _ in range(57)]
    arr = bytes_arr([v.to_bytes(32, "little") for v in vals])
    lt = np.asarray(j_lt_l(scalar.bytes32_to_limbs(arr)))
    nib = np.asarray(j_nibbles(arr))
    for i, v in enumerate(vals):
        assert bool(lt[i]) == (v < L), v
        assert sum(int(nib[i, n]) << (4 * n) for n in range(64)) == v


# ---------------------------------------------------------------- edwards ops

def rand_points(n):
    pts = []
    while len(pts) < n:
        enc = bytearray(rng.bytes(32))
        pt = ref.pt_decompress_zip215(bytes(enc))
        if pt is not None:
            pts.append((bytes(enc), pt))
    return pts


def to_ext_batch(pts):
    xs = np.stack([fe.limbs_from_int(p[0] * pow(p[2], P - 2, P) % P) for p in pts])
    ys = np.stack([fe.limbs_from_int(p[1] * pow(p[2], P - 2, P) % P) for p in pts])
    ts = np.stack([fe.limbs_from_int(
        (p[0] * pow(p[2], P - 2, P) % P) * (p[1] * pow(p[2], P - 2, P) % P) % P)
        for p in pts])
    ones = np.stack([fe.limbs_from_int(1)] * len(pts))
    return edwards.Ext(xs, ys, ones, ts)


def test_decompress_add_dbl_compress():
    pairs = rand_points(32)
    encs = bytes_arr([e for e, _ in pairs])
    pts = [p for _, p in pairs]

    dev_pts, ok = jax.jit(edwards.decompress_zip215)(encs)
    assert np.asarray(ok).all()
    # compress(decompress(e)) == canonical encoding of the oracle point
    enc2 = np.asarray(jax.jit(edwards.compress)(dev_pts))
    for i in range(32):
        assert bytes(enc2[i].astype(np.uint8)) == ref.pt_compress(pts[i])

    # dbl and add against oracle
    d = np.asarray(jax.jit(lambda p: edwards.compress(edwards.dbl(p)))(dev_pts))
    q = to_ext_batch(pts[::-1])
    s = np.asarray(jax.jit(
        lambda p, q: edwards.compress(edwards.add_cached(p, edwards.cache(q))))(
        dev_pts, q))
    for i in range(32):
        assert bytes(d[i].astype(np.uint8)) == ref.pt_compress(ref.pt_double(pts[i]))
        assert bytes(s[i].astype(np.uint8)) == ref.pt_compress(
            ref.pt_add(pts[i], pts[31 - i]))


def test_noncanonical_decompress():
    # y >= p encodings (ZIP-215 must accept): y_enc = y + p for y in {1, 2}
    encs = []
    for y in (1, 2, 0):
        encs.append((y + P).to_bytes(32, "little"))
    # x=0 with sign bit: -0 encoding of identity
    encs.append((1 | (1 << 255)).to_bytes(32, "little"))
    arr = bytes_arr(encs)
    pts, ok = jax.jit(edwards.decompress_zip215)(arr)
    okn = np.asarray(ok)
    for i, e in enumerate(encs):
        oracle_pt = ref.pt_decompress_zip215(e)
        assert bool(okn[i]) == (oracle_pt is not None), e.hex()
        if oracle_pt is not None:
            got = bytes(np.asarray(jax.jit(edwards.compress)(pts))[i].astype(np.uint8))
            assert got == ref.pt_compress(oracle_pt)


# ------------------------------------------------------------------ full verify

def kernel_verify(pubs, sigs, msgs):
    """Host wrapper mirroring what the crypto layer will do."""
    bsz = len(pubs)
    nb = max(sha512.max_blocks_for_len(64 + len(m)) for m in msgs)
    maxlen = max(64 + len(m) for m in msgs)
    hin = np.zeros((bsz, maxlen), np.uint8)
    lens = np.zeros(bsz, np.int64)
    for i, (p, s, m) in enumerate(zip(pubs, sigs, msgs)):
        full = s[:32] + p + m
        hin[i, :len(full)] = np.frombuffer(full, np.uint8)
        lens[i] = len(full)
    blocks, active = sha512.host_pad(hin, lens, nb)
    out = jax.jit(ed25519.verify_padded)(
        bytes_arr(pubs), bytes_arr([s[:32] for s in sigs]),
        bytes_arr([s[32:] for s in sigs]), blocks, active)
    return np.asarray(out)


def make_torsion8():
    """Find a point of exact order 8 with the oracle."""
    while True:
        enc = rng.bytes(32)
        pt = ref.pt_decompress_zip215(enc)
        if pt is None:
            continue
        t = ref.pt_mul(ref.L, pt)
        if not ref.pt_equal(t, ref.IDENTITY) and \
           not ref.pt_equal(ref.pt_mul(4, t), ref.IDENTITY):
            assert ref.pt_equal(ref.pt_mul(8, t), ref.IDENTITY)
            return t


def test_verify_batch_mixed():
    """One batch covering every accept/reject class."""
    pubs, sigs, msgs, expect = [], [], [], []

    def case(p, s, m, want):
        pubs.append(p); sigs.append(s); msgs.append(m); expect.append(want)

    # RFC 8032 vector 2
    seed = bytes.fromhex(
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb")
    case(ref.public_key_from_seed(seed), ref.sign(seed, bytes.fromhex("72")),
         bytes.fromhex("72"), True)

    # valid signatures from the cryptography library, varied message sizes
    for n in (0, 1, 31, 32, 100, 120, 180, 250):
        sk = Ed25519PrivateKey.generate()
        pk = sk.public_key().public_bytes_raw()
        m = rng.bytes(n)
        case(pk, sk.sign(m), m, True)

    # corrupted signature / wrong message / wrong key
    sk = Ed25519PrivateKey.generate()
    pk = sk.public_key().public_bytes_raw()
    m = rng.bytes(80)
    good = sk.sign(m)
    bad_sig = bytearray(good); bad_sig[5] ^= 1
    case(pk, bytes(bad_sig), m, False)
    case(pk, good, m + b"x", False)
    pk2 = Ed25519PrivateKey.generate().public_key().public_bytes_raw()
    case(pk2, good, m, False)

    # S >= L (non-canonical S: reject), S = s + L of a valid sig
    s_int = int.from_bytes(good[32:], "little")
    if s_int + L < 2**256:
        case(pk, good[:32] + (s_int + L).to_bytes(32, "little"), m, False)

    # mixed-order pubkey: A' + T8 accepted under ZIP-215 cofactored verify.
    # The signature must be crafted against the *mixed* encoding (the hash
    # h = H(R || A || M) covers the encoded pubkey bytes).
    t8 = make_torsion8()
    seed2 = rng.bytes(32)
    h0 = hashlib.sha512(seed2).digest()
    a_sc = ref._clamp(h0[:32])
    prefix = h0[32:]
    a_prime = ref.pt_mul(a_sc, ref.BASE)
    mixed = ref.pt_compress(ref.pt_add(a_prime, t8))
    m3 = rng.bytes(50)
    r_sc = ref.sc_reduce64(hashlib.sha512(prefix + m3).digest())
    r_enc = ref.pt_compress(ref.pt_mul(r_sc, ref.BASE))
    k_sc = ref.sc_reduce64(hashlib.sha512(r_enc + mixed + m3).digest())
    sig3 = r_enc + ((r_sc + k_sc * a_sc) % L).to_bytes(32, "little")
    assert ref.verify_zip215(mixed, m3, sig3)     # oracle agrees: cofactored
    case(mixed, sig3, m3, True)

    # non-canonical identity pubkey (y = 1 + p): [S]B == R makes it valid
    r_scalar = int.from_bytes(rng.bytes(32), "little") % L
    r_enc = ref.pt_compress(ref.pt_mul(r_scalar, ref.BASE))
    ident_nc = (1 + P).to_bytes(32, "little")
    sig_id = r_enc + r_scalar.to_bytes(32, "little")
    assert ref.verify_zip215(ident_nc, b"whatever", sig_id)
    case(ident_nc, sig_id, b"whatever", True)

    # small-order R (torsion) with identity A: [S]B - R must be torsion: S=0, R=T8
    sig_t = ref.pt_compress(t8) + (0).to_bytes(32, "little")
    assert ref.verify_zip215(ident_nc, b"x", sig_t)
    case(ident_nc, sig_t, b"x", True)

    # undecodable A (non-square x^2): find one
    while True:
        cand = bytearray(rng.bytes(32)); cand[31] &= 127
        if ref.pt_decompress_zip215(bytes(cand)) is None:
            case(bytes(cand), good, m, False)
            break

    # pad batch to a fixed size with valid sigs so shapes bucket evenly
    while len(pubs) < 24:
        sk = Ed25519PrivateKey.generate()
        mm = rng.bytes(33)
        case(sk.public_key().public_bytes_raw(), sk.sign(mm), mm, True)

    got = kernel_verify(pubs, sigs, msgs)
    for i in range(len(pubs)):
        # oracle cross-check on every lane
        assert ref.verify_zip215(pubs[i], msgs[i], sigs[i]) == expect[i], i
        assert bool(got[i]) == expect[i], f"lane {i}: kernel={got[i]} want={expect[i]}"


def test_verify_random_roundtrip_larger():
    bsz = 64
    pubs, sigs, msgs = [], [], []
    flip = rng.integers(0, 3, size=bsz)
    for i in range(bsz):
        sk = Ed25519PrivateKey.generate()
        pk = sk.public_key().public_bytes_raw()
        m = rng.bytes(int(rng.integers(0, 150)))
        s = bytearray(sk.sign(m))
        if flip[i] == 1:
            s[int(rng.integers(0, 64))] ^= 1 << int(rng.integers(0, 8))
        elif flip[i] == 2:
            m = m + b"!"
        pubs.append(pk); sigs.append(bytes(s)); msgs.append(m)
    got = kernel_verify(pubs, sigs, msgs)
    for i in range(bsz):
        want = ref.verify_zip215(pubs[i], msgs[i], sigs[i])
        assert bool(got[i]) == want, i
