"""Light client over the RPC provider + the verified light proxy
(reference: ``light/provider/http``, ``light/proxy``)."""

import asyncio

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import Config
from cometbft_tpu.config import test_consensus_config as _tcc
from cometbft_tpu.light import Client, TrustOptions
from cometbft_tpu.light.proxy import run_light_proxy
from cometbft_tpu.light.rpc_provider import RPCProvider
from cometbft_tpu.node import Node
from cometbft_tpu.p2p import NodeKey
from cometbft_tpu.rpc import HTTPClient
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.priv_validator import MockPV

# spawns a full node + light client over live RPC — tier-2 with the
# other net suites.
pytestmark = [pytest.mark.timeout(150), pytest.mark.slow]

PERIOD = 3600 * 1_000_000_000


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _config() -> Config:
    cfg = Config(consensus=_tcc())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    return cfg


async def _net(n=3):
    pvs = [MockPV.from_secret(b"lpx%d" % i) for i in range(n)]
    doc = GenesisDoc(chain_id="lpx-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])
    nodes = []
    for i, pv in enumerate(pvs):
        node = await Node.create(
            doc, KVStoreApplication(), priv_validator=pv, config=_config(),
            node_key=NodeKey.from_secret(b"lk%d" % i), name=f"lpx{i}")
        nodes.append(node)
        await node.start()
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            await a.dial_peer(b.listen_addr, persistent=True)
    return nodes


async def _stop(nodes):
    for n in nodes:
        try:
            await n.stop()
        except Exception:
            pass


def test_light_client_over_rpc_provider():
    async def main():
        nodes = await _net(3)
        try:
            async def reach(h):
                while not all(n.height() >= h for n in nodes):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(6), 60)
            trust_h = 2
            trust_hash = nodes[0].block_store.load_block(trust_h).hash()
            primary = RPCProvider(*nodes[0].rpc_addr, "primary")
            witness = RPCProvider(*nodes[1].rpc_addr, "witness")
            client = Client("lpx-net",
                            TrustOptions(PERIOD, trust_h, trust_hash),
                            primary, witnesses=[witness], backend="cpu")
            lb = await client.verify_light_block_at_height(5)
            assert lb.header.hash() == \
                nodes[0].block_store.load_block(5).hash()
            # update() follows the moving chain tip
            tip = await client.update()
            assert tip.height >= 5
        finally:
            await _stop(nodes)
        return True

    assert run(main())


def test_light_proxy_serves_verified_routes():
    async def main():
        nodes = await _net(3)
        try:
            async def reach(h):
                while not all(n.height() >= h for n in nodes):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(5), 60)
            trust_h = 2
            trust_hash = nodes[0].block_store.load_block(trust_h).hash()
            client = Client(
                "lpx-net", TrustOptions(PERIOD, trust_h, trust_hash),
                RPCProvider(*nodes[0].rpc_addr, "primary"), backend="cpu")
            server, addr = await run_light_proxy(
                client, HTTPClient(*nodes[0].rpc_addr))
            try:
                cli = HTTPClient(*addr)
                st = await cli.call("status")
                assert st["node_info"]["network"] == "lpx-net"
                h = await cli.call("header", height=4)
                assert h["verified"] is True
                cm = await cli.call("commit", height=4)
                assert cm["commit"]["h"] == 4
                vals = await cli.call("validators", height=4)
                assert vals["total"] == 3
                blk = await cli.call("block", height=4)
                assert blk["verified"] is True
                want = nodes[0].block_store.load_block(4).hash().hex()
                assert blk["block_id"]["hash"]["~b"] == want
            finally:
                await server.close()
        finally:
            await _stop(nodes)
        return True

    assert run(main())


def test_light_proxy_verified_abci_query():
    """Wallet-grade flow: a state query through the proxy is proven
    against the app hash in a light-client-verified header; a tampered
    proof or value is rejected."""

    async def main():
        nodes = await _net(3)
        try:
            cli0 = HTTPClient(*nodes[0].rpc_addr)
            res = await cli0.call("broadcast_tx_commit", tx=b"pq=pv".hex())
            committed_h = res["height"]

            async def reach(h):
                while not all(n.height() >= h for n in nodes):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(committed_h + 2), 60)
            trust_hash = nodes[0].block_store.load_block(1).hash()
            client = Client(
                "lpx-net", TrustOptions(PERIOD, 1, trust_hash),
                RPCProvider(*nodes[0].rpc_addr, "primary"), backend="cpu")
            server, addr = await run_light_proxy(
                client, HTTPClient(*nodes[0].rpc_addr))
            try:
                pcli = HTTPClient(*addr)
                q = await pcli.call("abci_query", path="/key",
                                    data=b"pq".hex())
                assert q["verified"] is True
                assert bytes.fromhex(q["response"]["value"]) == b"pv"
                # absent keys cannot be verified -> explicit error
                from cometbft_tpu.rpc import RPCError

                with pytest.raises(RPCError):
                    await pcli.call("abci_query", path="/key",
                                    data=b"nope".hex())
            finally:
                await server.close()
        finally:
            await _stop(nodes)
        return True

    assert run(main())
