"""Multi-host device mesh smoke test (VERDICT r4 next 6 / SURVEY §2.7
cross-host DCN path): two OS processes bootstrap one jax.distributed
CPU cluster through ``parallel/mesh.py::init_multihost`` and run a
lane-sharded verification step over the shared 4-device global mesh —
the claim "init_multihost exists" becomes an executed path.  On real
TPU pods the same code rides ICI/DCN; the CPU backend exercises the
identical process-coordination and GSPMD machinery."""

import os
import socket
import subprocess
import sys
import time

import pytest

pytestmark = [pytest.mark.timeout(360), pytest.mark.slow]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_mesh_sharded_verify():
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)        # children set their own device count
    procs = [
        subprocess.Popen([sys.executable, CHILD, str(port), str(i)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env, cwd=REPO)
        for i in range(2)
    ]
    deadline = time.monotonic() + 300
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(5, deadline -
                                               time.monotonic()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost children timed out")
        outs.append(out)
    joined = "\n---\n".join(outs)
    if any(p.returncode != 0 for p in procs):
        # a sandboxed box that cannot run the coordination service is an
        # environment limitation, not a framework bug
        if "UNAVAILABLE" in joined or "Failed to connect" in joined or \
                "permission" in joined.lower():
            pytest.skip(f"distributed service unavailable:\n{joined[-800:]}")
        pytest.fail(f"multihost child failed:\n{joined[-3000:]}")
    assert "MULTIHOST_OK 0" in joined and "MULTIHOST_OK 1" in joined
