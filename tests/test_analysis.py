"""bftlint (scripts/analysis) — the rule engine that machine-checks the
repo's concurrency/determinism invariants.

Fixture snippets per rule: a positive hit, a suppressed hit, a
baseline'd hit, and the CLK001 aliased-import case the retired lint.sh
regex provably missed.  Each rule's positive fixture doubles as the
"fails if the rule is deleted" guard from the acceptance criteria."""

from __future__ import annotations

import json
import re
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "scripts"))

from analysis import engine  # noqa: E402
from analysis import rules as rules_mod  # noqa: E402
from analysis.engine import main as cli_main  # noqa: E402


def _scan(tree: dict[str, str], root: Path,
          rule_ids: set[str] | None = None):
    """Write a fixture tree under ``root`` and run the engine on it."""
    for rel, src in tree.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return engine.run_paths([root], root, rule_ids)


def _rules_of(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------- rule: CLK001

def test_clk001_positive_direct_call(tmp_path):
    fs = _scan({"cometbft_tpu/consensus/fx.py": """
        import time

        def age():
            return time.monotonic()
    """}, tmp_path)
    assert _rules_of(fs) == ["CLK001"]


def test_clk001_aliased_import_the_grep_missed(tmp_path):
    """``from time import monotonic as mono`` + ``mono()``: the retired
    lint.sh regex (kept verbatim here) finds NOTHING, the AST rule finds
    both the import and the call."""
    src = textwrap.dedent("""
        from time import monotonic as mono

        def age():
            return mono()
    """)
    grep = re.compile(
        r"asyncio\.sleep\(|time\.monotonic\(|time\.time\(|time\.time_ns\(")
    assert not any(grep.search(line) for line in src.splitlines()), \
        "fixture must be invisible to the old regex"
    fs = _scan({"cometbft_tpu/p2p/fx.py": src}, tmp_path)
    assert _rules_of(fs) == ["CLK001", "CLK001"]
    assert any("imports time.monotonic" in f.message for f in fs)


def test_clk001_loop_time_and_scope(tmp_path):
    fs = _scan({
        # loop.time() — also invisible to the regex
        "cometbft_tpu/mempool/fx.py": """
            import asyncio

            async def due():
                loop = asyncio.get_running_loop()
                return loop.time() + 1.0
        """,
        # crypto/ is NOT clock-managed: same call, no finding
        "cometbft_tpu/crypto/fx.py": """
            import time

            def bench():
                return time.monotonic()
        """,
        # the metrics clock is deliberately allowed
        "cometbft_tpu/node/fx.py": """
            import time

            def observe():
                return time.perf_counter()
        """}, tmp_path)
    assert _rules_of(fs) == ["CLK001"]
    assert fs[0].path == "cometbft_tpu/mempool/fx.py"
    assert "loop.time()" in fs[0].message


def test_clk001_suppressed_with_reason(tmp_path):
    fs = _scan({"cometbft_tpu/node/fx.py": """
        import time

        def boot_stamp():
            return time.time()  # bftlint: disable=CLK001 -- one-shot boot stamp, never compared across virtual time
    """}, tmp_path)
    assert fs == []


# --------------------------------------------------------------- rule: LCK001

def test_lck001_acquire_without_finally(tmp_path):
    fs = _scan({"cometbft_tpu/mempool/fx.py": """
        async def bad(self):
            await self._gate.acquire()
            self.n += 1
            self._gate.release()
    """}, tmp_path)
    assert _rules_of(fs) == ["LCK001"]
    assert "try/finally" in fs[0].message


def test_lck001_blessed_forms_pass(tmp_path):
    fs = _scan({"cometbft_tpu/mempool/fx.py": """
        async def ok_with(self):
            async with self._lock:
                self.n += 1

        async def ok_finally(self):
            await self._gate.acquire()
            try:
                self.n += 1
            finally:
                self._gate.release()

        async def ok_inside_try(self):
            try:
                await self._gate.acquire()
                self.n += 1
            finally:
                self._gate.release()

        def ok_probe(self):
            return self._mu.acquire(blocking=False)

        class Ctx:
            async def __aenter__(self):
                await self._lock.acquire()
                return self
    """}, tmp_path)
    assert fs == []


def test_lck001_await_under_sync_lock(tmp_path):
    fs = _scan({"cometbft_tpu/p2p/fx.py": """
        async def bad(self):
            with self._lock:
                await self.flush()
    """}, tmp_path)
    assert _rules_of(fs) == ["LCK001"]
    assert "synchronous lock" in fs[0].message


def test_lck001_lockish_needs_word_boundary(tmp_path):
    """'block' contains 'lock': block-named context managers must not
    read as sync locks, while lock-spelled names still do."""
    fs = _scan({"cometbft_tpu/mempool/fx.py": """
        async def ok(self):
            with self.open_block():
                await self.flush()

        async def bad(self):
            with self._wlock:
                await self.flush()
    """}, tmp_path)
    assert _rules_of(fs) == ["LCK001"]
    assert fs[0].scope == "bad"


# --------------------------------------------------------------- rule: TSK001

def test_tsk001_discarded_and_unused(tmp_path):
    fs = _scan({"cometbft_tpu/p2p/fx.py": """
        import asyncio

        def bad_discard(self):
            asyncio.create_task(self._run())

        def bad_unused(self):
            t = asyncio.ensure_future(self._run())
            return None
    """}, tmp_path)
    assert _rules_of(fs) == ["TSK001", "TSK001"]


def test_tsk001_retained_forms_pass(tmp_path):
    fs = _scan({"cometbft_tpu/p2p/fx.py": """
        import asyncio

        from ..libs import aio

        def ok(self):
            self._task = asyncio.create_task(self._run())
            self._tasks = [asyncio.create_task(self._recv())]
            t = asyncio.create_task(self._ping())
            t.add_done_callback(self._done)
            aio.spawn(self._sweep())
    """}, tmp_path)
    assert fs == []


# --------------------------------------------------------------- rule: BLK001

def test_blk001_blocking_calls_in_async(tmp_path):
    fs = _scan({"cometbft_tpu/rpc/fx.py": """
        import json
        import time

        async def bad(self, resp):
            time.sleep(0.1)
            return json.dumps(resp)
    """}, tmp_path)
    assert sorted(_rules_of(fs)) == ["BLK001", "BLK001"]


def test_blk001_sync_and_threaded_pass(tmp_path):
    fs = _scan({"cometbft_tpu/rpc/fx.py": """
        import asyncio
        import json

        def sync_helper(resp):          # sync def: caller's problem
            return json.dumps(resp)

        async def ok(self, resp):
            # passing the function is not calling it
            return await asyncio.to_thread(json.dumps, resp)
    """}, tmp_path)
    assert fs == []


def test_blk001_hashlib_only_in_loops(tmp_path):
    fs = _scan({"cometbft_tpu/p2p/fx.py": """
        import hashlib

        async def ok_single(self, b):
            return hashlib.sha256(b).digest()

        async def bad_loop(self, items):
            return [hashlib.sha256(i).digest() for i in items][0]
    """}, tmp_path)
    # a comprehension is not a For statement — the rule flags explicit
    # loop statements, where the N-times cost is structural
    fs2 = _scan({"cometbft_tpu/p2p/fx2.py": """
        import hashlib

        async def bad_loop(self, items):
            out = []
            for i in items:
                out.append(hashlib.sha256(i).digest())
            return out
    """}, tmp_path)
    assert _rules_of(fs) == []
    assert _rules_of(fs2) == ["BLK001"]


# --------------------------------------------------------------- rule: EXC001

def test_exc001_swallow_vs_routing(tmp_path):
    fs = _scan({"cometbft_tpu/storage/fx.py": """
        def bad(self):
            try:
                self._f.flush()
            except OSError:
                pass

        def ok_reraise(self):
            try:
                self._f.flush()
            except OSError:
                self._dead = True
                raise

        def ok_routed(self, e=None):
            try:
                self._f.flush()
            except Exception as e:
                self._io_failed(e)
    """}, tmp_path)
    assert _rules_of(fs) == ["EXC001"]
    assert fs[0].scope == "bad"


def test_exc001_nested_def_raise_does_not_route(tmp_path):
    """A raise inside a callback DEFINED in the handler body runs later
    (if ever) — it must not count as routing this exception."""
    fs = _scan({"cometbft_tpu/storage/fx.py": """
        def bad(self):
            try:
                self._f.flush()
            except OSError:
                def cb():
                    raise RuntimeError("later")
                self._register(cb)
    """}, tmp_path)
    assert _rules_of(fs) == ["EXC001"]


def test_exc001_narrow_except_passes(tmp_path):
    fs = _scan({"cometbft_tpu/privval/fx.py": """
        def ok(self):
            try:
                return self._decode()
            except (ValueError, KeyError):
                return None
    """}, tmp_path)
    assert fs == []


def test_exc001_multiline_clause_suppression(tmp_path):
    fs = _scan({"cometbft_tpu/privval/fx.py": """
        def ok(self):
            try:
                return self._roundtrip()
            except (ConnectionError,
                    OSError):  # bftlint: disable=EXC001 -- retry discipline, the retry re-raises
                return self._retry()
    """}, tmp_path)
    assert fs == []


# --------------------------------------------------------------- rule: DET001

def test_det001_global_rng_and_pick_random(tmp_path):
    fs = _scan({"cometbft_tpu/consensus/fx.py": """
        import random

        def bad_jitter():
            return 0.8 + 0.4 * random.random()

        def bad_pick(want):
            return want.pick_random()

        def ok_seeded(want, rng):
            r = random.Random("gossip:n0:peer1")
            return want.pick_random(rng), r.random()
    """}, tmp_path, {"DET001"})
    assert _rules_of(fs) == ["DET001", "DET001"]
    assert "GLOBAL RNG" in fs[0].message


def test_det001_sim_time_and_entropy(tmp_path):
    fs = _scan({"cometbft_tpu/sim/fx.py": """
        import os
        import time

        def bad():
            return os.urandom(8), time.monotonic()
    """}, tmp_path, {"DET001"})
    assert sorted(f.message.split("(")[0].split()[0] for f in fs) == \
        ["os.urandom", "time.monotonic"]


# ------------------------------------------------------- suppression grammar

def test_suppression_requires_reason(tmp_path):
    fs = _scan({"cometbft_tpu/node/fx.py": """
        import time

        def bad():
            return time.time()  # bftlint: disable=CLK001
    """}, tmp_path)
    # the disable is rejected AND the finding it failed to cover stays
    assert sorted(_rules_of(fs)) == [engine.BAD_SUPPRESSION, "CLK001"]


def test_suppression_own_line_covers_next_code_line(tmp_path):
    fs = _scan({"cometbft_tpu/node/fx.py": """
        import time

        def ok():
            # bftlint: disable=CLK001 -- long reasons go on their own line
            return time.time()
    """}, tmp_path)
    assert fs == []


def test_suppression_is_rule_scoped(tmp_path):
    fs = _scan({"cometbft_tpu/node/fx.py": """
        import time

        def still_bad():
            return time.time()  # bftlint: disable=TSK001 -- wrong rule on purpose
    """}, tmp_path)
    assert _rules_of(fs) == ["CLK001"]


# ------------------------------------------------------------------ baseline

def _write_fixture(root: Path, src: str,
                   rel="cometbft_tpu/consensus/fx.py") -> Path:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return p


def test_baselined_hit_passes_new_finding_fails(tmp_path, capsys):
    src = """
        import time

        def age():
            return time.monotonic()
    """
    _write_fixture(tmp_path, src)
    bl = tmp_path / "baseline.json"

    # triage the pre-existing finding into the baseline
    rc = cli_main([str(tmp_path / "cometbft_tpu"), "--root", str(tmp_path),
                   "--baseline", str(bl), "--write-baseline",
                   "--reason", "pre-existing; tracked in fixture triage"])
    assert rc == 0
    # baselined -> exit 0
    rc = cli_main([str(tmp_path / "cometbft_tpu"), "--root", str(tmp_path),
                   "--baseline", str(bl)])
    assert rc == 0

    # a NEW finding in the same file still fails
    _write_fixture(tmp_path, src + """
        def age2():
            return time.monotonic()
    """)
    rc = cli_main([str(tmp_path / "cometbft_tpu"), "--root", str(tmp_path),
                   "--baseline", str(bl)])
    assert rc == 1
    out = capsys.readouterr().out
    assert "age2" in out or "1 new finding" in out


def test_baseline_fingerprint_survives_line_drift(tmp_path):
    _write_fixture(tmp_path, """
        import time

        def age():
            return time.monotonic()
    """)
    bl = tmp_path / "baseline.json"
    assert cli_main([str(tmp_path / "cometbft_tpu"), "--root",
                     str(tmp_path), "--baseline", str(bl),
                     "--write-baseline", "--reason", "triaged"]) == 0
    # shift the finding 3 lines down: fingerprint (rule|path|scope|line
    # text) is unchanged, so the entry still matches
    _write_fixture(tmp_path, """
        import time

        # a
        # b
        # c
        def age():
            return time.monotonic()
    """)
    assert cli_main([str(tmp_path / "cometbft_tpu"), "--root",
                     str(tmp_path), "--baseline", str(bl)]) == 0


def test_baseline_entry_requires_reason(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(
        {"version": 1, "entries": [{"fingerprint": "cafe", "reason": ""}]}))
    with pytest.raises(SystemExit):
        engine.load_baseline(bl)


# ----------------------------------------------------------------------- CLI

def test_cli_rules_filter_and_json_report(tmp_path):
    _write_fixture(tmp_path, """
        import time
        import asyncio

        def age():
            return time.monotonic()

        def fire(self):
            asyncio.create_task(self._run())
    """)
    report = tmp_path / "report.json"
    rc = cli_main([str(tmp_path / "cometbft_tpu"), "--root", str(tmp_path),
                   "--no-baseline", "--rules", "TSK001",
                   "--json", str(report)])
    assert rc == 1
    doc = json.loads(report.read_text())
    assert doc["tool"] == "bftlint"
    assert [f["rule"] for f in doc["findings"]] == ["TSK001"]
    assert doc["summary"]["new"] == 1
    f = doc["findings"][0]
    assert f["fingerprint"] and f["path"].endswith("fx.py") and f["line"]


def test_cli_unknown_rule_is_usage_error(tmp_path):
    assert cli_main(["--rules", "NOPE42"]) == 2


def test_cli_prune_stale_refuses_filtered_runs(tmp_path):
    """A --rules or path-filtered scan can't see the whole tree, so
    pruning from it would delete live out-of-scope entries."""
    _write_fixture(tmp_path, "x = 1\n")
    args = ["--baseline", str(tmp_path / "b.json"), "--write-baseline",
            "--prune-stale", "--reason", "x"]
    assert cli_main(["--rules", "CLK001"] + args) == 2
    assert cli_main([str(tmp_path / "cometbft_tpu"), "--root",
                     str(tmp_path)] + args) == 2


def test_every_shipped_rule_exists_and_has_scope():
    ids = {r.id for r in rules_mod.ALL_RULES}
    # deleting any of the six invariants from the engine fails here
    assert {"CLK001", "LCK001", "TSK001",
            "BLK001", "EXC001", "DET001"} <= ids
    for r in rules_mod.ALL_RULES:
        assert r.scopes and r.severity in ("high", "medium") and r.title


# ----------------------------------------------------------- the real tree

def test_repo_tree_is_clean_under_the_shipped_baseline():
    """The acceptance bar: ``python -m analysis`` exits 0 on the full
    tree — every finding either fixed, suppressed-with-reason, or
    triaged into baseline.json."""
    assert cli_main([]) == 0


def test_shipped_baseline_entries_all_carry_reasons():
    bl = engine.load_baseline(engine.DEFAULT_BASELINE)
    for ent in bl.values():
        assert ent["reason"].strip()
