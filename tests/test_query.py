"""Query language tests (reference: ``libs/pubsub/query/query_test.go``)."""

import asyncio

import pytest

from cometbft_tpu.libs.query import Query, QuerySyntaxError


def m(**kw):
    return {k.replace("_", "."): (v if isinstance(v, list) else [v])
            for k, v in kw.items()}


def test_equality_and_conjunction():
    q = Query.parse("tm.event = 'NewBlock' AND block.height = '5'")
    assert q.matches({"tm.event": ["NewBlock"], "block.height": ["5"]})
    assert not q.matches({"tm.event": ["Tx"], "block.height": ["5"]})
    assert not q.matches({"tm.event": ["NewBlock"]})
    assert q.equality_clauses() == {"tm.event": "NewBlock",
                                    "block.height": "5"}


def test_numeric_comparisons():
    q = Query.parse("tx.height > 5 AND tx.height <= 10")
    assert q.matches({"tx.height": ["7"]})
    assert q.matches({"tx.height": ["10"]})
    assert not q.matches({"tx.height": ["5"]})
    assert not q.matches({"tx.height": ["11"]})
    # unparseable values are skipped, not errors
    assert not q.matches({"tx.height": ["7atom"]})
    # floats compare against int conditions
    assert Query.parse("p.x >= 0.5").matches({"p.x": ["0.75"]})
    # numeric equality parses the value as a number (07 == 7)
    assert Query.parse("tx.height = 7").matches({"tx.height": ["07"]})


def test_contains_and_exists():
    q = Query.parse("transfer.amount CONTAINS 'uatom'")
    assert q.matches({"transfer.amount": ["100uatom"]})
    assert not q.matches({"transfer.amount": ["100stake"]})
    q = Query.parse("account.created EXISTS")
    assert q.matches({"account.created": ["anything"]})
    assert not q.matches({"other.key": ["x"]})


def test_any_value_matches():
    # a condition is satisfied by ANY value of a repeated attribute
    q = Query.parse("transfer.to = 'bob'")
    assert q.matches({"transfer.to": ["alice", "bob"]})


def test_time_and_date():
    q = Query.parse("tx.time >= TIME 2023-05-03T14:45:00Z")
    assert q.matches({"tx.time": ["2023-05-03T15:00:00Z"]})
    assert not q.matches({"tx.time": ["2023-05-03T14:00:00Z"]})
    q = Query.parse("tx.date = DATE 2023-05-03")
    assert q.matches({"tx.date": ["2023-05-03T00:00:00Z"]})


def test_syntax_errors():
    for bad in ["", "AND", "tm.event =", "tm.event < 'str'", "key CONTAINS 5",
                "a = 'x' OR b = 'y'", "a = 'x' b = 'y'", "a = 'x' AND"]:
        with pytest.raises(QuerySyntaxError):
            Query.parse(bad)


def test_escaped_quote_roundtrip():
    q = Query.parse(r"app.note = 'it\'s'")
    assert q.matches({"app.note": ["it's"]})


def test_event_bus_full_query():
    from cometbft_tpu.libs.pubsub import EventBus

    async def run():
        bus = EventBus()
        sub = bus.subscribe("s", "tm.event='Tx' AND tx.height > 3")
        bus.publish("Tx", {"n": 1}, {"tx.height": "2"})
        bus.publish("Tx", {"n": 2}, {"tx.height": "9"})
        bus.publish("NewBlock", {"n": 3}, {"tx.height": "9"})
        got = sub.queue.get_nowait()
        assert got.data == {"n": 2}
        assert sub.queue.empty()
    asyncio.run(run())


def test_tx_indexer_range_search():
    from cometbft_tpu.abci.types import Event, EventAttribute, ExecTxResult
    from cometbft_tpu.indexer.tx import TxIndexer

    ix = TxIndexer()
    for h in range(1, 8):
        res = ExecTxResult(code=0, data=b"", log="", gas_wanted=0, gas_used=1,
                       events=[Event("transfer",
                                     [EventAttribute("amount",
                                                     f"{h}00uatom")])])
        ix.index(h, 0, b"tx%d" % h, res, {})
    out = ix.search("tx.height > 2 AND tx.height <= 5")
    assert [r["height"] for r in out["txs"]] == [3, 4, 5]
    out = ix.search("transfer.amount CONTAINS '00uatom' AND tx.height < 3")
    assert [r["height"] for r in out["txs"]] == [1, 2]
    out = ix.search("transfer.amount = '300uatom'")
    assert [r["height"] for r in out["txs"]] == [3]


def test_indexer_order_by_desc():
    from cometbft_tpu.abci.types import ExecTxResult
    from cometbft_tpu.indexer.block import BlockIndexer
    from cometbft_tpu.indexer.tx import TxIndexer

    ix = TxIndexer()
    for h in range(1, 6):
        ix.index(h, 0, b"otx%d" % h, ExecTxResult(), {})
    out = ix.search("tx.height > 0", order_by="desc")
    assert [r["height"] for r in out["txs"]] == [5, 4, 3, 2, 1]

    bx = BlockIndexer()
    for h in range(1, 6):
        bx.index(h, [])
    out = bx.search("block.height > 2", order_by="desc")
    assert out["heights"] == [5, 4, 3]


def test_tx_indexer_hash_search():
    from cometbft_tpu.abci.types import ExecTxResult
    from cometbft_tpu.indexer.tx import TxIndexer
    from cometbft_tpu.mempool.mempool import TxKey

    ix = TxIndexer()
    ix.index(4, 0, b"mytx", ExecTxResult(), {"tx.hash": TxKey(b"mytx").hex()})
    out = ix.search(f"tx.hash='{TxKey(b'mytx').hex()}'")
    assert out["total_count"] == 1 and out["txs"][0]["height"] == 4


def test_block_indexer_tm_event_tolerated():
    from cometbft_tpu.abci.types import Event, EventAttribute
    from cometbft_tpu.indexer.block import BlockIndexer

    ix = BlockIndexer()
    ix.index(1, [Event("reward", [EventAttribute("amt", "10")])])
    # any tm.event value is tolerated: all records here are block events
    for ev in ("NewBlock", "NewBlockEvents"):
        out = ix.search(f"tm.event='{ev}' AND block.height=1")
        assert out["heights"] == [1], ev
    assert ix.search("tm.event='NewBlock'")["heights"] == [1]


def test_block_indexer_legacy_empty_record():
    """Rows written before events were stored (value b'') must stay
    findable through postings + height conditions."""
    from cometbft_tpu.indexer.block import BlockIndexer, K_ATTR, K_HEIGHT

    ix = BlockIndexer()
    h8 = (5).to_bytes(8, "big")
    ix.db.set_batch({
        K_HEIGHT + h8: b"",
        K_ATTR + b"reward.amt\x00" + b"50\x00" + h8: b"",
    })
    assert ix.search("reward.amt='50'")["heights"] == [5]
    assert ix.search("reward.amt='50' AND block.height <= 5")["heights"] == [5]
    assert ix.search("block.height > 5")["heights"] == []


def test_block_indexer_range_search():
    from cometbft_tpu.abci.types import Event, EventAttribute
    from cometbft_tpu.indexer.block import BlockIndexer

    ix = BlockIndexer()
    for h in range(1, 8):
        ix.index(h, [Event("reward", [EventAttribute("amt", str(h * 10))])])
    out = ix.search("block.height >= 6")
    assert out["heights"] == [6, 7]
    out = ix.search("reward.amt = 30")
    assert out["heights"] == [3]
    out = ix.search("reward.amt EXISTS AND block.height < 3")
    assert out["heights"] == [1, 2]
