"""Scenario-lab tests: virtual clock, in-memory transport, seeded
byzantine adversaries, and the replay contract (same seed + same
scenario => identical verdict AND identical chaos signature)."""

import asyncio
import json
import time

import pytest

from cometbft_tpu.libs import clock, failures
from cometbft_tpu.sim import (MemNetwork, Scenario, SimTuning,
                              VirtualTimeDeadlock, run_scenario)
from cometbft_tpu.sim import vtime


# -------------------------------------------------------- virtual clock

def test_virtual_clock_sleep_and_timeout_cost_no_real_time():
    """Hours of virtual sleeping and a fired wait_for timeout complete in
    real milliseconds, and the clock seam reads virtual time."""

    async def main():
        t0 = clock.monotonic()
        await clock.sleep(3600)
        with pytest.raises(asyncio.TimeoutError):
            await clock.wait_for(asyncio.Event().wait(), 1800)
        return clock.monotonic() - t0, clock.walltime_ns()

    real0 = time.monotonic()
    virt, wall = vtime.run(main, seed=1)
    assert time.monotonic() - real0 < 5.0      # vs 5400 s simulated
    assert virt == pytest.approx(5400.0)
    assert wall == vtime.VIRTUAL_EPOCH_NS + int(5400e9)
    # seam restored: real clock again
    assert clock.installed() is None
    assert abs(clock.monotonic() - time.monotonic()) < 1.0


def test_virtual_clock_timer_order_is_deterministic():
    """Same seed, same schedule: callback order (hence the trace of a
    run) is identical across runs."""

    def make():
        async def main():
            out = []
            for i, d in enumerate((0.3, 0.1, 0.2, 0.1, 0.0)):
                async def tick(i=i, d=d):
                    await clock.sleep(d)
                    out.append(i)
                asyncio.get_running_loop().create_task(tick())
            await clock.sleep(1.0)
            return out

        return vtime.run(main, seed=5)

    assert make() == make() == [4, 1, 3, 2, 0]


def test_virtual_deadlock_detected(monkeypatch):
    """A quiescent loop with nothing scheduled raises instead of
    hanging CI forever."""
    monkeypatch.setattr(vtime, "_MAX_IDLE_ROUNDS", 3)
    monkeypatch.setattr(vtime, "_IDLE_SLICE_S", 0.01)

    async def main():
        await asyncio.Event().wait()       # can never fire

    with pytest.raises(VirtualTimeDeadlock):
        vtime.run(main, seed=0)


# ------------------------------------------------------- mem transport

def test_mem_network_policy_resolution_and_specs():
    net = MemNetwork(default_latency_s=0.01)
    net.apply_spec("link:node=a:peer=b:delay=0.2")
    net.apply_spec("link:node=c:delay=0.05")           # c -> * wildcard
    assert net.policy("a", "b").latency_s == pytest.approx(0.2)
    assert net.policy("b", "a").latency_s == pytest.approx(0.01)
    assert net.policy("c", "zz").latency_s == pytest.approx(0.05)
    net.apply_spec("link:node=a:peer=b:cut=fwd")
    assert net.policy("a", "b").cut and not net.policy("b", "a").cut
    net.heal()
    assert not net.policy("a", "b").cut
    net.partition(["a"], ["b", "c"], one_way=True)
    assert net.policy("a", "b").cut and not net.policy("b", "a").cut
    with pytest.raises(failures.FaultSpecError):
        net.apply_spec("notlink:delay=1")


def test_mem_transport_full_stack_commits():
    """Two sim nodes over MemTransport: real Switch handshake (NodeInfo
    exchange, identity check), real MConnection packets, blocks
    committed — the whole production p2p stack minus TCP."""
    from cometbft_tpu.sim import make_genesis, make_sim_node

    async def main():
        failures.reset()
        failures.configure(enabled=True, seed=3)
        net = MemNetwork()
        doc, pvs = make_genesis(2, chain_id="mem-pair")
        nodes = [await make_sim_node(i, doc, pv, net)
                 for i, pv in enumerate(pvs)]
        for n in nodes:
            await n.start()
        peer = await nodes[0].dial(nodes[1], persistent=True)
        assert peer.id == nodes[1].node_key.id
        deadline = clock.monotonic() + 60
        while min(n.height() for n in nodes) < 2:
            assert clock.monotonic() < deadline, "no commits over mem wire"
            await clock.sleep(0.1)
        h1 = {n.block_store.load_block(1).hash() for n in nodes}
        assert len(h1) == 1
        for n in nodes:
            await n.stop()
        failures.reset()
        return True

    assert vtime.run(main, seed=3)


# ----------------------------------------------------------- scenarios

def test_partition_heal_liveness_and_recovery_metric():
    scn = Scenario(
        name="t-partition", seed=21, n_nodes=7, out_links=3,
        target_height=5, max_virtual_s=900.0,
        steps=[
            {"at": 0.3, "op": "partition",
             "groups": [[0, 1], [2, 3, 4, 5, 6]]},
            {"at": 1.5, "op": "heal"},
        ])
    v = run_scenario(scn)
    assert v["reached_target"] and v["fork_free"]
    assert v["common_height"] >= 5
    assert v["time_to_recover_s"] is not None
    assert len(v["block_hashes"]) == v["common_height"]


def test_scenario_json_round_trip_keeps_tuning():
    """A Scenario saved to JSON must come back byte-identical INCLUDING
    tuning — spam-flood-ban-25 exists to exercise ban_ttl_s=3.0, and a
    round-trip that silently resurrects the default 10.0 changes the
    ban/readmit cycle (hence the verdict) with no error."""
    from cometbft_tpu.sim.scenario import curated_suite

    for scn in curated_suite():
        back = Scenario.from_dict(json.loads(json.dumps(scn.to_dict())))
        assert back.tuning == scn.tuning, scn.name
        assert back.to_dict() == scn.to_dict(), scn.name
    # legacy dicts without the key still load (default tuning)
    legacy = Scenario(name="t", seed=1).to_dict()
    del legacy["tuning"]
    assert Scenario.from_dict(legacy).tuning == SimTuning()


def test_replay_identical_verdict_and_signature_with_prob_site():
    """Satellite: same seed + same program => identical fault
    signature() AND identical verdict JSON across two runs, including a
    prob= site (the nondeterminism-prone trigger class)."""
    scn = Scenario(
        name="t-replay", seed=99, n_nodes=5, out_links=2,
        target_height=3,
        faults=["p2p.recv.corrupt:prob=0.05:max=8",
                "p2p.send.delay:every=40:delay=0.05:max=10"])
    from cometbft_tpu.sim.scenario import chaos_signature_of

    v1, sig1 = chaos_signature_of(scn)
    v2, sig2 = chaos_signature_of(scn)
    assert sig1 == sig2 and len(sig1) > 0
    assert json.dumps(v1, sort_keys=True) == json.dumps(v2, sort_keys=True)
    assert v1["fork_free"]
    # the prob site really fired (the signature carries its call indices)
    assert any(site == "p2p.recv.corrupt" for site, _, _ in sig1)


def test_double_sign_scenario_ends_in_committed_evidence():
    """Satellite: the equivocator's conflicting votes must flow through
    the evidence pool into a committed block, the byzantine validator
    must be identified, and NO honest node may be banned for relaying
    the (legitimate) evidence — the bad_evidence-exempt path."""
    scn = Scenario(
        name="t-equivocator", seed=31, n_nodes=5, out_links=2,
        target_height=6, max_virtual_s=900.0,
        byzantine={2: "equivocator"})
    v = run_scenario(scn)
    assert v["fork_free"], "one equivocator must not fork 3f+1 honest"
    assert v["reached_target"]
    assert v["evidence"]["committed_total"] >= 1
    assert v["evidence"]["byzantine_punished"] == ["sim002"]
    # honest gossip of real evidence is never scored bad_evidence, and
    # nobody gets banned for it (EvidenceNotApplicableError drop path +
    # committed-evidence dedup both return without punishment)
    assert "bad_evidence" not in v["misbehavior_events"]
    assert "bad_evidence" not in v["bans"]["by_reason"]
    for name in v["bans"]["banned_nodes"]:
        assert name == "sim002", f"honest node {name} banned"


def test_flooder_is_banned_and_net_survives():
    scn = Scenario(
        name="t-flood", seed=41, n_nodes=5, out_links=2,
        target_height=8, max_virtual_s=900.0,
        byzantine={4: "flooder"},
        tuning=SimTuning(ban_ttl_s=2.0))
    v = run_scenario(scn)
    assert v["reached_target"] and v["fork_free"]
    assert v["misbehavior_events"].get("invalid_tx", 0) > 0
    assert v["bans"]["total"] >= 1
    assert v["bans"]["banned_nodes"] == ["sim004"]


def test_crash_restore_rejoins():
    scn = Scenario(
        name="t-crash", seed=51, n_nodes=5, out_links=2,
        target_height=5, max_virtual_s=900.0,
        steps=[
            {"at": 0.8, "op": "crash", "node": 1},
            {"at": 2.0, "op": "restore", "node": 1},
        ])
    v = run_scenario(scn)
    assert v["reached_target"] and v["fork_free"]
    # the restored node is back in the honest floor: common_height
    # includes it, so reaching target proves the rejoin worked
    assert v["common_height"] >= 5


# ----------------------------------------------- clock seam (real mode)

def test_clock_seam_real_mode_matches_time_module():
    assert clock.installed() is None
    assert abs(clock.monotonic() - time.monotonic()) < 0.5
    assert abs(clock.walltime_ns() - time.time_ns()) < int(5e8)
    assert abs(clock.walltime() - time.time()) < 0.5


def test_scorer_ban_ttl_runs_on_virtual_clock():
    """quality.py decay/TTL rides the seam: a ban expires after virtual
    seconds, not real ones."""
    from cometbft_tpu.p2p.quality import PeerScorer

    async def main():
        sc = PeerScorer(ban_ttl_s=5.0)
        for _ in range(3):
            sc.report("peerX", "bad_block")
        assert sc.is_banned("peerX")
        await clock.sleep(6.0)          # virtual — instant in real time
        return sc.is_banned("peerX")

    assert vtime.run(main, seed=0) is False
