"""Types-layer tests: wire format, merkle, canonical sign bytes (byte-exact
vs protoc), validator set rotation, and the VerifyCommit family on both
backends."""

import hashlib
import subprocess
import sys
import tempfile
from fractions import Fraction
from pathlib import Path

import pytest

from cometbft_tpu.crypto import merkle
from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.types import (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT,
                                BLOCK_ID_FLAG_NIL, Block, BlockID, Commit,
                                CommitSig, Data, Header, PartSetHeader,
                                Validator, ValidatorSet, VerifyCommit,
                                VerifyCommitLight, VerifyCommitLightTrusting,
                                Vote, PRECOMMIT_TYPE)
from cometbft_tpu.types import canonical, validation, wire
from cometbft_tpu.types.validation import (ErrInvalidCommit,
                                           ErrInvalidSignature,
                                           ErrNotEnoughVotingPower)

CHAIN_ID = "test-chain"


# ----------------------------------------------------------------- wire/proto

CANONICAL_PROTO = """
syntax = "proto3";
package ct;
message Timestamp { int64 seconds = 1; int32 nanos = 2; }
message CanonicalPartSetHeader { uint32 total = 1; bytes hash = 2; }
message CanonicalBlockID {
  bytes hash = 1;
  CanonicalPartSetHeader part_set_header = 2;
}
message CanonicalVote {
  int32 type = 1;
  sfixed64 height = 2;
  sfixed64 round = 3;
  CanonicalBlockID block_id = 4;
  Timestamp timestamp = 5;
  string chain_id = 6;
}
"""


@pytest.fixture(scope="module")
def pb():
    """Compile the canonical schema with protoc into a temp module."""
    import shutil

    if shutil.which("protoc") is None:
        pytest.skip("protoc not installed on this image")
    with tempfile.TemporaryDirectory() as td:
        proto = Path(td) / "ct.proto"
        proto.write_text(CANONICAL_PROTO)
        subprocess.run(["protoc", f"-I{td}", f"--python_out={td}", "ct.proto"],
                       check=True)
        sys.path.insert(0, td)
        try:
            import ct_pb2  # noqa: F401
            yield ct_pb2
        finally:
            sys.path.remove(td)
            sys.modules.pop("ct_pb2", None)


def test_canonical_vote_byte_exact(pb):
    bid = BlockID(hash=b"\xaa" * 32,
                  part_set_header=PartSetHeader(3, b"\xbb" * 32))
    ts = 1_700_000_000_123_456_789
    for block_id, h, r in [(bid, 5, 0), (bid, 1 << 40, 7), (BlockID(), 3, 2)]:
        got = canonical.canonical_vote_sign_bytes(
            CHAIN_ID, PRECOMMIT_TYPE, h, r, block_id, ts)
        msg = pb.CanonicalVote()
        msg.type = PRECOMMIT_TYPE
        msg.height = h
        msg.round = r
        if not block_id.is_nil():
            msg.block_id.hash = block_id.hash
            msg.block_id.part_set_header.total = block_id.part_set_header.total
            msg.block_id.part_set_header.hash = block_id.part_set_header.hash
        msg.timestamp.seconds = ts // 10**9
        msg.timestamp.nanos = ts % 10**9
        msg.chain_id = CHAIN_ID
        want = msg.SerializeToString()
        # strip our varint length prefix, compare the body byte-for-byte
        n = 0
        shift = 0
        i = 0
        while True:
            b = got[i]
            n |= (b & 0x7F) << shift
            shift += 7
            i += 1
            if not (b & 0x80):
                break
        assert got[i:] == want, (got.hex(), want.hex())
        assert n == len(want)


def test_wire_negative_varint(pb):
    # negative sfixed64 height is invalid domain-wise, but negative varints
    # (e.g. pol_round=-1, timestamp seconds pre-1970) must match protobuf
    msg = pb.Timestamp()
    msg.seconds = -5
    assert wire.field_varint(1, -5) == msg.SerializeToString()


# -------------------------------------------------------------------- merkle

def test_merkle_rfc6962():
    # independent expressions of the RFC6962 shape
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    one = merkle.hash_from_byte_slices([b"x"])
    assert one == hashlib.sha256(b"\x00x").digest()
    two = merkle.hash_from_byte_slices([b"a", b"b"])
    assert two == hashlib.sha256(
        b"\x01" + hashlib.sha256(b"\x00a").digest()
        + hashlib.sha256(b"\x00b").digest()).digest()
    # split point: 5 leaves -> left 4, right 1
    five = merkle.hash_from_byte_slices([b"1", b"2", b"3", b"4", b"5"])
    left = merkle.hash_from_byte_slices([b"1", b"2", b"3", b"4"])
    right = merkle.hash_from_byte_slices([b"5"])
    assert five == hashlib.sha256(b"\x01" + left + right).digest()


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13])
def test_merkle_proofs(n):
    items = [bytes([i]) * (i + 1) for i in range(n)]
    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == merkle.hash_from_byte_slices(items)
    for i, p in enumerate(proofs):
        assert p.verify(root, items[i]), (n, i)
        assert not p.verify(root, items[i] + b"!")
        if n > 1:
            assert not p.verify(hashlib.sha256(b"no").digest(), items[i])


# ------------------------------------------------------------- validator set

def make_vals(powers, secret_prefix=b"v"):
    keys = [Ed25519PrivKey.from_secret(secret_prefix + bytes([i]))
            for i in range(len(powers))]
    vals = ValidatorSet([Validator(k.pub_key(), p)
                         for k, p in zip(keys, powers)])
    by_addr = {k.pub_key().address(): k for k in keys}
    return vals, by_addr


def test_proposer_rotation_weighted():
    vals, _ = make_vals([1, 2, 3])
    counts = {}
    for _ in range(600):
        p = vals.get_proposer()
        counts[p.voting_power] = counts.get(p.voting_power, 0) + 1
        vals.increment_proposer_priority(1)
    assert counts[1] == 100 and counts[2] == 200 and counts[3] == 300


def test_proposer_determinism_and_copy():
    a, _ = make_vals([5, 5, 5, 10])
    b, _ = make_vals([5, 5, 5, 10])
    seq_a = []
    for _ in range(20):
        seq_a.append(a.get_proposer().address)
        a.increment_proposer_priority(1)
    c = b.copy_increment_proposer_priority(5)
    for _ in range(20):
        assert seq_a.pop(0) == b.get_proposer().address
        b.increment_proposer_priority(1)
    # copy didn't disturb the original
    assert c is not b


def test_valset_hash_and_updates():
    vals, _ = make_vals([10, 20, 30])
    h1 = vals.hash()
    vals2, _ = make_vals([10, 20, 31])
    assert h1 != vals2.hash()

    new_key = Ed25519PrivKey.from_secret(b"new").pub_key()
    vals.update_with_change_set([Validator(new_key, 7)])
    assert vals.size() == 4
    idx, v = vals.get_by_address(new_key.address())
    assert idx >= 0 and v.voting_power == 7
    # removal
    vals.update_with_change_set([Validator(new_key, 0)])
    assert vals.size() == 3 and not vals.has_address(new_key.address())
    with pytest.raises(ValueError):
        vals.update_with_change_set([Validator(new_key, 0)])


# ------------------------------------------------------------ commit verify

def make_commit(vals, by_addr, height=10, round_=1, *, nil_at=(), absent_at=(),
                bad_at=(), bid=None):
    bid = bid or BlockID(b"\xcd" * 32, PartSetHeader(1, b"\xef" * 32))
    sigs = []
    for i, v in enumerate(vals.validators):
        if i in absent_at:
            sigs.append(CommitSig.absent())
            continue
        flag = BLOCK_ID_FLAG_NIL if i in nil_at else BLOCK_ID_FLAG_COMMIT
        vote_bid = BlockID() if i in nil_at else bid
        ts = 1_700_000_000_000_000_000 + i
        sb = canonical.canonical_vote_sign_bytes(
            CHAIN_ID, PRECOMMIT_TYPE, height, round_, vote_bid, ts)
        sig = by_addr[v.address].sign(sb)
        if i in bad_at:
            sig = sig[:20] + bytes([sig[20] ^ 1]) + sig[21:]
        sigs.append(CommitSig(flag, v.address, ts, sig))
    return Commit(height, round_, bid, sigs)


@pytest.mark.parametrize("backend", ["cpu", "jax"])
def test_verify_commit_ok(backend):
    vals, by_addr = make_vals([10] * 7)
    commit = make_commit(vals, by_addr, absent_at={0}, nil_at={1})
    VerifyCommit(CHAIN_ID, vals, commit.block_id, 10, commit, backend=backend)
    VerifyCommitLight(CHAIN_ID, vals, commit.block_id, 10, commit,
                      backend=backend)


@pytest.mark.parametrize("backend", ["cpu", "jax"])
def test_verify_commit_bad_sig(backend):
    vals, by_addr = make_vals([10] * 7)
    commit = make_commit(vals, by_addr, bad_at={6})
    with pytest.raises(ErrInvalidSignature) as ei:
        VerifyCommit(CHAIN_ID, vals, commit.block_id, 10, commit,
                     backend=backend)
    assert ei.value.idx == 6
    # a bad *nil* signature also fails VerifyCommit (verifies all sigs)...
    commit2 = make_commit(vals, by_addr, nil_at={3}, bad_at={3})
    with pytest.raises(ErrInvalidSignature):
        VerifyCommit(CHAIN_ID, vals, commit2.block_id, 10, commit2,
                     backend=backend)
    # ...but not VerifyCommitLight (skips nil votes entirely)
    VerifyCommitLight(CHAIN_ID, vals, commit2.block_id, 10, commit2,
                      backend=backend)


def test_verify_commit_not_enough_power():
    vals, by_addr = make_vals([10] * 6)
    # 4 of 6 at 10 power = 40 <= 2/3*60 -> fails (needs STRICTLY more)
    commit = make_commit(vals, by_addr, nil_at={0}, absent_at={1})
    with pytest.raises(ErrNotEnoughVotingPower):
        VerifyCommit(CHAIN_ID, vals, commit.block_id, 10, commit,
                     backend="cpu")
    # 5 of 6 passes
    commit = make_commit(vals, by_addr, nil_at={0})
    VerifyCommit(CHAIN_ID, vals, commit.block_id, 10, commit, backend="cpu")


def test_verify_commit_basics_mismatch():
    vals, by_addr = make_vals([10] * 4)
    commit = make_commit(vals, by_addr)
    with pytest.raises(ErrInvalidCommit):
        VerifyCommit(CHAIN_ID, vals, commit.block_id, 11, commit,
                     backend="cpu")
    with pytest.raises(ErrInvalidCommit):
        VerifyCommit(CHAIN_ID, vals, BlockID(b"\x01" * 32,
                                             PartSetHeader(1, b"\x02" * 32)),
                     10, commit, backend="cpu")
    small = ValidatorSet(vals.validators[:3])
    with pytest.raises(ErrInvalidCommit):
        VerifyCommit(CHAIN_ID, small, commit.block_id, 10, commit,
                     backend="cpu")


@pytest.mark.parametrize("backend", ["cpu", "jax"])
def test_verify_commit_light_trusting(backend):
    vals, by_addr = make_vals([10] * 8)
    commit = make_commit(vals, by_addr)
    # trusted set: 4 of the original validators + 2 unknown, different powers
    trusted_vals = [v.copy() for v in vals.validators[:4]]
    extra, extra_addr = make_vals([10, 10], secret_prefix=b"x")
    trusted = ValidatorSet(trusted_vals + [v.copy()
                                           for v in extra.validators])
    VerifyCommitLightTrusting(CHAIN_ID, trusted, commit,
                              Fraction(1, 3), backend=backend)
    with pytest.raises(ErrNotEnoughVotingPower):
        VerifyCommitLightTrusting(CHAIN_ID, trusted, commit,
                                  Fraction(1, 1), backend=backend)


def test_vote_sign_verify_roundtrip():
    sk = Ed25519PrivKey.from_secret(b"val")
    bid = BlockID(b"\x11" * 32, PartSetHeader(2, b"\x22" * 32))
    v = Vote(type=PRECOMMIT_TYPE, height=3, round=0, block_id=bid,
             timestamp_ns=1_700_000_000_000_000_000,
             validator_address=sk.pub_key().address(), validator_index=0)
    v.signature = sk.sign(v.sign_bytes(CHAIN_ID))
    assert v.validate_basic() is None
    assert v.verify(CHAIN_ID, sk.pub_key())
    assert not v.verify("other-chain", sk.pub_key())
    v.extension = b"ext-data"
    v.extension_signature = sk.sign(v.extension_sign_bytes(CHAIN_ID))
    assert v.verify_extension(CHAIN_ID, sk.pub_key())


def test_header_block_hash():
    vals, by_addr = make_vals([10] * 4)
    h = Header(chain_id=CHAIN_ID, height=5,
               time_ns=1_700_000_000_000_000_000,
               last_block_id=BlockID(b"\x01" * 32,
                                     PartSetHeader(1, b"\x02" * 32)),
               validators_hash=vals.hash(), next_validators_hash=vals.hash(),
               proposer_address=vals.get_proposer().address)
    b = Block(header=h, data=Data(txs=[b"tx1", b"tx2"]),
              last_commit=make_commit(vals, by_addr, height=4))
    b.fill_hashes()
    assert b.validate_basic() is None
    h1 = b.hash()
    assert len(h1) == 32
    b.data.txs.append(b"tx3")
    b.fill_hashes()
    assert b.hash() != h1
    # tampering with data without refreshing hashes is caught
    b.data.txs.append(b"tx4")
    assert b.validate_basic() == "wrong data_hash"
