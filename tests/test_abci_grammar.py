"""ABCI grammar conformance: live nodes' recorded call sequences satisfy
the ABCI 2.0 ordering grammar (reference: ``test/e2e/pkg/grammar``)."""

import asyncio

import pytest

from cometbft_tpu.abci.grammar import (GrammarError, RecordingApp,
                                       check_sequence)
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.testing import make_inproc_network

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_checker_accepts_legal_sequences():
    assert check_sequence(
        ["init_chain",
         "prepare_proposal", "process_proposal",
         "finalize_block", "commit",
         "process_proposal", "finalize_block", "commit"]) == 2
    # statesync start
    assert check_sequence(
        ["offer_snapshot", "apply_snapshot_chunk", "apply_snapshot_chunk",
         "process_proposal", "finalize_block", "commit"]) == 1
    # crash recovery: no InitChain, straight to replay
    assert check_sequence(["finalize_block", "commit"]) == 1
    # free-interleave calls are ignored by the sequencer
    assert check_sequence(
        ["info", "init_chain", "check_tx", "finalize_block", "query",
         "commit"]) == 1


def test_checker_rejects_illegal_sequences():
    with pytest.raises(GrammarError):
        check_sequence(["init_chain", "commit"])            # commit w/o finalize
    with pytest.raises(GrammarError):
        check_sequence(["finalize_block", "finalize_block"])  # no commit between
    with pytest.raises(GrammarError):
        check_sequence(["init_chain", "prepare_proposal", "commit"])
    with pytest.raises(GrammarError):
        # snapshot restore cannot restart mid-chain
        check_sequence(["init_chain", "finalize_block", "commit",
                        "offer_snapshot"])


def test_live_nodes_obey_the_grammar():
    """Every node in a running network produces a grammar-legal ABCI call
    sequence, including proposal rounds and tx traffic."""

    async def main():
        net = await make_inproc_network(
            4, app_factory=lambda: RecordingApp(KVStoreApplication()))
        try:
            await net.start()
            for i, node in enumerate(net.nodes):
                await node.mempool.check_tx(b"g%d=h%d" % (i, i))
            await net.wait_for_height(5, timeout=60)
        finally:
            await net.stop()
        for node in net.nodes:
            heights = node.app.check()
            assert heights >= 5, f"{node.name}: only {heights} heights"
            assert "check_tx" in node.app.calls
        return True

    assert run(main())


def test_checker_accepts_statesync_retry():
    # a failed restore attempt retries with another snapshot — legal
    assert check_sequence(
        ["offer_snapshot", "apply_snapshot_chunk", "offer_snapshot",
         "apply_snapshot_chunk", "finalize_block", "commit"]) == 1
