"""Evidence subsystem: pool verification + the byzantine tier-1 test — a
double-signing validator is detected, its equivocation becomes
DuplicateVoteEvidence in a committed block, and the app is told via ABCI
misbehavior (reference: ``internal/evidence/pool_test.go``,
``internal/consensus/byzantine_test.go``)."""

import asyncio

import pytest

from cometbft_tpu.evidence import EvidencePool
from cometbft_tpu.testing import make_inproc_network
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.evidence import DuplicateVoteEvidence, EvidenceError
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.vote import PRECOMMIT_TYPE, Vote

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def _conflicting_votes(pv, idx, height, round_=0):
    addr = pv.get_pub_key().address()
    a = Vote(type=PRECOMMIT_TYPE, height=height, round=round_,
             block_id=BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32)),
             timestamp_ns=1000, validator_address=addr, validator_index=idx)
    b = Vote(type=PRECOMMIT_TYPE, height=height, round=round_,
             block_id=BlockID(b"\x33" * 32, PartSetHeader(1, b"\x44" * 32)),
             timestamp_ns=1001, validator_address=addr, validator_index=idx)
    await pv.sign_vote("test-net", a, sign_extension=False)
    await pv.sign_vote("test-net", b, sign_extension=False)
    return a, b


def test_pool_accepts_and_serves_valid_duplicate_vote_evidence():
    async def main():
        net = await make_inproc_network(4)
        try:
            await net.start()
            await net.wait_for_height(3, timeout=60)
            node = net.nodes[0]
            pool: EvidencePool = node.consensus.block_exec.evidence_pool
            pv = net.nodes[3].pv
            a, b = await _conflicting_votes(pv, 3, height=2)
            vals = node.state_store.load_validators(2)
            ev_time = node.block_store.load_block(2).header.time_ns
            ev = DuplicateVoteEvidence.from_votes(a, b, ev_time, vals)
            assert pool.add_evidence(ev) is True
            assert pool.is_pending(ev)
            assert pool.add_evidence(ev) is False          # dedupe
            assert ev in pool.pending_evidence(1 << 20)
            # a tampered copy is rejected
            bad = DuplicateVoteEvidence(
                ev.vote_a, ev.vote_b, ev.total_voting_power + 1,
                ev.validator_power, ev.timestamp_ns)
            with pytest.raises(EvidenceError):
                pool.add_evidence(bad)
        finally:
            await net.stop()
        return True

    assert run(main())


def test_pool_check_evidence_rejects_committed():
    async def main():
        net = await make_inproc_network(4)
        try:
            await net.start()
            await net.wait_for_height(3, timeout=60)
            node = net.nodes[0]
            pool: EvidencePool = node.consensus.block_exec.evidence_pool
            pv = net.nodes[3].pv
            a, b = await _conflicting_votes(pv, 3, height=2)
            vals = node.state_store.load_validators(2)
            ev_time = node.block_store.load_block(2).header.time_ns
            ev = DuplicateVoteEvidence.from_votes(a, b, ev_time, vals)
            pool.check_evidence([ev])            # verifies fresh evidence
            with pytest.raises(EvidenceError):
                pool.check_evidence([ev, ev])    # duplicate in one block
            pool.update(pool.state, [ev])        # mark committed
            with pytest.raises(EvidenceError):
                pool.check_evidence([ev])
        finally:
            await net.stop()
        return True

    assert run(main())


def test_byzantine_double_signer_is_punished():
    """A forged conflicting precommit from validator 3 surfaces as
    ConflictingVoteError in peers' vote sets, becomes evidence, rides in a
    proposal, and reaches the app as ABCI misbehavior."""

    async def main():
        net = await make_inproc_network(4)
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
            byz = net.nodes[3]
            byz_addr = byz.pv.get_pub_key().address()
            byz_idx, _ = net.nodes[0].consensus.state.validators \
                .get_by_address(byz_addr)
            # forge a second precommit for whatever height node0 is on
            for _ in range(10):
                h = net.nodes[0].consensus.rs.height
                fake = Vote(
                    type=PRECOMMIT_TYPE, height=h, round=0,
                    block_id=BlockID(b"\x66" * 32,
                                     PartSetHeader(1, b"\x77" * 32)),
                    timestamp_ns=123456,
                    validator_address=byz_addr, validator_index=byz_idx)
                await byz.pv.sign_vote("test-net", fake,
                                       sign_extension=False)
                for node in net.nodes[:3]:
                    node.consensus.feed_vote(fake, "byzantine")
                # wait for the evidence to be committed in a block
                try:
                    await asyncio.wait_for(self_check(net, byz_addr), 5)
                    break
                except asyncio.TimeoutError:
                    continue
            else:
                raise AssertionError("no misbehavior observed")
        finally:
            await net.stop()
        return True

    async def self_check(net, byz_addr):
        while True:
            for node in net.nodes:
                for mb in node.app.misbehavior_seen:
                    if mb.validator_address == byz_addr and \
                            mb.type == "DUPLICATE_VOTE":
                        return
            await asyncio.sleep(0.05)

    assert run(main())


def test_committed_block_carries_evidence():
    """The block that punishes the offender actually contains the
    DuplicateVoteEvidence (proposal path pending_evidence -> block)."""

    async def main():
        net = await make_inproc_network(4)
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
            byz = net.nodes[3]
            byz_addr = byz.pv.get_pub_key().address()
            byz_idx, _ = net.nodes[0].consensus.state.validators \
                .get_by_address(byz_addr)
            h = net.nodes[0].consensus.rs.height
            fake = Vote(type=PRECOMMIT_TYPE, height=h, round=0,
                        block_id=BlockID(b"\x88" * 32,
                                         PartSetHeader(1, b"\x99" * 32)),
                        timestamp_ns=7777,
                        validator_address=byz_addr, validator_index=byz_idx)
            await byz.pv.sign_vote("test-net", fake, sign_extension=False)
            for node in net.nodes[:3]:
                node.consensus.feed_vote(fake, "byzantine")

            async def block_with_evidence():
                while True:
                    for node in net.nodes:
                        for hh in range(1, node.block_store.height() + 1):
                            blk = node.block_store.load_block(hh)
                            for ev in blk.evidence:
                                if isinstance(ev, DuplicateVoteEvidence) \
                                        and ev.vote_a.validator_address \
                                        == byz_addr:
                                    return hh
                    await asyncio.sleep(0.05)

            hh = await asyncio.wait_for(block_with_evidence(), 30)
            assert hh > 0
        finally:
            await net.stop()
        return True

    assert run(main())
