"""Batched SHA-256 merkle subsystem: golden vectors against the hashlib
reference.

Every engine behind the size-based dispatch (pure-Python level builder,
native C++ ``kv_merkle_levels``, batched JAX level kernel) must be
bit-identical to the recursive RFC-6962 reference — roots AND full proof
sets — including leaf/inner domain separation, the largest-power-of-two
split point, and the promote-odd level-order equivalence the iterative
builders rely on."""

import hashlib

import numpy as np
import pytest

from cometbft_tpu.crypto import merkle

GOLDEN_NS = [0, 1, 2, 3, 10, 1000]
EDGE_NS = [4, 5, 6, 7, 8, 9, 15, 16, 17, 63, 64, 65, 127, 128, 129, 255]


def _items(n, seed=7, max_len=64):
    rng = np.random.default_rng(seed)
    return [bytes(rng.integers(0, 256, int(rng.integers(0, max_len + 1)),
                               dtype=np.uint8)) for _ in range(n)]


def _assert_proofs_equal(got, ref):
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        assert g.total == r.total and g.index == r.index
        assert g.leaf_hash == r.leaf_hash
        assert list(g.aunts) == list(r.aunts)


# ------------------------------------------------------------ raw kernel

def test_sha256_blocks_matches_hashlib():
    import jax

    from cometbft_tpu.ops import sha256 as s

    rng = np.random.default_rng(0)
    lens = rng.integers(0, 119, 16)
    msgs = np.zeros((16, 120), np.uint8)
    for i, ln in enumerate(lens):
        msgs[i, :ln] = rng.integers(0, 256, ln)
    blocks, active = s.host_pad(msgs, lens, 2)
    out = np.asarray(jax.jit(s.sha256_blocks)(blocks, active), np.uint8)
    for i in range(16):
        want = hashlib.sha256(bytes(msgs[i, :lens[i]])).digest()
        assert bytes(out[i]) == want


def test_merkle_inner_level_matches_hashlib():
    import jax

    from cometbft_tpu.ops import sha256 as s

    rng = np.random.default_rng(1)
    left = rng.integers(0, 256, (16, 32), dtype=np.uint8)
    right = rng.integers(0, 256, (16, 32), dtype=np.uint8)
    out = s.words_to_bytes(np.asarray(jax.jit(s.merkle_inner_level)(
        s.bytes_to_words(left), s.bytes_to_words(right))))
    for i in range(16):
        want = hashlib.sha256(
            b"\x01" + bytes(left[i]) + bytes(right[i])).digest()
        assert bytes(out[i]) == want


def test_digest_word_roundtrip():
    from cometbft_tpu.ops import sha256 as s

    rng = np.random.default_rng(2)
    d = rng.integers(0, 256, (7, 32), dtype=np.uint8)
    assert np.array_equal(s.words_to_bytes(s.bytes_to_words(d)), d)


# ------------------------------------------------- domain separation

def test_domain_separation_and_empty():
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    assert merkle.leaf_hash(b"abc") == hashlib.sha256(b"\x00abc").digest()
    assert merkle.inner_hash(b"L" * 32, b"R" * 32) == hashlib.sha256(
        b"\x01" + b"L" * 32 + b"R" * 32).digest()
    # a leaf never collides with an inner node of the same bytes
    assert merkle.leaf_hash(b"x") != hashlib.sha256(b"\x01x").digest()


def test_rfc6962_split_point():
    # split at the largest power of two STRICTLY below n: for n=6 the
    # left subtree takes 4 leaves, not 3 (pinned explicitly — the
    # balanced-split would produce a different root)
    items = _items(6, seed=11)
    left = merkle.hash_from_byte_slices(items[:4])
    right = merkle.hash_from_byte_slices(items[4:])
    assert merkle.hash_from_byte_slices(items) == \
        merkle.inner_hash(left, right)


# ------------------------------------------------------- golden vectors

@pytest.mark.parametrize("n", GOLDEN_NS)
def test_golden_roots_and_proofs(n):
    items = _items(n)
    ref_root, ref_proofs = merkle.proofs_from_byte_slices_reference(items)
    assert ref_root == merkle.hash_from_byte_slices(items)

    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == ref_root
    _assert_proofs_equal(proofs, ref_proofs)
    assert merkle.hash_from_byte_slices_fast(items) == ref_root

    for i in (0, n // 2, n - 1) if n else ():
        assert proofs[i].verify(root, items[i])
        assert not proofs[i].verify(root, items[i] + b"x")


@pytest.mark.parametrize("n", EDGE_NS)
def test_level_order_equals_recursive(n):
    """The promote-odd level-order build IS the recursive split tree."""
    items = _items(n, seed=n)
    ref_root, ref_proofs = merkle.proofs_from_byte_slices_reference(items)
    levels = merkle._levels_hashlib(items)
    assert levels[-1][0] == ref_root
    root, proofs = merkle._proofs_from_levels(levels, n)
    assert root == ref_root
    _assert_proofs_equal(proofs, ref_proofs)


@pytest.mark.parametrize("n", [1, 2, 3, 10, 129, 1000])
def test_native_levels_engine(n):
    items = _items(n, seed=n + 100)
    levels = merkle._levels_native(items)
    if levels is None:
        pytest.skip("native kvstore lib unavailable")
    assert levels == merkle._levels_hashlib(items)


@pytest.mark.parametrize("n", [2, 3, 10, 1000])
def test_kernel_levels_engine(n):
    """The batched JAX level kernel, forced on the CPU backend."""
    items = _items(n, seed=n + 200)
    levels = merkle._levels_kernel(items)
    if levels is None:
        pytest.skip("jax unavailable for the merkle kernel")
    assert levels == merkle._levels_hashlib(items)
    ref_root, ref_proofs = merkle.proofs_from_byte_slices_reference(items)
    root, proofs = merkle._proofs_from_levels(levels, n)
    assert root == ref_root
    _assert_proofs_equal(proofs, ref_proofs)


def test_kernel_root_only():
    items = _items(1000, seed=42)
    root = merkle._root_kernel(items)
    if root is None:
        pytest.skip("jax unavailable for the merkle kernel")
    assert root == merkle.hash_from_byte_slices(items)


def test_kernel_big_leaves_route():
    """Items past the leaf-kernel bucket hash through hashlib but the
    levels still ride the kernel."""
    items = _items(200, seed=43, max_len=300)
    levels = merkle._levels_kernel(items)
    if levels is None:
        pytest.skip("jax unavailable for the merkle kernel")
    assert levels == merkle._levels_hashlib(items)


def test_kernel_dispatch_env_force(monkeypatch):
    monkeypatch.setenv("TPU_BFT_MERKLE_KERNEL", "1")
    items = _items(4096, seed=44, max_len=40)
    ref_root, ref_proofs = merkle.proofs_from_byte_slices_reference(items)
    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == ref_root
    _assert_proofs_equal(proofs, ref_proofs)
    assert merkle.hash_from_byte_slices_fast(items) == ref_root
    monkeypatch.setenv("TPU_BFT_MERKLE_KERNEL", "0")
    root2, proofs2 = merkle.proofs_from_byte_slices(items)
    assert root2 == ref_root
    _assert_proofs_equal(proofs2, ref_proofs)


# ------------------------------------------------------------- consumers

def test_part_set_proofs_through_dispatch():
    from cometbft_tpu.types.part_set import PartSet

    rng = np.random.default_rng(9)
    data = bytes(rng.integers(0, 256, 100 * 1024, dtype=np.uint8))
    ps = PartSet.from_data(data, part_size=1024)    # 100 parts: level path
    assert ps.is_complete()
    header = ps.header()
    # every proof must verify against the header hash on a fresh set
    fresh = PartSet(header)
    for i in range(ps.total):
        assert fresh.add_part(ps.get_part(i))
    assert fresh.get_data() == data


def test_value_op_roundtrip_with_levelorder_proofs():
    """ProofOps serialize/verify with proofs from the batched builder
    (tuple aunt paths must survive msgpack)."""
    from cometbft_tpu.crypto.merkle import (ProofOperators, ValueOp,
                                            kv_leaf, leaf_hash)

    keys = [b"k%03d" % i for i in range(80)]
    vals = [b"v%03d" % i for i in range(80)]
    leaves = [kv_leaf(k, v) for k, v in zip(keys, vals)]
    root, proofs = merkle.proofs_from_byte_slices(leaves)
    op = ValueOp(keys[17], proofs[17])
    assert proofs[17].leaf_hash == leaf_hash(leaves[17])
    decoded = ValueOp.decode(op.proof_op())
    ops = ProofOperators([decoded])
    ops.verify(root, [keys[17]], vals[17])          # raises on mismatch


def test_data_hash_matches_reference():
    from cometbft_tpu.types.header import Data, tx_hash

    txs = _items(300, seed=13, max_len=200)
    want = merkle.hash_from_byte_slices([tx_hash(t) for t in txs])
    assert Data(txs=list(txs)).hash() == want
