"""Deterministic fault-injection plane (libs/failures) + seeded chaos
acceptance.

Fast tier: plane semantics (seeded schedules, same-seed reproducibility,
spec parsing, env arming, phased arm/disarm), the per-site behavior of
the MConnection send/recv faults, the device dispatch hang/raise
rehearsal, and the fsyncgate halt-and-recover contract on a real node.

Slow tier: the 4-node mixed-fault acceptance run — partition, message
corruption, a device hang, and an fsync-EIO crash on one seeded
schedule, asserting safety (identical hashes), liveness (progress after
faults stop), a watchdog incident bundle for the halt, clean recovery of
the crashed node through the existing replay path, and that re-running
the same seed reproduces the identical fault event log.
"""

import asyncio
import errno
import os
import time

import pytest

from cometbft_tpu.libs import failures as F


@pytest.fixture(autouse=True)
def _clean_plane():
    """No chaos leaks into (or out of) any test."""
    F.reset()
    yield
    F.reset()


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ------------------------------------------------------------ plane: unit


def test_disabled_plane_is_a_noop():
    assert not F.is_enabled()
    assert F.fire("wal.fsync.eio") is None
    assert F.events() == [] and F.signature() == []
    assert F.stats() == {"enabled": False}


def test_rule_triggers_at_count_every_after_max():
    F.configure(enabled=True, seed=1, faults=[
        "a:at=2:at=5", "b:count=3", "c:every=3:max=2", "d:after=2:count=2"])
    fired = {s: [] for s in "abcd"}
    for n in range(1, 10):
        for s in "abcd":
            if F.fire(s) is not None:
                fired[s].append(n)
    assert fired["a"] == [2, 5]
    assert fired["b"] == [1, 2, 3]
    assert fired["c"] == [3, 6]            # every=3, bounded by max=2
    assert fired["d"] == [3, 4]            # offset by after=2


def test_same_seed_reproduces_identical_event_log():
    """The acceptance property in miniature: two same-seed drives of the
    same call pattern (including a probabilistic site) produce the
    identical fault event log."""

    def drive():
        F.configure(enabled=True, seed=99, faults=[
            "p.q:prob=0.25:max=6", "r.s:every=7", "t.u:at=11:delay=2.5"])
        for _ in range(40):
            F.fire("p.q")
            F.fire("r.s", chan="vote")
            F.fire("t.u")
        return F.signature(), [(e["site"], e["n"], e.get("delay"))
                               for e in F.events()]

    sig1, ev1 = drive()
    sig2, ev2 = drive()
    assert sig1 and sig1 == sig2
    assert ev1 == ev2
    assert ("t.u", 11, 2.5) in ev1          # params ride the event
    # a different seed moves the probabilistic fires
    F.configure(enabled=True, seed=100, faults=["p.q:prob=0.25:max=6"])
    for _ in range(40):
        F.fire("p.q")
    assert F.signature() != [s for s in sig1 if s[0] == "p.q"]


def test_fault_spec_parsing_and_errors():
    r = F.parse_fault_spec("wal.fsync.eio:at=40")
    assert r.site == "wal.fsync.eio" and r.at == {40}
    r = F.parse_fault_spec("x:prob=0.5:max=3:delay=1.5:cut=header")
    assert r.prob == 0.5 and r.max_fires == 3
    assert r.params == {"delay": 1.5, "cut": "header"}
    for bad in ("", "prob=1", "x:notakv", "x:prob=2", "x:at=abc"):
        with pytest.raises(F.FaultSpecError):
            F.parse_fault_spec(bad)
    # config validation surfaces spec errors at load time
    from cometbft_tpu.config import Config, ConfigError

    cfg = Config()
    cfg.chaos.enable = True
    cfg.chaos.faults = ["x:prob=2"]
    with pytest.raises(ConfigError):
        cfg.validate()


def test_env_var_arms_plane_over_config(monkeypatch):
    from cometbft_tpu.config import ChaosConfig

    monkeypatch.setenv(F.ENV_VAR,
                       "seed=9;log=4096;wal.fsync.eio:at=2;p.q:prob=0.1")
    F.configure_from_config(ChaosConfig())        # section disabled
    assert F.is_enabled()
    st = F.stats()
    assert st["seed"] == 9 and set(st["sites"]) == {"wal.fsync.eio", "p.q"}
    monkeypatch.delenv(F.ENV_VAR)
    F.reset()
    # without the env var, a disabled section leaves the plane down
    F.configure_from_config(ChaosConfig())
    assert not F.is_enabled()
    # and an enabled section arms it
    F.configure_from_config(ChaosConfig(enable=True, seed=3,
                                        faults=["a.b:at=1"]))
    assert F.is_enabled() and F.stats()["seed"] == 3


def test_phased_arm_disarm_keeps_log_and_counters():
    F.configure(enabled=True, seed=4, faults=["a:at=1"])
    assert F.fire("a") is not None
    F.arm("b:at=2")
    with pytest.raises(F.FaultSpecError):
        F.arm("b:at=3")                    # double-arm refused
    assert F.fire("b") is None and F.fire("b") is not None
    F.disarm("b")
    assert F.fire("b") is None
    # the log kept everything from before the disarm
    assert F.signature() == [("a", 1, 1), ("b", 2, 1)]


# -------------------------------------------------------- p2p conn sites


async def _mconn_net(descs):
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.p2p.conn import MConnection
    from cometbft_tpu.p2p.secret_connection import handshake

    accepted = asyncio.get_running_loop().create_future()

    async def on_conn(r, w):
        accepted.set_result((r, w))

    server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
    host, port = server.sockets[0].getsockname()[:2]
    r1, w1 = await asyncio.open_connection(host, port)
    r2, w2 = await accepted
    c1, c2 = await asyncio.gather(
        handshake(r1, w1, Ed25519PrivKey.generate()),
        handshake(r2, w2, Ed25519PrivKey.generate()))
    got1, got2 = [], []
    m1 = MConnection(c1, descs, lambda ch, m: got1.append((ch, m)),
                     lambda e: got1.append(("err", e)))
    m2 = MConnection(c2, descs, lambda ch, m: got2.append((ch, m)),
                     lambda e: got2.append(("err", e)))
    m1.start(), m2.start()
    return server, m1, m2, got1, got2


async def _drain(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, "condition never met"
        await asyncio.sleep(0.01)


def test_mconn_send_drop_and_recv_corrupt():
    from cometbft_tpu.p2p.reactor import ChannelDescriptor

    async def main():
        descs = [ChannelDescriptor(0x20, name="vote")]
        server, m1, m2, got1, got2 = await _mconn_net(descs)
        # first data packet dropped: the message silently vanishes
        F.configure(enabled=True, seed=7, faults=["p2p.send.drop:at=1"])
        assert m1.send(0x20, b"swallowed")
        await asyncio.sleep(0.3)
        assert got2 == []
        # next message passes (at=1 exhausted)
        assert m1.send(0x20, b"alive")
        await _drain(lambda: len(got2) >= 1)
        assert got2 == [(0x20, b"alive")]
        ev = F.events()
        assert [(e["site"], e["chan"]) for e in ev] == \
            [("p2p.send.drop", "vote")]
        # receive-side corruption: delivered, same length, wrong bytes
        F.arm("p2p.recv.corrupt:at=2")     # 2nd complete message POST-arm
        m1.send(0x20, b"ok-2")
        m1.send(0x20, b"corrupt-me")
        await _drain(lambda: len(got2) >= 3)
        assert got2[1] == (0x20, b"ok-2")
        chan, msg = got2[2]
        assert len(msg) == len(b"corrupt-me") and msg != b"corrupt-me"
        await m1.stop(), await m2.stop()
        server.close()
        return True

    assert run(main())


def test_mconn_duplicate_and_reorder():
    from cometbft_tpu.p2p.reactor import ChannelDescriptor

    async def main():
        descs = [ChannelDescriptor(0x20, name="vote")]
        server, m1, m2, got1, got2 = await _mconn_net(descs)
        # duplicate the first packet: one send, two deliveries
        F.configure(enabled=True, seed=7,
                    faults=["p2p.send.duplicate:at=1"])
        m1.send(0x20, b"twice")
        await _drain(lambda: len(got2) >= 2)
        assert got2 == [(0x20, b"twice"), (0x20, b"twice")]
        F.disarm("p2p.send.duplicate")
        # reorder: packet A held, B released first, then A
        got2.clear()
        F.arm("p2p.send.reorder:at=1")
        m1.send(0x20, b"A")
        m1.send(0x20, b"B")
        await _drain(lambda: len(got2) >= 2)
        assert got2 == [(0x20, b"B"), (0x20, b"A")]
        await m1.stop(), await m2.stop()
        server.close()
        return True

    assert run(main())


def test_mconn_reorder_flushes_held_packet_at_idle():
    """A reordered packet with no follow-up traffic must still arrive
    (released at wire idle), or a quiet channel would lose its tail."""
    from cometbft_tpu.p2p.reactor import ChannelDescriptor

    async def main():
        descs = [ChannelDescriptor(0x20, name="vote")]
        server, m1, m2, got1, got2 = await _mconn_net(descs)
        F.configure(enabled=True, seed=7, faults=["p2p.send.reorder:at=1"])
        m1.send(0x20, b"lonely")
        await _drain(lambda: len(got2) >= 1, timeout=3.0)
        assert got2 == [(0x20, b"lonely")]
        await m1.stop(), await m2.stop()
        server.close()
        return True

    assert run(main())


def test_fuzzer_routes_through_fault_plane():
    """Armed p2p.fuzz.* sites override the fuzzer's local probability
    draw, so connection fuzzing composes with chaos schedules (and its
    decisions land in the shared event log)."""
    from cometbft_tpu.p2p.fuzz import FuzzConnConfig, _Fuzzer

    class _W:
        closed = False

        def close(self):
            self.closed = True

    async def main():
        F.configure(enabled=True, seed=5,
                    faults=["p2p.fuzz.drop:at=2", "p2p.fuzz.kill:at=3"])
        # local probabilities all zero: only the plane can fire
        w = _W()
        fz = _Fuzzer(FuzzConnConfig(prob_drop_rw=0.0, start_after_s=0.0,
                                    seed=1), w)
        assert await fz.fuzz() is False
        assert await fz.fuzz() is True          # plane drop
        assert await fz.fuzz() is True and w.closed   # plane kill
        assert [e["site"] for e in F.events()] == \
            ["p2p.fuzz.drop", "p2p.fuzz.kill"]
        return True

    assert run(main())


# ----------------------------------------------------- device + storage


def test_device_dispatch_hang_and_raise_degrade_to_host():
    from cometbft_tpu.crypto import batch as B

    gauge, abandoned = B._device_health()
    before = abandoned.value()
    old_wait = B._DEVICE_WAIT_S
    B.set_device_wait(0.1)
    try:
        F.configure(enabled=True, seed=3,
                    faults=["device.dispatch.hang:at=1:delay=0.4",
                            "device.dispatch.raise:at=2"])
        # 1) hang past the bounded wait: abandoned, degraded gauge up
        assert B._device_call(lambda: 11) is None
        assert gauge.value() == 1
        assert abandoned.value() == before + 1
        time.sleep(0.5)                 # let the wedged future drain
        # 2) raise: same degrade path, NEVER an exception to the caller
        assert B._device_call(lambda: 12) is None
        assert abandoned.value() == before + 2
        # 3) recovered: next dispatch answers and clears the gauge
        assert B._device_call(lambda: 13) == 13
        assert gauge.value() == 0
        assert [(e["site"], e["n"]) for e in F.events()] == \
            [("device.dispatch.hang", 1), ("device.dispatch.raise", 2)]
    finally:
        B.set_device_wait(old_wait)


def test_logdb_enospc_fails_handle_closed(tmp_path):
    from cometbft_tpu.storage.db import LogDB

    F.configure(enabled=True, seed=1, faults=["db.append.enospc:at=2"])
    db = LogDB(str(tmp_path / "kv.db"))
    db.set(b"a", b"1")
    with pytest.raises(OSError) as ei:
        db.set(b"b", b"2")
    assert ei.value.errno == errno.ENOSPC
    # fsyncgate: the handle is dead, no retry on the same fd
    with pytest.raises(OSError):
        db.set(b"c", b"3")
    db.close()
    F.reset()
    # restart replays the intact prefix: 'a' survived, 'b' never landed
    db2 = LogDB(str(tmp_path / "kv.db"))
    assert db2.get(b"a") == b"1" and db2.get(b"b") is None
    db2.set(b"d", b"4")                 # and the fresh handle writes
    db2.close()


# ------------------------------------------------ fsyncgate on a live node


def _genesis(n, chain_id, secret=b"chaos"):
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    pvs = [MockPV.from_secret(secret + b"%d" % i) for i in range(n)]
    doc = GenesisDoc(chain_id=chain_id,
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])
    return doc, pvs


async def _mk_node(doc, pv, i, *, home=None, watchdog=False,
                   name_prefix="chaos", tweak=None, fast_sync=False):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey

    cfg = Config(consensus=test_consensus_config())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = ""
    cfg.base.signature_backend = "cpu"
    if watchdog:
        cfg.instrumentation.watchdog_stall_threshold_s = 2.0
        cfg.instrumentation.watchdog_check_interval_s = 0.25
    else:
        cfg.instrumentation.watchdog_stall_threshold_s = 0.0
    if tweak is not None:
        tweak(cfg)
    node = await Node.create(
        doc, KVStoreApplication(), priv_validator=pv, config=cfg,
        node_key=NodeKey.from_secret(b"%s-%d" % (name_prefix.encode(), i)),
        home=home, name=f"{name_prefix}{i}", fast_sync=fast_sync)
    await node.start()
    return node


async def _wait_height(nodes, h, timeout=60.0):
    deadline = time.monotonic() + timeout
    while not all(n.height() >= h for n in nodes):
        assert time.monotonic() < deadline, \
            f"heights {[n.height() for n in nodes]} stuck below {h}"
        await asyncio.sleep(0.05)


def _find_bundle(inc_dir, reason, deadline_s=10.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            names = [n for n in os.listdir(inc_dir) if reason in n
                     and n.endswith(".json")]
        except OSError:
            names = []
        if names:
            return names[0]
        time.sleep(0.1)
    return None


@pytest.mark.timeout(120)
def test_wal_fsync_eio_halts_fatally_and_recovers_on_restart(tmp_path):
    """The fsyncgate regression (via the ``wal.fsync.eio`` site): an
    injected fsync failure halts consensus with ``fatal_error`` set (the
    watchdog bundles it) instead of being swallowed by the generic
    handler-error counter; a restart on the same home replays the WAL
    and keeps committing."""
    home = str(tmp_path / "solo")
    doc, pvs = _genesis(1, "fsyncgate-net", secret=b"fg")

    async def crash_phase():
        F.configure(enabled=True, seed=11, faults=["wal.fsync.eio:at=10"])
        node = await _mk_node(doc, pvs[0], 0, home=home, watchdog=True)
        try:
            deadline = time.monotonic() + 30
            while node.consensus.fatal_error is None:
                assert time.monotonic() < deadline, "never went fatal"
                await asyncio.sleep(0.05)
            err = node.consensus.fatal_error
            assert isinstance(err, OSError) and err.errno == errno.EIO
            # the WAL is dead: no retry on the same fd
            from cometbft_tpu.consensus.wal import WALError

            with pytest.raises(WALError):
                node.consensus.wal.flush_and_sync()
            # the watchdog turns the halt into an incident bundle
            bundle = await asyncio.to_thread(
                _find_bundle, node.incident_dir(), "consensus_fatal_error")
            assert bundle is not None, "no incident bundle for the halt"
            return node.height()
        finally:
            await node.stop()

    h_crash = run(crash_phase())
    F.reset()

    async def recover_phase():
        node = await _mk_node(doc, pvs[0], 0, home=home, watchdog=True)
        try:
            await _wait_height([node], h_crash + 2, timeout=60)
            assert node.consensus.fatal_error is None
        finally:
            await node.stop()
        return True

    assert run(recover_phase())


# ------------------------------------------------------- slow acceptance


async def _acceptance_scenario(base_dir: str) -> list[tuple]:
    """One seeded mixed-fault run; returns the fault-log signature.
    Phases: healthy start -> partition+heal -> message-corruption window
    -> device hang -> fsync-EIO crash -> restart/recover -> safety."""
    from cometbft_tpu.crypto import batch as B

    F.reset()
    F.configure(enabled=True, seed=2026,
                faults=["sched.dispatch.raise:at=1"])
    doc, pvs = _genesis(4, "chaos-net")
    victim_home = os.path.join(base_dir, "victim")
    nodes = []
    for i in range(4):
        nodes.append(await _mk_node(
            doc, pvs[i], i,
            home=victim_home if i == 3 else None,
            watchdog=(i == 3)))
    try:
        # mesh: node1's links are non-persistent so the partition below
        # stays down until explicitly healed; everything else reconnects
        for i, a in enumerate(nodes):
            for j in range(i + 1, 4):
                if 1 in (i, j):
                    continue
                await a.dial_peer(nodes[j].listen_addr, persistent=True)
        for j in (0, 2, 3):
            await nodes[1].dial_peer(nodes[j].listen_addr,
                                     persistent=False)
        await _wait_height(nodes, 3)

        # --- partition: node1 drops off; the 3/4 majority stays live
        for peer in list(nodes[1].switch.peers.values()):
            await nodes[1].switch.stop_peer_gracefully(peer)
        h0 = max(n.height() for n in nodes)
        others = [nodes[0], nodes[2], nodes[3]]
        await _wait_height(others, h0 + 3)
        assert nodes[1].height() < h0 + 3, "partition did not isolate"
        # heal (persistent now: later fault-induced teardowns reconnect)
        for j in (0, 2, 3):
            await nodes[1].dial_peer(nodes[j].listen_addr,
                                     persistent=True)
        await _wait_height(nodes, max(n.height() for n in nodes) + 2)

        # --- message-corruption window: every 15th delivered message,
        # 10 total; codec/signature rejection and reconnects absorb it
        F.arm("p2p.recv.corrupt:every=15:max=10")
        deadline = time.monotonic() + 45
        while sum(1 for e in F.events()
                  if e["site"] == "p2p.recv.corrupt") < 10:
            assert time.monotonic() < deadline, "corruption never drained"
            await asyncio.sleep(0.1)
        await _wait_height(nodes, max(n.height() for n in nodes) + 2)

        # --- scheduler dispatch failure: force one micro-batch through
        # the armed site (in-proc nets cache-hit around natural
        # batches); the injected raise must still demux REAL per-item
        # verdicts to every batchmate
        from cometbft_tpu.crypto import scheduler as vsched
        from cometbft_tpu.crypto.keys import gen_priv_key

        sched = vsched.get_scheduler()
        assert sched is not None and sched.is_running
        privs = [gen_priv_key() for _ in range(3)]
        msgs = [b"chaos-acc-%d" % i for i in range(3)]
        sigs = [p.sign(m) for p, m in zip(privs, msgs)]
        sigs[1] = bytes(64)
        oks = await asyncio.gather(*[
            sched.verify(p.pub_key(), m, s)
            for p, m, s in zip(privs, msgs, sigs)])
        assert oks == [True, False, True], oks
        assert any(e["site"] == "sched.dispatch.raise"
                   for e in F.events())

        # --- device hang (CPU rehearsal): the bounded wait abandons the
        # dispatch, verification degrades to host, then recovers
        F.arm("device.dispatch.hang:at=1:delay=0.4")
        old_wait = B._DEVICE_WAIT_S
        B.set_device_wait(0.1)
        try:
            gauge, _ = B._device_health()
            assert B._device_call(lambda: 7) is None
            assert gauge.value() == 1
            await asyncio.sleep(0.5)
            assert B._device_call(lambda: 7) == 7
            assert gauge.value() == 0
        finally:
            B.set_device_wait(old_wait)

        # --- fsync EIO on the victim: fatal halt + incident bundle,
        # while the 3/4 majority keeps committing
        F.arm("wal.fsync.eio:at=3")
        deadline = time.monotonic() + 30
        while nodes[3].consensus.fatal_error is None:
            assert time.monotonic() < deadline, "victim never halted"
            await asyncio.sleep(0.05)
        err = nodes[3].consensus.fatal_error
        assert isinstance(err, OSError) and err.errno == errno.EIO
        h2 = max(n.height() for n in others)
        await _wait_height([nodes[0], nodes[2]], h2 + 3)
        bundle = await asyncio.to_thread(
            _find_bundle, nodes[3].incident_dir(), "consensus_fatal_error")
        assert bundle is not None, "no watchdog bundle for the halt"

        # --- recovery: restart the victim from the same home (WAL torn
        # tail truncated, replay, rejoin, catch up)
        F.disarm("wal.fsync.eio")
        await nodes[3].stop()
        nodes[3] = await _mk_node(doc, pvs[3], 3, home=victim_home,
                                  watchdog=True)
        for j in (0, 1, 2):
            await nodes[3].dial_peer(nodes[j].listen_addr,
                                     persistent=True)
        target = max(n.height() for n in nodes[:3]) + 2
        await _wait_height(nodes, target, timeout=90)
        assert nodes[3].consensus.fatal_error is None

        # --- safety: every height every node holds is the same block
        common = min(n.height() for n in nodes)
        assert common >= target - 1
        for h in range(1, common + 1):
            hashes = {n.block_store.load_block(h).hash() for n in nodes
                      if n.block_store.load_block(h) is not None}
            assert len(hashes) == 1, f"fork at height {h}: {hashes}"

        return F.signature()
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass
        F.reset()


@pytest.mark.slow
@pytest.mark.timeout(500)
def test_chaos_acceptance_4node_mixed_faults(tmp_path):
    sig1 = run(_acceptance_scenario(str(tmp_path / "run1")))
    sig2 = run(_acceptance_scenario(str(tmp_path / "run2")))
    # same seed, same scenario -> the identical fault event log
    assert sig1 == sig2
    assert ("wal.fsync.eio", 3, 1) in sig1
    assert ("device.dispatch.hang", 1, 1) in sig1
    assert ("sched.dispatch.raise", 1, 1) in sig1
    corrupts = [s for s in sig1 if s[0] == "p2p.recv.corrupt"]
    assert len(corrupts) == 10
    # every=15 fires at exact call indices — the deterministic schedule
    assert [n for _, n, _ in corrupts] == [15 * k for k in range(1, 11)]


# --------------------------------------------------------------------------
# PR 9 acceptance: the chaos plane as forcing function for the peer-quality
# defense layer — a seeded 3-node run where ONE peer's links are armed with
# p2p.send.corrupt (node=<name> selector): the victim scores it down, issues
# a timed ban, keeps committing off the good peer, and readmits the peer
# after the ban expires; the fault log reproduces identically across two
# same-seed runs.

BADPEER_SEED = 90210
BADPEER_MAX_FIRES = 8
BADPEER_SPEC = f"p2p.send.corrupt:node=bqbad0:every=2:max={BADPEER_MAX_FIRES}"


async def _badpeer_scenario() -> tuple:
    from cometbft_tpu.libs import metrics as m
    from cometbft_tpu.rpc.core import Environment, net_info

    doc, pvs = _genesis(2, "badpeer-net", secret=b"badpeer")
    F.reset()
    F.configure(enabled=True, seed=BADPEER_SEED, faults=[BADPEER_SPEC])

    def victim_tweak(cfg):
        # two scoring events (weight >= 1.5 each) ban; short TTL so the
        # readmission leg fits the test budget
        cfg.p2p.quality_disconnect_score = 1.5
        cfg.p2p.quality_ban_score = 3.5
        cfg.p2p.quality_ban_ttl_s = 1.5
        cfg.p2p.quality_half_life_s = 600.0

    victim = await _mk_node(doc, pvs[0], 0, name_prefix="bq",
                            tweak=victim_tweak)
    good = await _mk_node(doc, pvs[1], 1, name_prefix="bq")
    # the corrupting node: a non-validator observer whose OUTBOUND links
    # are armed via the node= selector (name "bqbad0" = chaos scope)
    bad = await _mk_node(doc, None, 0, name_prefix="bqbad")
    nodes = [victim, good, bad]
    try:
        await good.dial_peer(victim.listen_addr, persistent=True)
        # persistent FROM the bad node's side: it keeps re-dialing after
        # every disconnect/ban, which is what exercises readmission (on
        # the VICTIM's side it is inbound and fully bannable)
        await bad.dial_peer(victim.listen_addr, persistent=True)
        bad_id = bad.node_key.id
        vsw = victim.switch

        await _wait_height([victim, good], 2, timeout=30.0)

        # --- score decay -> timed ban ---------------------------------
        deadline = time.monotonic() + 45
        while vsw.scorer.bans_total < 1:
            assert time.monotonic() < deadline, \
                f"no ban; scorer={vsw.scorer.snapshot()} " \
                f"chaos={F.stats()['sites']}"
            await asyncio.sleep(0.05)
        bans_metric = sum(
            m.counter("p2p_peer_bans_total").value(
                node=victim.node_key.id[:8], reason=r)
            for r in ("malformed_frame", "protocol_error", "invalid_vote",
                      "invalid_part", "invalid_proposal"))
        assert bans_metric >= 1
        ni = await net_info(Environment(victim))
        if vsw.scorer.is_banned(bad_id):     # may already have expired
            assert any(b["node_id"] == bad_id for b in ni["bans"])

        # --- liveness off the good peer THROUGH the ban ---------------
        h_ban = victim.height()
        await _wait_height([victim, good], h_ban + 3, timeout=45.0)

        # --- schedule drains; peer readmitted after expiry ------------
        deadline = time.monotonic() + 60
        while True:
            fired = F.stats()["sites"]["p2p.send.corrupt"]["fired"]
            if fired >= BADPEER_MAX_FIRES and \
                    not vsw.scorer.is_banned(bad_id) and \
                    bad_id in vsw.peers:
                break
            assert time.monotonic() < deadline, \
                f"no readmission: fired={fired} " \
                f"banned={vsw.scorer.is_banned(bad_id)} " \
                f"connected={bad_id in vsw.peers}"
            await asyncio.sleep(0.1)
        # readmitted peer carries its quality history in /net_info
        snap = {p["node_id"]: p for p in vsw.peer_snapshot()}
        assert snap[bad_id]["quality"]["ban_count"] >= 1

        # --- fork-free at every common height -------------------------
        common = min(victim.height(), good.height())
        hashes = []
        for h in range(1, common + 1):
            hs = {n.block_store.load_block(h).hash()
                  for n in (victim, good)
                  if n.block_store.load_block(h) is not None}
            assert len(hs) == 1, f"fork at {h}"
            hashes.append(hs.pop().hex())
        return F.signature(), hashes
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass
        F.reset()


@pytest.mark.slow
@pytest.mark.timeout(400)
def test_badpeer_acceptance_score_ban_readmit():
    sig1, hashes1 = run(_badpeer_scenario())
    sig2, hashes2 = run(_badpeer_scenario())
    # same seed -> identical fault-log signature across the two runs
    assert sig1 == sig2
    corrupts = sorted(s for s in sig1 if s[0] == "p2p.send.corrupt")
    assert len(corrupts) == BADPEER_MAX_FIRES
    # every=2 over the BAD node's send stream only (node= selector):
    # exact call indices, independent of the other nodes' traffic
    assert [n for _, n, _ in corrupts] == \
        [2 * k for k in range(1, BADPEER_MAX_FIRES + 1)]
    assert len(hashes1) >= 5


# --------------------------------------------------------------------------
# PR 10: storage integrity doctor + privval/signer hardening


@pytest.mark.timeout(120)
def test_privval_state_eio_halts_fatally_with_bundle(tmp_path):
    """The privval fsyncgate satellite (via ``privval.state.fsync.eio``):
    a failed sign-state persist must NOT release the signature — the
    node halts fatally (watchdog bundles it) instead of signing on top
    of an unknown on-disk guard; a restart on the same home recovers."""
    from cometbft_tpu.privval import FilePV, SignStateError

    home = str(tmp_path / "solo")
    key_path = str(tmp_path / "pvkey.json")
    state_path = os.path.join(home, "data", "priv_validator_state.json")
    pv = FilePV.generate(key_path, state_path)
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    doc = GenesisDoc(chain_id="pv-eio-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)])

    async def crash_phase():
        F.configure(enabled=True, seed=5,
                    faults=["privval.state.fsync.eio:at=5"])
        node = await _mk_node(doc, pv, 0, home=home, watchdog=True)
        try:
            deadline = time.monotonic() + 30
            while node.consensus.fatal_error is None:
                assert time.monotonic() < deadline, "never went fatal"
                await asyncio.sleep(0.05)
            err = node.consensus.fatal_error
            assert isinstance(err, OSError) and err.errno == errno.EIO
            # the privval handle is dead: every further sign refuses
            from cometbft_tpu.types.block_id import BlockID
            from cometbft_tpu.types.vote import PREVOTE_TYPE, Vote

            dead_probe = Vote(
                type=PREVOTE_TYPE, height=99, round=0,
                block_id=BlockID(), timestamp_ns=1,
                validator_address=pv.get_pub_key().address(),
                validator_index=0)
            with pytest.raises(SignStateError):
                await pv.sign_vote(doc.chain_id, dead_probe,
                                   sign_extension=False)
            assert dead_probe.signature == b""    # never released
            bundle = await asyncio.to_thread(
                _find_bundle, node.incident_dir(), "consensus_fatal_error")
            assert bundle is not None, "no incident bundle for the halt"
            return node.height()
        finally:
            await node.stop()

    h_crash = run(crash_phase())
    F.reset()

    async def recover_phase():
        # restart reloads the sign state that DID land: double-sign
        # protection intact, consensus resumes
        pv2 = FilePV.load(key_path, state_path)
        node = await _mk_node(doc, pv2, 0, home=home, watchdog=True)
        try:
            await _wait_height([node], h_crash + 2, timeout=60)
            assert node.consensus.fatal_error is None
        finally:
            await node.stop()
        return True

    assert run(recover_phase())


# --------------------------------------------------------------------------
# PR 10 acceptance: seeded mid-log blockstore corruption -> boot-time
# detection (salvage + doctor deep scan) -> repair (truncate to last
# verified height) -> blocksync re-fetch -> fork-free, run twice with
# identical fault signatures.  The victim is a REAL FilePV validator: its
# persisted last-sign-state is what makes the mid-round rejoin
# equivocation-free (re-signs return the stored signature).

DOCTOR_SEED = 77010
DOCTOR_SPEC = "db.replay.corrupt:file=blockstore.db:at=1:frac=0.5"


async def _doctor_scenario(base_dir: str) -> tuple:
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    F.reset()
    victim_home = os.path.join(base_dir, "victim")
    pvs = [MockPV.from_secret(b"drv%d" % i) for i in range(2)]
    victim_pv = FilePV.generate(
        os.path.join(base_dir, "victim_key.json"),
        os.path.join(victim_home, "data", "priv_validator_state.json"))
    pvs.append(victim_pv)
    doc = GenesisDoc(chain_id="doctor-acc-net",
                     validators=[GenesisValidator(pv.get_pub_key(), 10)
                                 for pv in pvs])
    nodes = []
    for i in range(3):
        nodes.append(await _mk_node(
            doc, pvs[i], i, home=victim_home if i == 2 else None,
            name_prefix="dr"))
    try:
        for i in range(3):
            for j in range(i + 1, 3):
                await nodes[i].dial_peer(nodes[j].listen_addr,
                                         persistent=True)
        await _wait_height(nodes, 6, timeout=45)
        h_stop = nodes[2].height()
        await nodes[2].stop()

        # ---- arm the seeded bit-flip for the victim's NEXT blockstore
        # open (at-rest bit-rot, file-selected so the other stores'
        # opens don't consume the schedule)
        F.configure(enabled=True, seed=DOCTOR_SEED, faults=[DOCTOR_SPEC])
        victim_pv2 = FilePV.load(
            os.path.join(base_dir, "victim_key.json"),
            os.path.join(victim_home, "data",
                         "priv_validator_state.json"))
        victim = await _mk_node(doc, victim_pv2, 2, home=victim_home,
                                name_prefix="dr", fast_sync=True)
        nodes[2] = victim

        # ---- boot-time detection: salvage fired, the doctor deep scan
        # gated the salvaged store and repaired it
        rep = victim.doctor_report.to_dict()
        assert rep["salvage"].get("blockstore", {}).get(
            "salvaged_this_open"), rep
        assert rep["deep_scan"] is not None, rep
        assert rep["ok"] and rep["refused"] is None, rep
        repaired = rep["deep_scan"].get("truncated_to") is not None or \
            any("ahead" in a for a in rep["actions"])
        assert repaired or rep["deep_scan"]["ok"], rep
        assert not victim.block_store.is_dirty()     # verified or rebuilt

        # ---- blocksync re-fetch + consensus rejoin: all three advance
        for j in (0, 1):
            await victim.dial_peer(nodes[j].listen_addr, persistent=True)
        target = max(h_stop, max(n.height() for n in nodes[:2])) + 2
        await _wait_height(nodes, target, timeout=90)
        assert victim.consensus.fatal_error is None

        # ---- fork-free at EVERY common height
        common = min(n.height() for n in nodes)
        hashes = []
        for h in range(1, common + 1):
            hs = {n.block_store.load_block(h).hash() for n in nodes
                  if n.block_store.load_block(h) is not None}
            assert len(hs) == 1, f"fork at height {h}: {hs}"
            hashes.append(hs.pop().hex())
        return F.signature(), rep["deep_scan"].get("truncated_to"), hashes
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass
        F.reset()


@pytest.mark.slow
@pytest.mark.timeout(400)
def test_doctor_acceptance_corrupt_restart_repair_catchup(tmp_path):
    sig1, trunc1, hashes1 = run(_doctor_scenario(str(tmp_path / "run1")))
    sig2, trunc2, hashes2 = run(_doctor_scenario(str(tmp_path / "run2")))
    # same seed -> the identical fault signature, at the exact call index
    assert sig1 == sig2 == [("db.replay.corrupt", 1, 1)]
    assert len(hashes1) >= 6 and len(hashes2) >= 6
