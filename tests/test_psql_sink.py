"""External SQL event sink (indexer/psql.py — reference
``state/indexer/sink/psql``), exercised against a REAL DB-API backend
(stdlib sqlite3) so the SQL actually executes."""

import json
import sqlite3

import pytest

from cometbft_tpu.abci.types import Event, EventAttribute, ExecTxResult
from cometbft_tpu.indexer.psql import PsqlEventSink, PsqlSinkError


@pytest.fixture
def sink():
    conn = sqlite3.connect(":memory:")
    s = PsqlEventSink(conn=conn, chain_id="sql-chain")
    yield s
    s.close()


def _result(events):
    return ExecTxResult(code=0, data=b"\x01", log="ok", gas_used=5,
                        events=events)


def test_tx_and_block_rows(sink):
    ev = [Event(type="transfer",
                attributes=[EventAttribute(key="sender", value="alice"),
                            EventAttribute(key="amount", value="7")])]
    sink.index(height=3, idx=0, tx=b"tx-bytes", result=_result(ev),
               attrs={"tx.height": "3"})
    sink.index_block(3, [("rewards", [("validator", "v1")])])

    cur = sink.conn.cursor()
    cur.execute("SELECT height, chain_id FROM blocks")
    assert cur.fetchall() == [(3, "sql-chain")]

    cur.execute("SELECT index_in_block, tx_result FROM tx_results")
    rows = cur.fetchall()
    assert len(rows) == 1 and rows[0][0] == 0
    rec = json.loads(rows[0][1])
    assert rec["tx"] == b"tx-bytes".hex() and rec["gas_used"] == 5

    # tx-scoped and block-scoped events distinguished by tx_id
    cur.execute("SELECT type, tx_id FROM events ORDER BY rowid")
    evs = cur.fetchall()
    assert [t for t, _ in evs] == ["transfer", "rewards"]
    assert evs[0][1] is not None and evs[1][1] is None

    cur.execute("SELECT composite_key, value FROM attributes "
                "ORDER BY rowid")
    assert cur.fetchall() == [("transfer.sender", "alice"),
                              ("transfer.amount", "7"),
                              ("rewards.validator", "v1")]


def test_one_block_row_per_height(sink):
    for i in range(3):
        sink.index(height=9, idx=i, tx=b"t%d" % i, result=_result([]),
                   attrs={})
    cur = sink.conn.cursor()
    cur.execute("SELECT COUNT(*) FROM blocks")
    assert cur.fetchone()[0] == 1
    cur.execute("SELECT COUNT(*) FROM tx_results")
    assert cur.fetchone()[0] == 3


def test_rollback_on_failure(sink):
    class Boom:
        type = "x"

        @property
        def attributes(self):
            raise RuntimeError("boom")

    with pytest.raises(RuntimeError):
        sink.index(height=1, idx=0, tx=b"t", result=_result([Boom()]),
                   attrs={})
    cur = sink.conn.cursor()
    cur.execute("SELECT COUNT(*) FROM tx_results")
    assert cur.fetchone()[0] == 0          # partial insert rolled back


def test_write_only_surface(sink):
    with pytest.raises(PsqlSinkError):
        sink.get(b"\x00" * 32)
    with pytest.raises(PsqlSinkError):
        sink.search("tx.height = 1")


def test_missing_driver_is_a_clear_error():
    with pytest.raises(PsqlSinkError, match="psycopg2"):
        PsqlEventSink(dsn="postgres://nowhere/none")


def test_block_indexer_facade_matches_service_signature(sink):
    """IndexerService pumps block events via ``.index(height, events)``;
    the sink's BlockIndexer facade must accept exactly that call."""
    bi = sink.block_indexer()
    bi.index(4, [("upgrade", [("version", "2")])])
    cur = sink.conn.cursor()
    cur.execute("SELECT type, tx_id FROM events")
    assert cur.fetchall() == [("upgrade", None)]
    with pytest.raises(PsqlSinkError):
        bi.search("x = 1")
