"""Field-arithmetic tests: JAX limb ops vs Python big-int ground truth.

All device code goes through jit (the only way it's used in production);
inputs are batched so each op compiles once.
"""

import jax
import numpy as np
import pytest

from cometbft_tpu.ops import fe

P = fe.P_INT
rng = np.random.default_rng(1234)

EDGE = [0, 1, 2, 19, P - 1, P - 2, P, P + 1, 2**255 - 1, 2**255 - 20,
        fe.SQRT_M1_INT, fe.D_INT, (P - 1) // 2, 2**254]

j_add = jax.jit(lambda a, b: fe.freeze(fe.add(a, b)))
j_sub = jax.jit(lambda a, b: fe.freeze(fe.sub(a, b)))
j_mul = jax.jit(lambda a, b: fe.freeze(fe.mul(a, b)))
j_square = jax.jit(lambda a: fe.freeze(fe.square(a)))
j_invert = jax.jit(lambda a: fe.freeze(fe.invert(a)))
j_pow22523 = jax.jit(lambda a: fe.freeze(fe.pow22523(a)))
j_freeze = jax.jit(fe.freeze)
j_is_zero = jax.jit(fe.is_zero)
j_to_bytes = jax.jit(fe.to_bytes32)
j_from_bytes = jax.jit(fe.from_bytes32)
j_sqrt_ratio = jax.jit(lambda u, v: fe.sqrt_ratio(u, v))


def rand_ints(n):
    return [int.from_bytes(rng.bytes(32), "little") % P for _ in range(n)]


def to_limbs_batch(xs):
    return np.stack([fe.limbs_from_int(x) for x in xs])


def pad64(xs):
    """Pad a python list to length 64 so every jit call shares one shape."""
    xs = list(xs)
    assert len(xs) <= 64
    return xs + [0] * (64 - len(xs)), len(xs)


def test_roundtrip_int_limbs():
    for x in EDGE + rand_ints(20):
        assert fe.int_from_limbs(fe.limbs_from_int(x)) == x


@pytest.mark.parametrize("op,pyop", [
    (j_add, lambda a, b: (a + b) % P),
    (j_sub, lambda a, b: (a - b) % P),
    (j_mul, lambda a, b: (a * b) % P),
])
def test_binary_ops(op, pyop):
    xs, n = pad64(EDGE + rand_ints(40))
    ys, _ = pad64(list(reversed(EDGE)) + rand_ints(40))
    out = np.asarray(op(to_limbs_batch(xs), to_limbs_batch(ys)))
    for i in range(n):
        assert fe.int_from_limbs(out[i]) == pyop(xs[i], ys[i]) % P, (i, xs[i], ys[i])


def test_square_and_chains():
    xs, n = pad64(EDGE + rand_ints(30))
    # avoid 0 for inversion ground truth (0^-1 is 0 by the chain; pow(0,p-2)=0 too)
    a = to_limbs_batch(xs)
    sq = np.asarray(j_square(a))
    inv = np.asarray(j_invert(a))
    p2523 = np.asarray(j_pow22523(a))
    for i in range(n):
        x = xs[i]
        assert fe.int_from_limbs(sq[i]) == x * x % P
        assert fe.int_from_limbs(inv[i]) == pow(x, P - 2, P)
        assert fe.int_from_limbs(p2523[i]) == pow(x, (P - 5) // 8, P)


def test_loose_form_stacking():
    # repeated adds stay within the loose bound and stay correct under jit
    xs, n = pad64(rand_ints(8))
    a = to_limbs_batch(xs)

    def chain(a):
        acc = a
        for _ in range(50):
            acc = fe.add(acc, a)
        return fe.freeze(fe.mul(acc, acc))

    out = np.asarray(jax.jit(chain)(a))
    for i in range(n):
        want = (xs[i] * 51) % P
        assert fe.int_from_limbs(out[i]) == want * want % P


def test_freeze_canonical():
    vals, n = pad64([0, 1, P - 1, P, P + 1, 2 * P - 1, 2**255 - 1])
    out = np.asarray(j_freeze(to_limbs_batch(vals)))
    for i in range(n):
        assert fe.int_from_limbs(out[i]) == vals[i] % P
    z = np.asarray(j_is_zero(to_limbs_batch([P, 1] + [0] * 62)))
    assert bool(z[0]) and not bool(z[1])


def test_bytes_roundtrip():
    raw, n = pad64([x % P for x in EDGE] + rand_ints(20))
    a = to_limbs_batch(raw)
    enc = np.asarray(j_to_bytes(a))
    for i in range(n):
        assert bytes(enc[i].astype(np.uint8)) == raw[i].to_bytes(32, "little")
    dec = np.asarray(j_from_bytes(enc))
    for i in range(n):
        assert fe.int_from_limbs(dec[i]) == raw[i]
    # sign-bit masking
    top = np.frombuffer((2**255 + 12345).to_bytes(32, "little"), np.uint8)
    arr = np.broadcast_to(top, (64, 32)).astype(np.int32)
    assert fe.int_from_limbs(np.asarray(j_from_bytes(arr))[0]) == 12345


def test_sqrt_ratio():
    squares = [x * x % P for x in rand_ints(20)]
    nonsq = [x for x in rand_ints(60) if pow(x, (P - 1) // 2, P) != 1][:20]
    denom = rand_ints(20)
    num = [(s * d) % P for s, d in zip(squares, denom)]

    us, n = pad64(squares + nonsq + num)
    vs, _ = pad64([1] * 40 + denom)
    root, ok = j_sqrt_ratio(to_limbs_batch(us), to_limbs_batch(vs))
    root, ok = np.asarray(fe.freeze(root)), np.asarray(ok)
    for i in range(20):
        assert ok[i]
        r = fe.int_from_limbs(root[i])
        assert r * r % P == squares[i]
    for i in range(20, 40):
        assert not ok[i]
    for i in range(40, 60):
        assert ok[i]
        r = fe.int_from_limbs(root[i])
        assert r * r % P == us[i] * pow(vs[i], P - 2, P) % P


j_neg = jax.jit(lambda a: fe.freeze(fe.neg(a)))
j_eq = jax.jit(fe.eq)
j_parity = jax.jit(fe.parity)
j_mul_small = jax.jit(lambda a: fe.freeze(fe.mul_small(a, 32767)))


def rand_loose(n, lim=None):
    """Adversarial loose-form limb arrays: any limbs up to LIMB_MAX."""
    lim = lim or fe.LIMB_MAX
    a = rng.integers(0, lim + 1, size=(n, fe.NLIMBS), dtype=np.int32)
    # seed with crafted all-max / overflow-cascade rows
    a[0] = fe.LIMB_MAX
    a[1] = 0
    a[2] = [7584, 8191, 8191] + [0] * 16 + [8192]  # freeze fold-cascade case
    a[3] = [0] * 19 + [fe.LIMB_MAX]
    a[4] = fe.MASK
    return a


def test_freeze_loose_adversarial():
    a = rand_loose(64)
    out = np.asarray(j_freeze(a))
    for i in range(64):
        want = fe.int_from_limbs(a[i]) % P
        got = fe.int_from_limbs(out[i])
        assert got == want, (i, list(a[i]))
        assert got < P


def test_ops_on_loose_inputs():
    a, b = rand_loose(64), rand_loose(64)[::-1].copy()
    m = np.asarray(j_mul(a, b))
    s = np.asarray(j_sub(a, b))
    ng = np.asarray(j_neg(a))
    ms = np.asarray(j_mul_small(a))
    par = np.asarray(j_parity(a))
    for i in range(64):
        av, bv = fe.int_from_limbs(a[i]), fe.int_from_limbs(b[i])
        assert fe.int_from_limbs(m[i]) == av * bv % P
        assert fe.int_from_limbs(s[i]) == (av - bv) % P
        assert fe.int_from_limbs(ng[i]) == (-av) % P
        assert fe.int_from_limbs(ms[i]) == av * 32767 % P
        assert par[i] == (av % P) & 1


def test_eq_loose():
    xs = rand_ints(32)
    a = to_limbs_batch(xs + xs)
    # b: same values but in a different (loose) representation: add p
    b = np.asarray(j_add(to_limbs_batch([x % P for x in xs] * 2),
                          to_limbs_batch([P] * 64)))
    b = to_limbs_batch([fe.int_from_limbs(b[i]) for i in range(64)])
    eq1 = np.asarray(j_eq(a, b))
    assert eq1.all()
    c = to_limbs_batch([(x + 1) % P for x in xs] * 2)
    assert not np.asarray(j_eq(a, c)).any()


def test_mul_shift_matches_einsum():
    """Both field-multiply implementations (einsum Toeplitz and shifted
    accumulation) agree on random loose-form operands; the shift form is
    the candidate fix for the TPU large-batch HBM cliff and must be
    interchangeable."""
    rng = np.random.default_rng(77)
    a = rng.integers(0, fe.LIMB_MAX + 1, (64, 20)).astype(np.int32)
    b = rng.integers(0, fe.LIMB_MAX + 1, (64, 20)).astype(np.int32)
    r1 = np.asarray(fe._mul_einsum(a, b))
    r2 = np.asarray(fe._mul_shift(a, b))
    for i in range(8):
        v1 = fe.int_from_limbs(r1[i]) % fe.P_INT
        v2 = fe.int_from_limbs(r2[i]) % fe.P_INT
        want = (fe.int_from_limbs(a[i]) * fe.int_from_limbs(b[i])) % fe.P_INT
        assert v1 == want and v2 == want, i
    # loose-form bound holds for both
    assert r1.max() <= fe.LIMB_MAX and r2.max() <= fe.LIMB_MAX
