"""Loadtime generator + report (reference: ``test/loadtime/``)."""

import asyncio
import time

from cometbft_tpu.loadtime import make_load_tx, parse_load_tx


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_load_tx_roundtrip():
    tx = make_load_tx("abc123", 42, size=256, now_ns=1_700_000_000_000_000_000)
    assert len(tx) == 256
    rid, seq, t = parse_load_tx(tx)
    assert (rid, seq, t) == ("abc123", 42, 1_700_000_000_000_000_000)
    assert parse_load_tx(b"k=v") is None
    assert parse_load_tx(b"load:bad") is None
    # kvstore accepts it as a k=v tx
    from cometbft_tpu.abci.kvstore import KVStoreApplication

    assert KVStoreApplication._parse_tx(tx) is not None


def test_report_throughput_window_is_send_to_commit():
    """Throughput must be sustained (first send -> last commit), not the
    burst rate over the block-timestamp span: a starved node committing a
    whole run in two giant blocks would otherwise report ~50x reality."""
    from cometbft_tpu import loadtime

    S = 1_000_000_000  # ns
    t0 = 1_700_000_000 * S

    # 100 txs sent over 10s, committed into just two blocks 0.4s apart
    txs_h1 = [make_load_tx("r", i, size=64, now_ns=t0 + i * S // 10)
              for i in range(50)]
    txs_h2 = [make_load_tx("r", 50 + i, size=64,
                           now_ns=t0 + 5 * S + i * S // 10)
              for i in range(50)]
    blocks = {
        1: (t0 + 11 * S, txs_h1),
        2: (t0 + int(11.4 * S), txs_h2),
        3: (t0 + 12 * S, []),      # commit-time proxy for height 2
    }

    class FakeClient:
        async def call(self, method, **kw):
            if method == "status":
                return {"sync_info": {"latest_block_height": 3}}
            ts, txs = blocks[kw["height"]]
            return {"block": {"hdr": {"ts": ts},
                              "data": {"txs": [t.hex() for t in txs]}}}

    rep = run(loadtime.report(FakeClient()))
    assert rep["txs"] == 100
    assert rep["blocks"] == 2
    # window = ts(h=3) - first send = 12s, NOT ts(2)-ts(1) = 0.4s
    assert abs(rep["window_s"] - 12.0) < 1e-6
    assert abs(rep["throughput_tx_s"] - 100 / 12.0) < 0.1


def test_load_generate_and_report_against_node():
    """Generate ~2s of load at a single-validator node over RPC, then the
    report recovers per-tx latency from committed blocks."""
    from cometbft_tpu import loadtime
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config, test_consensus_config
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.rpc.client import HTTPClient
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    async def main():
        cfg = Config(consensus=test_consensus_config())
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        pv = MockPV.from_secret(b"load0")
        doc = GenesisDoc(chain_id="load-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)])
        node = await Node.create(doc, KVStoreApplication(),
                                 priv_validator=pv, config=cfg,
                                 node_key=NodeKey.from_secret(b"lnk"),
                                 name="load0")
        await node.start()
        try:
            host, port = node.rpc_addr
            client = HTTPClient(host, port)
            gen = await loadtime.generate(client, rate=50, duration_s=2.0,
                                          tx_size=128)
            assert gen["sent"] > 20, gen
            # let the tail commit
            target = node.height() + 2
            while node.height() < target:
                await asyncio.sleep(0.05)
            rep = await loadtime.report(client, run_id=gen["run_id"])
            assert rep["txs"] > 20, rep
            # block header time is BFT time (median of the PREVIOUS
            # round's vote timestamps), so a tx committed immediately can
            # show slightly negative latency — small skew is expected
            assert rep["min_s"] >= -2.0
            assert rep["p50_s"] <= rep["p99_s"] <= rep["max_s"]
            assert rep["max_s"] < 30
            assert rep["throughput_tx_s"] is None or \
                rep["throughput_tx_s"] > 0
        finally:
            await node.stop()
        return True

    assert run(main())
