"""SHA-512 kernel vs hashlib."""

import hashlib

import jax
import numpy as np

from cometbft_tpu.ops import sha512

rng = np.random.default_rng(99)


def run_batch(msgs):
    nb = max(sha512.max_blocks_for_len(len(m)) for m in msgs)
    maxlen = max((len(m) for m in msgs), default=0)
    arr = np.zeros((len(msgs), max(maxlen, 1)), np.uint8)
    lens = np.zeros(len(msgs), np.int64)
    for i, m in enumerate(msgs):
        arr[i, :len(m)] = np.frombuffer(m, np.uint8)
        lens[i] = len(m)
    blocks, active = sha512.host_pad(arr, lens, nb)
    out = np.asarray(jax.jit(sha512.sha512_blocks)(blocks, active))
    return [bytes(out[i].astype(np.uint8)) for i in range(len(msgs))]


def test_vectors_and_hashlib():
    msgs = [
        b"",
        b"abc",
        b"a" * 111,   # exactly fills one block with padding
        b"a" * 112,   # forces a second block
        b"a" * 127,
        b"a" * 128,
        b"a" * 129,
        bytes(range(256)),
    ]
    got = run_batch(msgs)
    for m, g in zip(msgs, got):
        assert g == hashlib.sha512(m).digest(), (len(m), g.hex())


def test_random_lengths_mixed_batch():
    msgs = [rng.bytes(int(n)) for n in rng.integers(0, 300, size=64)]
    got = run_batch(msgs)
    for m, g in zip(msgs, got):
        assert g == hashlib.sha512(m).digest(), len(m)


def test_ed25519_shape_hash():
    # the shape the verify kernel uses: 64-byte prefix + ~150-byte message
    msgs = [rng.bytes(64 + 150) for _ in range(16)]
    got = run_batch(msgs)
    for m, g in zip(msgs, got):
        assert g == hashlib.sha512(m).digest()
