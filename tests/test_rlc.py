"""RLC batch-verification kernel: one cofactored random-linear-
combination verdict per batch (ops/rlc.py), differential against the
per-lane kernel and the pure-Python oracle.  Reference contract:
curve25519-voi's batch verify (crypto/ed25519/ed25519.go:188-221) —
all-or-nothing verdict, per-lane fallback on reject."""

import hashlib

import numpy as np
import jax
import pytest

pytestmark = [pytest.mark.timeout(900), pytest.mark.slow]

from cometbft_tpu.crypto import _ed25519_py as ref
from cometbft_tpu.ops import ed25519, rlc, scalar, fe
from cometbft_tpu.testing import dense_signature_batch

L = scalar.L_INT


def _z(n, seed=3):
    rng = np.random.default_rng(seed)
    return rlc.host_rlc_coeffs(n, rng_bytes=rng.bytes(16 * n))


def test_mul_mod_l_and_sum_mod_l():
    rng = np.random.default_rng(11)
    xs = [int.from_bytes(rng.bytes(32), "little") for _ in range(24)]
    zs = [int.from_bytes(rng.bytes(16), "little") for _ in range(24)]
    x20 = np.stack([fe.limbs_from_int(v) for v in xs]).astype(np.int32)
    z10 = np.stack([fe.limbs_from_int(v)[:scalar.Z_NLIMBS] for v in zs]
                   ).astype(np.int32)
    prod = np.asarray(jax.jit(scalar.mul_mod_l)(x20, z10))
    for i in range(24):
        got = fe.int_from_limbs(prod[i])
        assert got < 2**256 and got % L == (xs[i] * zs[i]) % L, i
    tot = np.asarray(jax.jit(lambda p: scalar.sum_mod_l(p, axis=0))(prod))
    want = sum(fe.int_from_limbs(prod[i]) for i in range(24))
    got = fe.int_from_limbs(tot)
    assert got < 2**256 and got % L == want % L


def test_rlc_accepts_valid_batch():
    args, _ = dense_signature_batch(24, msg_len=80, seed=42)
    ok = jax.jit(rlc.verify_batch_rlc)(*args, _z(24))
    assert bool(np.asarray(ok))


def test_rlc_rejects_each_tamper_surface():
    args, _ = dense_signature_batch(24, msg_len=80, seed=43)
    pub, rb, sb, blocks, active = [np.asarray(a).copy() for a in args]
    fn = jax.jit(rlc.verify_batch_rlc)
    z = _z(24)
    for tamper in ("s", "r", "a", "m"):
        p2, r2, s2, b2 = pub.copy(), rb.copy(), sb.copy(), blocks.copy()
        if tamper == "s":
            s2[3, 0] ^= 1
        elif tamper == "r":
            r2[7, 31] ^= 0x40
        elif tamper == "a":
            p2[11, 5] ^= 2
        else:
            b2[13, 0, 0] ^= 1
        assert not bool(np.asarray(fn(p2, r2, s2, b2, active, z))), tamper
    assert bool(np.asarray(fn(pub, rb, sb, blocks, active, z)))


def test_rlc_padding_lanes_do_not_contribute():
    """z = 0 lanes (padding) are excluded from the sums: corrupt a
    padding lane's signature and the batch verdict must stay True."""
    args, _ = dense_signature_batch(16, msg_len=80, seed=44)
    pub, rb, sb, blocks, active = [np.asarray(a).copy() for a in args]
    mask = np.ones(16, bool)
    mask[12:] = False                      # lanes 12..15 are padding
    z = rlc.host_rlc_coeffs(16, active_mask=mask,
                            rng_bytes=np.random.default_rng(1).bytes(256))
    assert (z[12:] == 0).all() and (z[:12] != 0).any(axis=1).all()
    sb[13, 0] ^= 1                         # tamper INSIDE the padding
    ok = jax.jit(rlc.verify_batch_rlc)(pub, rb, sb, blocks, active, z)
    assert bool(np.asarray(ok))
    sb[5, 0] ^= 1                          # tamper an ACTIVE lane
    ok2 = jax.jit(rlc.verify_batch_rlc)(pub, rb, sb, blocks, active, z)
    assert not bool(np.asarray(ok2))


def test_rlc_invalid_padding_lane_cannot_veto():
    """Regression (ADVICE r5): the per-lane ok_a/ok_r/ok_s bits must be
    masked to ACTIVE lanes before the all-reduce.  A padding lane whose
    pubkey/R fail decompression or whose s is non-canonical contributes
    identity to every sum (z = 0), but its ok bits are False — pre-fix
    that forced a whole-batch false reject."""
    args, _ = dense_signature_batch(16, msg_len=80, seed=45)
    pub, rb, sb, blocks, active = [np.asarray(a).copy() for a in args]
    mask = np.ones(16, bool)
    mask[12:] = False                      # lanes 12..15 are padding
    z = rlc.host_rlc_coeffs(16, active_mask=mask,
                            rng_bytes=np.random.default_rng(2).bytes(256))
    pub[12] = 0xFF                         # not a curve point: ok_a False
    rb[13] = 0xFF                          # not a curve point: ok_r False
    sb[14] = 0xFF                          # s >= L: ok_s False
    ok = jax.jit(rlc.verify_batch_rlc)(pub, rb, sb, blocks, active, z)
    assert bool(np.asarray(ok)), \
        "garbage padding lane vetoed a fully-valid batch"
    # the same garbage on an ACTIVE lane must still reject
    pub[3] = 0xFF
    ok2 = jax.jit(rlc.verify_batch_rlc)(pub, rb, sb, blocks, active, z)
    assert not bool(np.asarray(ok2))


def test_rlc_gather_variant_matches():
    """The cached-table route gives the same verdicts through a valset
    table + scope indices (the steady-state commit path)."""
    n_vals, b = 12, 16
    args, items = dense_signature_batch(b, msg_len=80, seed=45,
                                        n_keys=n_vals)
    pub, rb, sb, blocks, active = [np.asarray(a) for a in args]
    # valset = the distinct keys; scope = each lane's validator index
    uniq, scope = np.unique(pub, axis=0, return_inverse=True)
    tab, ok_a = jax.jit(ed25519.prepare_pubkey_tables)(uniq.astype(np.int32))
    fn = jax.jit(rlc.verify_batch_rlc_gather)
    z = _z(b)
    ok = fn(tab, ok_a, scope.astype(np.int32), rb, sb, blocks, active, z)
    assert bool(np.asarray(ok))
    sb2 = np.asarray(sb).copy()
    sb2[4, 2] ^= 8
    ok2 = fn(tab, ok_a, scope.astype(np.int32), rb, sb2, blocks, active, z)
    assert not bool(np.asarray(ok2))


def test_rlc_accepts_zip215_torsion_edge_cases():
    """Lanes whose defect is pure torsion (mixed-order A, small-order R,
    non-canonical identity A) are ZIP-215-valid and must pass the
    cofactored RLC equation too."""
    rng = np.random.default_rng(46)
    pubs, sigs, msgs = [], [], []

    # mixed-order pubkey: A' + T8, signature over the mixed encoding
    def torsion8():
        while True:
            enc = rng.bytes(32)
            pt = ref.pt_decompress_zip215(enc)
            if pt is None:
                continue
            t = ref.pt_mul(ref.L, pt)
            if not ref.pt_equal(t, ref.IDENTITY) and \
               not ref.pt_equal(ref.pt_mul(4, t), ref.IDENTITY):
                return t

    t8 = torsion8()
    seed2 = rng.bytes(32)
    h0 = hashlib.sha512(seed2).digest()
    a_sc = ref._clamp(h0[:32])
    prefix = h0[32:]
    mixed = ref.pt_compress(ref.pt_add(ref.pt_mul(a_sc, ref.BASE), t8))
    m3 = rng.bytes(50)
    r_sc = ref.sc_reduce64(hashlib.sha512(prefix + m3).digest())
    r_enc = ref.pt_compress(ref.pt_mul(r_sc, ref.BASE))
    k_sc = ref.sc_reduce64(hashlib.sha512(r_enc + mixed + m3).digest())
    sig3 = r_enc + ((r_sc + k_sc * a_sc) % L).to_bytes(32, "little")
    assert ref.verify_zip215(mixed, m3, sig3)
    pubs.append(mixed); sigs.append(sig3); msgs.append(m3)

    # small-order R with non-canonical identity A: S=0, R=T8
    ident_nc = (1 + fe.P_INT).to_bytes(32, "little")
    sig_t = ref.pt_compress(t8) + (0).to_bytes(32, "little")
    assert ref.verify_zip215(ident_nc, b"x", sig_t)
    pubs.append(ident_nc); sigs.append(sig_t); msgs.append(b"x")

    # fill with ordinary valid lanes to a padded width of 4
    while len(pubs) < 4:
        sd = rng.bytes(32)
        m = rng.bytes(50)
        pubs.append(ref.public_key_from_seed(sd))
        sigs.append(ref.sign(sd, m)); msgs.append(m)

    from cometbft_tpu.ops import sha512
    b = len(pubs)
    hin = np.zeros((b, 64 + 50), np.uint8)
    lens = np.zeros(b, np.int64)
    for i, (p, s, m) in enumerate(zip(pubs, sigs, msgs)):
        full = s[:32] + p + m
        hin[i, :len(full)] = np.frombuffer(full, np.uint8)
        lens[i] = len(full)
    blocks, active = sha512.host_pad(hin, lens, 2)
    arr = lambda bs: np.stack(
        [np.frombuffer(x, np.uint8) for x in bs]).astype(np.int32)
    ok = jax.jit(rlc.verify_batch_rlc)(
        arr(pubs), arr([s[:32] for s in sigs]),
        arr([s[32:] for s in sigs]), blocks, active, _z(b))
    assert bool(np.asarray(ok))
