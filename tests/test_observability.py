"""Observability: metrics registry + exposition, structured logger,
tx/block indexers and their RPC routes (reference: ``libs/metrics``,
``libs/log``, ``state/txindex``)."""

import asyncio
import io
import json

import pytest

from cometbft_tpu.libs import log as tmlog
from cometbft_tpu.libs.metrics import Counter, Gauge, Histogram, Registry

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_metrics_counter_gauge_histogram_exposition():
    reg = Registry()
    c = reg.register(Counter("test_total", "a counter"))
    g = reg.register(Gauge("test_gauge", "a gauge"))
    h = reg.register(Histogram("test_seconds", "a histogram",
                               buckets=(0.1, 1.0, 10.0)))
    c.inc()
    c.inc(2, route="device")
    g.set(42, node="n0")
    h.observe(0.05)
    h.observe(0.5)
    h.observe(100.0)
    text = reg.collect()
    assert "# TYPE test_total counter" in text
    assert "test_total 1.0" in text
    assert 'test_total{route="device"} 2.0' in text
    assert 'test_gauge{node="n0"} 42.0' in text
    assert 'test_seconds_bucket{le="0.1"} 1' in text
    assert 'test_seconds_bucket{le="1.0"} 2' in text
    assert 'test_seconds_bucket{le="+Inf"} 3' in text
    assert "test_seconds_count 3" in text
    # registering the same name returns the same instance
    assert reg.register(Counter("test_total")) is c


def test_register_type_mismatch_raises():
    """Re-registering a name as a DIFFERENT metric type must fail loudly
    (regression: it used to hand back the existing Counter to code that
    asked for a Gauge, breaking far from the offending registration)."""
    reg = Registry()
    c = reg.register(Counter("dup_metric", "counter first"))
    with pytest.raises(ValueError, match="dup_metric"):
        reg.register(Gauge("dup_metric", "now a gauge"))
    with pytest.raises(ValueError):
        reg.register(Histogram("dup_metric"))
    # same type still dedups to the original
    assert reg.register(Counter("dup_metric")) is c


def test_help_text_escaped_per_exposition_spec():
    """Backslashes and newlines in HELP text must be escaped — a raw
    multi-line help string corrupts the whole scrape."""
    reg = Registry()
    reg.register(Counter("esc_total",
                         "line one\nline two with a \\ backslash"))
    text = reg.collect()
    assert "# HELP esc_total line one\\nline two with a \\\\ backslash" \
        in text
    # no naked continuation line leaked into the exposition
    assert "\nline two" not in text
    # and every line still parses as comment/series
    for line in text.splitlines():
        assert not line or line.startswith("# ") or " " in line


def test_label_cardinality_cap_evicts_oldest():
    """Per-metric label sets are capped: the oldest labeled child is
    evicted to admit a new one, the eviction is counted, and the
    unlabeled series survives — per-peer labels cannot grow the registry
    unboundedly as peers churn."""
    reg = Registry()
    c = reg.register(Counter("cap_total", "capped", max_label_sets=4))
    c.inc()                                   # unlabeled series
    for i in range(8):
        c.inc(1, peer=f"p{i}")
    assert c.label_sets() == 4                # cap held
    assert c.evicted_total == 5               # 9 inserts - 4 kept
    assert c.value() == 1.0                   # unlabeled never evicted
    assert c.value(peer="p7") == 1.0          # newest kept
    assert c.value(peer="p0") == 0.0          # oldest gone
    text = reg.collect()
    assert "# TYPE metrics_label_evictions_total counter" in text
    assert 'metrics_label_evictions_total{metric="cap_total"} 5' in text
    # an uncapped sibling metric exports no eviction series
    reg2 = Registry()
    reg2.register(Counter("free_total")).inc(route="x")
    assert "metrics_label_evictions_total" not in reg2.collect()


def test_label_cap_applies_to_bound_children_and_other_types():
    """Bound children go through the same guard, and Gauge/Histogram are
    capped like Counter (set/add/observe paths)."""
    reg = Registry()
    c = reg.register(Counter("bcap_total", max_label_sets=3))
    bound = [c.bind(peer=f"b{i}") for i in range(6)]
    for b in bound:
        b.inc()
    assert c.label_sets() == 3
    # an evicted bound child transparently re-inserts (counter resets,
    # which Prometheus rate() treats as a restart)
    bound[0].inc()
    assert c.value(peer="b0") == 1.0
    assert c.label_sets() == 3

    g = reg.register(Gauge("bcap_gauge", max_label_sets=3))
    for i in range(6):
        g.set(i, peer=f"g{i}")
    for i in range(6):
        g.add(1, peer=f"ga{i}")
    assert g.label_sets() == 3

    h = reg.register(Histogram("bcap_seconds", buckets=(1.0,),
                               max_label_sets=3))
    for i in range(6):
        h.observe(0.5, peer=f"h{i}")
    assert len(h._counts) == 3
    assert len(h._sums) == 3 and len(h._totals) == 3   # evicted together
    assert h.count(peer="h5") == 1 and h.count(peer="h0") == 0
    # exposition stays parseable after evictions
    for line in reg.collect().splitlines():
        assert not line or line.startswith("# ") or " " in line


def test_gauge_remove_drops_labeled_child():
    """Gauge.remove lets the switch drop a departed peer's series so it
    does not report its last value forever."""
    reg = Registry()
    g = reg.register(Gauge("rm_gauge"))
    g.set(7, peer="x")
    g.set(9, peer="y")
    g.remove(peer="x")
    g.remove(peer="ghost")                    # absent: no-op
    text = reg.collect()
    assert 'rm_gauge{peer="y"} 9.0' in text
    assert 'peer="x"' not in text


def test_gauge_and_histogram_bind():
    """Gauge.bind()/Histogram.bind() mirror Counter.bind(): pre-resolved
    label sets that skip the per-call sort on hot paths but land in the
    same series."""
    reg = Registry()
    g = reg.register(Gauge("bind_gauge"))
    bg = g.bind(node="n1")
    bg.set(5)
    bg.add(2.5)
    assert g.value(node="n1") == 7.5
    g.set(1, node="n2")                   # unbound path coexists
    assert g.value(node="n2") == 1.0

    h = reg.register(Histogram("bind_seconds", buckets=(0.1, 1.0)))
    bh = h.bind(route="fast")
    bh.observe(0.05)
    bh.observe(0.5)
    h.observe(0.5, route="slow")
    assert h.count(route="fast") == 2
    assert h.sum(route="fast") == 0.55
    assert h.count(route="slow") == 1
    text = reg.collect()
    assert 'bind_seconds_bucket{le="0.1",route="fast"} 1' in text
    assert 'bind_seconds_count{route="fast"} 2' in text


def test_device_abandonment_flips_health_metrics(monkeypatch):
    """A stalled device dispatch must be VISIBLE (VERDICT r3 weak 6):
    crypto_device_degraded goes 1 and the abandonment counter ticks when
    _device_call times out; a completing dispatch clears the gauge."""
    import threading

    from cometbft_tpu.crypto import batch as cb

    gauge, abandoned = cb._device_health()
    before = abandoned.value()
    monkeypatch.setattr(cb, "_DEVICE_WAIT_S", 0.05)
    # a fresh pool + inflight slot so a previous test's state can't leak
    monkeypatch.setattr(cb, "_DEVICE_POOL", None)
    monkeypatch.setattr(cb, "_DEVICE_INFLIGHT", None)
    monkeypatch.setattr(cb, "_DEGRADED_LOGGED", False)

    release = threading.Event()
    assert cb._device_call(lambda: release.wait(5)) is None  # abandoned
    assert abandoned.value() == before + 1
    assert gauge.value() == 1
    # while the stuck call occupies the worker, later calls see degraded
    assert cb._device_call(lambda: 42) is None
    assert gauge.value() == 1
    release.set()                      # the wedge resolves
    cb._DEVICE_INFLIGHT.result(timeout=5)
    assert cb._device_call(lambda: 42) == 42
    assert gauge.value() == 0


def test_overload_shed_rejects_broadcast_under_loop_lag():
    """Flood admission control: when the loop watchdog reports lag above
    rpc.overload_shed_lag_s, broadcast_tx_* reject with a retryable
    RPCError instead of queueing more CheckTx work (the one-core testnet
    stall scenario); normal lag admits."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.rpc import core as rpc_core

    class FakeWatchdog:
        last_lag_s = 0.0

    class FakeMempool:
        async def check_tx(self, raw):
            return None

    class FakeNode:
        config = Config()
        loop_watchdog = FakeWatchdog()
        mempool = FakeMempool()

    node = FakeNode()
    node.config.rpc.overload_shed_lag_s = 2.0
    env = rpc_core.Environment(node)

    node.loop_watchdog.last_lag_s = 0.05
    res = run(rpc_core.broadcast_tx_sync(env, tx=b"ok".hex()))
    assert res["code"] == 0

    node.loop_watchdog.last_lag_s = 5.0
    with pytest.raises(rpc_core.RPCError) as ei:
        run(rpc_core.broadcast_tx_sync(env, tx=b"ok".hex()))
    assert "overloaded" in str(ei.value)
    with pytest.raises(rpc_core.RPCError):
        run(rpc_core.broadcast_tx_async(env, tx=b"ok".hex()))

    # 0 disables shedding entirely
    node.config.rpc.overload_shed_lag_s = 0.0
    res = run(rpc_core.broadcast_tx_sync(env, tx=b"ok".hex()))
    assert res["code"] == 0


def test_structured_logger_levels_and_format():
    buf = io.StringIO()
    tmlog.set_sink(buf)
    try:
        lg = tmlog.logger("testmod", node="n1")
        tmlog.set_level("testmod", "warn")
        lg.info("should not appear")
        lg.warn("warned", height=5)
        tmlog.set_level("testmod", "debug")
        lg.debug("now visible")
        out = buf.getvalue()
        assert "should not appear" not in out
        assert "warned" in out and "height=5" in out and "node=n1" in out
        assert "now visible" in out
        # json format
        buf2 = io.StringIO()
        tmlog.set_sink(buf2)
        tmlog.set_format("json")
        lg.error("boom", code=7)
        rec = json.loads(buf2.getvalue())
        assert rec["level"] == "error" and rec["code"] == 7
    finally:
        tmlog.set_format("plain")
        tmlog.set_sink(__import__("sys").stderr)
        tmlog.set_level("testmod", "info")


def test_tx_indexer_index_get_search():
    from cometbft_tpu.abci.types import Event, EventAttribute, ExecTxResult
    from cometbft_tpu.indexer import TxIndexer
    from cometbft_tpu.mempool.mempool import TxKey

    ti = TxIndexer()
    res = ExecTxResult(code=0, data=b"ok", log="",
                       events=[Event("transfer",
                                     [EventAttribute("sender", "alice")])])
    ti.index(5, 0, b"tx-one", res, {"tx.hash": TxKey(b"tx-one").hex()})
    ti.index(6, 0, b"tx-two", ExecTxResult(), {})

    got = ti.get(TxKey(b"tx-one"))
    assert got["height"] == 5 and bytes.fromhex(got["tx"]) == b"tx-one"

    r = ti.search("transfer.sender='alice'")
    assert r["total_count"] == 1
    assert r["txs"][0]["height"] == 5

    r2 = ti.search("tx.height='6'")
    assert r2["total_count"] == 1 and r2["txs"][0]["height"] == 6

    # intersection of clauses
    r3 = ti.search("transfer.sender='alice' AND tx.height='6'")
    assert r3["total_count"] == 0


def test_block_indexer_search():
    from cometbft_tpu.abci.types import Event, EventAttribute
    from cometbft_tpu.indexer import BlockIndexer

    bi = BlockIndexer()
    bi.index(3, [Event("epoch", [EventAttribute("id", "9")])])
    bi.index(4, [])
    assert bi.has(3) and bi.has(4) and not bi.has(5)
    assert bi.search("epoch.id='9'")["heights"] == [3]
    assert bi.search("block.height='4'")["heights"] == [4]


@pytest.mark.slow   # live node over RPC
def test_node_indexes_and_serves_tx_routes():
    """Live node: a committed tx becomes queryable via tx / tx_search /
    block_search, and /metrics exposes consensus gauges."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config
    from cometbft_tpu.config import test_consensus_config as tcc
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.rpc import HTTPClient
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    def cfg():
        c = Config(consensus=tcc())
        c.p2p.laddr = "tcp://127.0.0.1:0"
        c.rpc.laddr = "tcp://127.0.0.1:0"
        return c

    async def main():
        pvs = [MockPV.from_secret(b"obs%d" % i) for i in range(4)]
        doc = GenesisDoc(chain_id="obs-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            n = await Node.create(doc, KVStoreApplication(),
                                  priv_validator=pv, config=cfg(),
                                  node_key=NodeKey.from_secret(b"ok%d" % i),
                                  name=f"obs{i}")
            nodes.append(n)
            await n.start()
        for i, a in enumerate(nodes):
            for b in nodes[i + 1:]:
                await a.dial_peer(b.listen_addr, persistent=True)
        try:
            cli = HTTPClient(*nodes[0].rpc_addr)
            res = await cli.call("broadcast_tx_commit", tx=b"ik=iv".hex())
            h = res["height"]
            txh = res["hash"]
            # the indexer consumes events asynchronously: poll briefly
            for _ in range(100):
                try:
                    got = await cli.call("tx", hash=txh)
                    break
                except Exception:
                    await asyncio.sleep(0.05)
            else:
                raise AssertionError("tx never indexed")
            assert got["height"] == h
            sr = await cli.call("tx_search", query=f"tx.height='{h}'")
            assert sr["total_count"] >= 1
            br = await cli.call("block_search", query=f"block.height='{h}'")
            assert h in br["heights"]

            # prove=True returns a merkle inclusion proof that verifies
            # against the block header's data_hash (rpc/core/tx.go:40)
            from cometbft_tpu.crypto.merkle import Proof
            from cometbft_tpu.types.header import tx_hash as _txh

            proved = await cli.call("tx", hash=txh, prove=True)
            pf = proved["proof"]["proof"]
            proof = Proof(total=pf["total"], index=pf["index"],
                          leaf_hash=bytes.fromhex(pf["leaf_hash"]),
                          aunts=[bytes.fromhex(a) for a in pf["aunts"]])
            blk = await cli.call("block", height=h)
            data_hash = bytes.fromhex(blk["block"]["hdr"]["dh"]["~b"])
            assert bytes.fromhex(proved["proof"]["root_hash"]) == data_hash
            assert proof.verify(data_hash, _txh(b"ik=iv"))

            # order_by governs result ordering; bad values are rejected
            sr2 = await cli.call("tx_search", query="tx.height > 0",
                                 order_by="desc")
            hs = [r["height"] for r in sr2["txs"]]
            assert hs == sorted(hs, reverse=True)
            from cometbft_tpu.rpc import RPCError
            import pytest as _pytest
            with _pytest.raises(RPCError):
                await cli.call("tx_search", query="tx.height > 0",
                               order_by="sideways")

            # commit-verification metrics need a block with a last commit
            while nodes[0].height() < 3:
                await asyncio.sleep(0.05)

            # /metrics exposition over the RPC port
            reader, writer = await asyncio.open_connection(
                *nodes[0].rpc_addr)
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            status = await reader.readline()
            assert b"200" in status
            headers = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            text = (await reader.readexactly(
                int(headers["content-length"]))).decode()
            writer.close()
            assert "consensus_height{" in text
            assert "crypto_batch_verify_seconds" in text
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())


def test_base_service_lifecycle():
    """libs.service.BaseService: double-start refusal, failed-start reset,
    idempotent stop, waitable termination — exercised through its two
    adopters (Pruner, IndexerService)."""
    from cometbft_tpu.libs.service import BaseService, ServiceError

    class Boom(BaseService):
        async def on_start(self):
            raise RuntimeError("nope")

    class Ok(BaseService):
        def __init__(self):
            super().__init__("ok")
            self.events = []

        async def on_start(self):
            self.events.append("start")

        async def on_stop(self):
            self.events.append("stop")

    async def main():
        s = Ok()
        await s.start()
        assert s.is_running
        with pytest.raises(ServiceError):
            await s.start()
        waiter = asyncio.create_task(s.wait())
        await s.stop()
        await s.stop()                      # idempotent
        await asyncio.wait_for(waiter, 1)
        assert s.events == ["start", "stop"]

        b = Boom()
        with pytest.raises(RuntimeError):
            await b.start()
        assert not b.is_running
        await asyncio.wait_for(b.wait(), 1)   # failed start releases waiters

        # the real adopters run on it
        from cometbft_tpu.sm.pruner import Pruner
        from cometbft_tpu.storage import BlockStore, MemDB, StateStore

        p = Pruner(StateStore(MemDB()), BlockStore(MemDB()))
        await p.start()
        assert p.is_running
        await p.stop()
        assert not p.is_running
        return True

    assert run(main())


def test_prometheus_standalone_listener():
    """instrumentation.prometheus serves the dedicated scrape port
    (reference node/node.go Prometheus server)."""
    import asyncio

    from cometbft_tpu.node.node import _serve_prometheus
    from cometbft_tpu.libs import metrics

    async def main():
        server = await _serve_prometheus("tcp://127.0.0.1:0")
        port = server.sockets[0].getsockname()[1]
        metrics.counter("obs_test_total", "test counter").inc(3)
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        await writer.drain()
        # read the whole body (the shared registry grows with the suite;
        # a single read() caps at 64KB and truncates late metrics)
        status = await asyncio.wait_for(reader.readline(), 5)
        assert b"200 OK" in status
        headers = {}
        while True:
            line = await asyncio.wait_for(reader.readline(), 5)
            if line in (b"\r\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        raw = await asyncio.wait_for(
            reader.readexactly(int(headers["content-length"])), 10)
        assert b"obs_test_total" in raw
        writer.close()
        server.close()
        return True

    assert run(main())


def test_loop_watchdog_detects_stall():
    """The loop watchdog (libs/loopwatch) reports synchronous work that
    froze the event loop — the asyncio analogue of deadlock detection."""
    import asyncio
    import time as _time

    from cometbft_tpu.libs.loopwatch import LoopWatchdog

    async def main():
        wd = LoopWatchdog(asyncio.get_running_loop(),
                          interval_s=0.05, stall_threshold_s=0.2,
                          name="wdtest")
        wd.start()
        try:
            await asyncio.sleep(0.2)     # healthy: no stalls
            healthy = wd.stalls
            _time.sleep(0.8)             # synchronous block ON the loop
            await asyncio.sleep(0.3)     # let the beat land
            return healthy, wd.stalls, wd.worst_stall_s
        finally:
            wd.stop()

    healthy, stalls, worst = run(main())
    assert healthy == 0
    assert stalls >= 1
    assert worst >= 0.5
