"""Bucketed address book (p2p/addrbook.py): anti-poisoning placement,
old/new tiers, promotion, persistence, and seed crawling — fresh
implementation of the defensive ideas in the reference's
``p2p/pex/addrbook.go``."""

import asyncio

import pytest

from cometbft_tpu.p2p.addrbook import (BUCKET_SIZE, BUCKETS_PER_SOURCE,
                                       MAX_ATTEMPTS, AddrBook)

pytestmark = pytest.mark.timeout(60)


def nid(i: int) -> str:
    return f"{i:040x}"


def test_flood_cannot_evict_vetted_entries(tmp_path):
    """One malicious source flooding thousands of invented addresses can
    neither evict old-tier entries nor occupy more than its bounded
    bucket share of the new tier."""
    book = AddrBook(str(tmp_path / "book.json"))
    # 40 known-good peers, vetted by successful connections
    good = []
    for i in range(40):
        node = nid(i)
        assert book.add(node, f"10.0.{i}.1:26656")
        book.mark_good(node)
        good.append(node)
    assert book.num_old() == 40

    # flood: 5000 addresses from ONE source (one /16 group)
    for j in range(5000):
        book.add(nid(10_000 + j), f"203.0.{j % 256}.{j // 256}:26656",
                 persist=False, source="66.66.1.2:26656")

    # every vetted entry survives untouched
    assert book.num_old() == 40
    assert all(book.is_good(g) for g in good)
    # the flood is confined to its bucket share
    assert book.num_new() <= BUCKETS_PER_SOURCE * BUCKET_SIZE
    # and the vetted tier still dominates dial selection
    picked = {p for p, _ in book.pick(set(), n=20)}
    assert picked & set(good), "flood crowded vetted peers out of pick()"


def test_flood_from_many_sources_still_bounded_per_source(tmp_path):
    """Each distinct source group gets its own bounded bucket share; no
    single source exceeds it."""
    book = AddrBook(None)
    for s in range(4):
        for j in range(3000):
            book.add(nid(s * 10_000 + j),
                     f"198.{s}.{j % 250}.1:26656",
                     persist=False, source=f"4{s}.1.2.3:26656")
    # total is bounded by 4 sources x share (with hash collisions it can
    # only be smaller)
    assert book.num_new() <= 4 * BUCKETS_PER_SOURCE * BUCKET_SIZE


def test_promotion_and_attempts(tmp_path):
    book = AddrBook(str(tmp_path / "b.json"))
    book.add(nid(1), "1.2.3.4:26656")
    assert not book.is_good(nid(1))
    book.mark_good(nid(1))
    assert book.is_good(nid(1))
    # a later hearsay add cannot displace the vetted address
    assert not book.add(nid(1), "6.6.6.6:26656", source="9.9.9.9:1")
    assert book.is_good(nid(1))

    # failed dials eventually drop an UNVETTED entry
    book.add(nid(2), "2.3.4.5:26656")
    for _ in range(MAX_ATTEMPTS + 1):
        book.mark_attempt(nid(2))
    assert book.pick({nid(1)}) == []
    # a vetted entry DEMOTES after repeated failures (the peer moved) so
    # hearsay can finally replace its stale address; one more failure
    # drops it
    for _ in range(MAX_ATTEMPTS + 1):
        book.mark_attempt(nid(1))
    assert not book.is_good(nid(1))
    assert book.add(nid(1), "7.7.7.7:26656", source="8.8.8.8:1")
    assert dict(book.pick(set(), n=5))[nid(1)] == "7.7.7.7:26656"


def test_persistence_round_trip(tmp_path):
    path = str(tmp_path / "book.json")
    book = AddrBook(path)
    book.add(nid(1), "1.1.1.1:1")
    book.mark_good(nid(1))
    book.add(nid(2), "2.2.2.2:2", source="3.3.3.3:3")
    book.mark_bad(nid(9))
    book.save()

    book2 = AddrBook(path)
    assert book2.is_good(nid(1))
    assert book2.size() == 2
    assert not book2.add(nid(9), "9.9.9.9:9")      # ban persisted
    # salt persisted -> same placement across restarts
    assert book._salt == book2._salt


def test_legacy_flat_format_import(tmp_path):
    import json

    path = str(tmp_path / "legacy.json")
    with open(path, "w") as f:
        json.dump({"addrs": {nid(5): "5.5.5.5:5", nid(6): "6.6.6.6:6"},
                   "banned": [nid(7)]}, f)
    book = AddrBook(path)
    assert book.size() == 2
    # legacy bare banned LIST carried no expiry: treated as already
    # expired on load, so the peer is readmittable
    assert not book.is_banned(nid(7))
    assert book.add(nid(7), "7.7.7.7:7")
    assert {p for p, _ in book.pick(set(), n=5)} >= {nid(5), nid(6)}


def test_seed_crawl_dials_and_hangs_up(monkeypatch):
    """A seed-mode reactor crawls book addresses and disconnects after
    the linger: connections are harvested, not held."""
    from cometbft_tpu.p2p import pex as pexmod
    from cometbft_tpu.p2p.pex import PexReactor

    monkeypatch.setattr(pexmod, "CRAWL_LINGER", 0.05)

    class FakeNodeInfo:
        listen_addr = "8.8.8.8:26656"

    class FakePeer:
        def __init__(self, pid, outbound=False):
            self.id = pid
            self.node_info = FakeNodeInfo()
            self.outbound = outbound
            self.remote_addr = "8.8.8.8:41234"
            self.dial_addr = "8.8.8.8:26656" if outbound else None
            self.sent = []

        def send(self, ch, msg):
            self.sent.append((ch, msg))

    class FakeSwitch:
        def __init__(self):
            self.peers = {}
            self.dialed = []
            self.stopped = []

        async def dial_peer(self, addr, persistent=False):
            self.dialed.append(addr)
            return None

        async def stop_peer_gracefully(self, peer):
            self.stopped.append(peer.id)
            self.peers.pop(peer.id, None)

    async def main():
        book = AddrBook(None)
        for i in range(6):
            book.add(nid(i), f"12.0.0.{i}:26656")
        r = PexReactor(book, own_id=nid(99), seed_mode=True,
                       request_interval=0.02)
        sw = FakeSwitch()
        r.switch = sw
        await r.start()
        await asyncio.sleep(0.06)          # a crawl round fires
        assert sw.dialed, "crawler never dialed book addresses"

        # an inbound peer gets harvested and then hung up — but its
        # self-advertised address is NOT vetted (inbound proves nothing)
        p = FakePeer(nid(50))
        sw.peers[p.id] = p
        r.add_peer(p)
        assert p.sent and b"pex_req" in p.sent[0][1]
        assert not book.is_good(p.id)
        await asyncio.sleep(0.12)
        assert p.id in sw.stopped, "seed kept the connection open"

        # an OUTBOUND connection (we dialed the address) does vet it
        po = FakePeer(nid(51), outbound=True)
        sw.peers[po.id] = po
        r.add_peer(po)
        assert book.is_good(po.id)
        await r.stop()
        return True

    loop = asyncio.new_event_loop()
    try:
        assert loop.run_until_complete(main())
    finally:
        loop.close()


def test_proven_address_replaces_stale_vetted_entry():
    """A peer that MOVED: its old vetted address is replaced when we
    successfully dial the new one (proven), while hearsay still can't
    touch the vetted entry."""
    book = AddrBook(None)
    book.add(nid(1), "1.1.1.1:26656")
    book.mark_good(nid(1))
    # hearsay about a new address: refused
    assert not book.add(nid(1), "2.2.2.2:26656", source="9.9.9.9:1")
    # proven (we dialed it): replaces and stays vetted
    assert book.add(nid(1), "2.2.2.2:26656", proven=True)
    assert book.is_good(nid(1))
    assert dict(book.sample(5))[nid(1)] == "2.2.2.2:26656"
