"""Pure-Python BLS12-381 correctness (crypto/_bls12381_py.py).

No external vectors exist in this image, so correctness rests on the
algebra: generator/curve/subgroup relations, pairing bilinearity and
non-degeneracy, serialization round-trips, hash-to-curve determinism +
subgroup membership, and full signature semantics through the key seam.
"""

import pytest

from cometbft_tpu.crypto import _bls12381_py as b


def test_field_towers():
    a = (1234567, 7654321)
    assert b.f2_mul(a, b.f2_inv(a)) == b.F2_ONE
    assert b.f2_sqr(a) == b.f2_mul(a, a)
    s = b.f2_sqrt(b.f2_sqr(a))
    assert s in (a, b.f2_neg(a))
    # non-residue has no root
    assert b.f2_legendre(b.XI) in (1, -1)
    x6 = ((3, 4), (5, 6), (7, 8))
    assert b.f6_mul(x6, b.f6_inv(x6)) == b.F6_ONE
    x12 = (x6, ((9, 1), (2, 3), (4, 5)))
    assert b.f12_mul(x12, b.f12_inv(x12)) == b.F12_ONE
    assert b.f12_pow(x12, b.P ** 12 - 1) == b.F12_ONE   # Lagrange


def test_generators_and_subgroups():
    assert b.g1_is_on_curve(b.G1)
    assert b.g2_is_on_curve(b.G2)
    assert b.g1_in_subgroup(b.G1)
    assert b.g2_in_subgroup(b.G2)
    # group laws
    two_g = b.g1_add(b.G1, b.G1)
    assert b.g1_add(two_g, b.g1_neg(b.G1)) == b.G1
    assert b.g1_mul(b.G1, 5) == b.g1_add(two_g, b.g1_add(two_g, b.G1))


def test_pairing_bilinearity():
    e_ab = b.pairing(b.g1_mul(b.G1, 6), b.g2_mul(b.G2, 7))
    e_base = b.pairing(b.G1, b.G2)
    assert e_ab == b.f12_pow(e_base, 42)
    assert e_base != b.F12_ONE                       # non-degenerate
    # e(P, Q1+Q2) = e(P,Q1) e(P,Q2)
    q1 = b.g2_mul(b.G2, 3)
    q2 = b.g2_mul(b.G2, 11)
    lhs = b.pairing(b.G1, b.g2_add(q1, q2))
    rhs = b.f12_mul(b.pairing(b.G1, q1), b.pairing(b.G1, q2))
    assert lhs == rhs


def test_serialization_roundtrip_and_rejects():
    p = b.g1_mul(b.G1, 123456789)
    assert b.g1_decompress(b.g1_compress(p)) == p
    assert b.g1_decompress(b.g1_compress(None)) is None
    q = b.g2_mul(b.G2, 987654321)
    assert b.g2_decompress(b.g2_compress(q)) == q
    assert b.g2_decompress(b.g2_compress(None)) is None
    with pytest.raises(ValueError):
        b.g1_decompress(b"\x00" * 48)        # compression bit unset
    with pytest.raises(ValueError):
        b.g1_decompress(b"\xff" * 48)        # x out of range
    # an x with no curve point
    for xx in range(2, 50):
        raw = bytearray(xx.to_bytes(48, "big"))
        raw[0] |= 0x80
        try:
            b.g1_decompress(bytes(raw))
        except ValueError:
            break
    else:
        pytest.fail("no invalid x found in range (unexpected)")


def test_hash_to_g2_deterministic_and_in_subgroup():
    h1 = b.hash_to_g2(b"message")
    h2 = b.hash_to_g2(b"message")
    h3 = b.hash_to_g2(b"other")
    assert h1 == h2
    assert h1 != h3
    assert b.g2_in_subgroup(h1)
    # a mapped-but-uncleared point is NOT in the subgroup (cofactor > 1
    # actually does something)
    u = b._hash_to_field_fq2(b"x", 1, b"test")[0]
    raw_pt = b._iso3_map(b._map_to_curve_sswu(u))
    assert b.g2_is_on_curve(raw_pt)
    assert not b.g2_in_subgroup(raw_pt)


def test_sswu_matches_rfc9380_vectors():
    """The standard-suite claim, pinned byte-exactly: RFC 9380 §G.2
    BLS12381G2_XMD:SHA-256_SSWU_RO_ vectors (QUUX DST).  Any deviation
    in SSWU, the 3-isogeny constants, hash_to_field, or h_eff clearing
    fails this — passing means blst-class interop."""
    DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"
    vecs = {
        b"": ((0x0141ebfbdca40eb85b87142e130ab689c673cf60f1a3e98d69335266f30d9b8d4ac44c1038e9dcdd5393faf5c41fb78a,
               0x05cb8437535e20ecffaef7752baddf98034139c38452458baeefab379ba13dff5bf5dd71b72418717047f5b0f37da03d),
              (0x0503921d7f6a12805e72940b963c0cf3471c7b2a524950ca195d11062ee75ec076daf2d4bc358c4b190c0c98064fdd92,
               0x12424ac32561493f3fe3c260708a12b7c620e7be00099a974e259ddc7d1f6395c3c811cdd19f1e8dbf3e9ecfdcbab8d6)),
        b"abc": ((0x02c2d18e033b960562aae3cab37a27ce00d80ccd5ba4b7fe0e7a210245129dbec7780ccc7954725f4168aff2787776e6,
               0x139cddbccdc5e91b9623efd38c49f81a6f83f175e80b06fc374de9eb4b41dfe4ca3a230ed250fbe3a2acf73a41177fd8),
              (0x1787327b68159716a37440985269cf584bcb1e621d3a7202be6ea05c4cfe244aeb197642555a0645fb87bf7466b2ba48,
               0x00aa65dae3c8d732d10ecd2c50f8a1baf3001578f71c694e03866e9f3d49ac1e1ce70dd94a733534f106d4cec0eddd16)),
        b"abcdef0123456789": ((0x121982811d2491fde9ba7ed31ef9ca474f0e1501297f68c298e9f4c0028add35aea8bb83d53c08cfc007c1e005723cd0,
               0x190d119345b94fbd15497bcba94ecf7db2cbfd1e1fe7da034d26cbba169fb3968288b3fafb265f9ebd380512a71c3f2c),
              (0x05571a0f8d3c08d094576981f4a3b8eda0a8e771fcdcc8ecceaf1356a6acf17574518acb506e435b639353c2e14827c8,
               0x0bb5e7572275c567462d91807de765611490205a941a5a6af3b1691bfe596c31225d3aabdf15faff860cb4ef17c7c3be)),
    }
    for msg, want in vecs.items():
        assert b.hash_to_g2(msg, DST) == want, msg


def test_backend_is_standard_suite():
    from cometbft_tpu.crypto import bls12381 as keys

    assert keys.is_standard_backend()
    assert keys.backend_ciphersuite() == keys.STANDARD_CIPHERSUITE
    assert keys.check_validator_backend() is None


def test_signature_scheme_through_key_seam():
    from cometbft_tpu.crypto import bls12381 as keys

    assert keys.ENABLED
    sk = keys.Bls12381PrivKey.generate()
    pub = sk.pub_key()
    assert pub.type() == "bls12_381"
    assert len(pub.bytes()) == 48 and len(pub.address()) == 20
    sig = sk.sign(b"payload")
    assert len(sig) == 96
    assert pub.verify_signature(b"payload", sig)
    assert not pub.verify_signature(b"other", sig)
    assert not pub.verify_signature(b"payload", b"\x00" * 96)
    assert not pub.verify_signature(b"payload", sig[:-1])


def test_native_backend_selected_and_byte_parity():
    """The backend seam prefers the native C++ build, and its pk/sig/
    verify are byte-identical with the RFC-pinned pure-Python
    implementation (which transitively pins the native hash-to-curve to
    the RFC 9380 QUUX vectors above)."""
    from cometbft_tpu.crypto import _bls12381_py as b
    from cometbft_tpu.crypto import bls12381 as keys

    # ambient selection prefers blspy (constant-time) when importable —
    # on boxes without it the native backend must win; either way the
    # parity checks below run against a directly-constructed native
    # backend so they never depend on ambient installs
    assert isinstance(keys._BACKEND,
                      (keys._NativeBackend, keys._BlspyBackend)), \
        type(keys._BACKEND).__name__
    n = keys._NativeBackend()
    for seed, msg in ((5, b""), (12345, b"native-parity"),
                      (2 ** 200 + 17, b"x" * 75)):
        sk = seed % b.R
        assert n.sk_to_pk(sk) == b.sk_to_pk(sk)
        sig_n = n.sign(sk, msg)
        assert sig_n == b.sign(sk, msg)
        assert n.verify(b.sk_to_pk(sk), msg, sig_n)
        assert b.verify(b.sk_to_pk(sk), msg, sig_n)


def test_native_backend_rejects_malleated_inputs():
    from cometbft_tpu.crypto import bls12381 as keys

    n = keys._NativeBackend()
    sk = 99991
    pk = n.sk_to_pk(sk)
    msg = b"reject-malleation"
    sig = n.sign(sk, msg)
    assert n.verify(pk, msg, sig)
    for pos in (0, 1, 47, 48, 95):
        bad = bytearray(sig)
        bad[pos] ^= 0x04
        assert not n.verify(pk, msg, bytes(bad)), pos
    for pos in (0, 5, 47):
        bad = bytearray(pk)
        bad[pos] ^= 0x04
        assert not n.verify(bytes(bad), msg, sig), pos
    assert not n.verify(pk, msg + b".", sig)
    # infinity encodings must be rejected outright
    inf_pk = bytes([0xC0] + [0] * 47)
    inf_sig = bytes([0xC0] + [0] * 95)
    assert not n.verify(inf_pk, msg, sig)
    assert not n.verify(pk, msg, inf_sig)


# ------------------------------------------------- aggregation (r20)


def _conformance_vectors():
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "vectors",
                        "bls12381_conformance.json")
    with open(path) as f:
        return json.load(f)


def test_aggregate_cross_backend_byte_parity():
    """Aggregation must be a consensus-stable operation: the native C++
    backend and the pure-Python oracle produce byte-identical aggregate
    signatures/pubkeys and agree on every FastAggregateVerify verdict."""
    from cometbft_tpu.crypto import bls12381 as keys

    n = keys._NativeBackend()
    sks = [(7 ** i + 13) % b.R for i in range(1, 6)]
    pks = [b.sk_to_pk(k) for k in sks]
    msg = b"cross-backend-aggregate"
    sigs = [b.sign(k, msg) for k in sks]

    agg_sig = n.aggregate_signatures(sigs)
    agg_pk = n.aggregate_pubkeys(pks)
    assert agg_sig == b.aggregate_signatures(sigs)
    assert agg_pk == b.aggregate_pubkeys(pks)
    # check=False must not change the bytes, only skip validation
    assert n.aggregate_signatures(sigs, check=False) == agg_sig
    assert n.aggregate_pubkeys(pks, check=False) == agg_pk

    assert n.fast_aggregate_verify(pks, msg, agg_sig)
    assert b.fast_aggregate_verify(pks, msg, agg_sig)
    # verdict agreement on wrong cohorts: extra signer, dropped signer,
    # wrong message
    extra = b.sk_to_pk(424242)
    for bad_pks, bad_msg in (
            (pks + [extra], msg), (pks[:-1], msg), (pks, msg + b".")):
        assert not n.fast_aggregate_verify(bad_pks, bad_msg, agg_sig)
        assert not b.fast_aggregate_verify(bad_pks, bad_msg, agg_sig)

    # proof-of-possession parity (the rogue-key gate)
    for k in sks[:2]:
        pop = n.pop_prove(k)
        assert pop == b.pop_prove(k)
        assert n.pop_verify(b.sk_to_pk(k), pop)
        assert b.pop_verify(b.sk_to_pk(k), pop)


def test_conformance_vectors_pinned():
    """Sweep tests/vectors/bls12381_conformance.json: keygen, pubkey
    derivation, per-key signatures and possession proofs, and the
    aggregate signature/pubkey — all pinned byte-exactly.  A backend
    change that shifts any of these bytes is a consensus break."""
    from cometbft_tpu.crypto import bls12381 as keys

    v = _conformance_vectors()
    assert v["ciphersuite"] == keys.STANDARD_CIPHERSUITE
    assert v["pop_dst"].encode() == keys.DST_POP
    msg = bytes.fromhex(v["message"])

    sigs, pks = [], []
    for i, k in enumerate(v["keys"]):
        sk = b.keygen(bytes.fromhex(k["ikm"]))
        assert sk == int.from_bytes(bytes.fromhex(k["sk"]), "big"), i
        pk = b.sk_to_pk(sk)
        assert pk == bytes.fromhex(k["pk"]), i
        sig = b.sign(sk, msg)
        assert sig == bytes.fromhex(k["sig"]), i
        assert keys.pop_prove(sk.to_bytes(32, "big")) == \
            bytes.fromhex(k["pop"]), i
        assert keys.pop_verify(pk, bytes.fromhex(k["pop"])), i
        pks.append(pk)
        sigs.append(sig)

    assert keys.aggregate_signatures(sigs) == \
        bytes.fromhex(v["aggregate_signature"])
    assert keys.aggregate_pubkeys(pks) == \
        bytes.fromhex(v["aggregate_pubkey"])
    assert keys.fast_aggregate_verify(
        pks, msg, bytes.fromhex(v["aggregate_signature"]))


def test_conformance_subgroup_and_infinity_rejects():
    """The subgroup-check pin: wrong-subgroup and infinity encodings from
    the conformance vectors must be rejected by every aggregate entry
    point, and a possession proof under the wrong DST must not verify."""
    from cometbft_tpu.crypto import bls12381 as keys

    v = _conformance_vectors()
    msg = bytes.fromhex(v["message"])
    pk0 = bytes.fromhex(v["keys"][0]["pk"])
    sig0 = bytes.fromhex(v["keys"][0]["sig"])

    # THE pin: a valid signature aggregated with a wrong-subgroup G2
    # point must raise — not silently poison the cohort's aggregate
    with pytest.raises(ValueError):
        keys.aggregate_signatures(
            [sig0, bytes.fromhex(v["g2_wrong_subgroup"])], check=True)
    with pytest.raises(ValueError):
        keys.aggregate_signatures([bytes.fromhex(v["g2_infinity"])])
    with pytest.raises(ValueError):
        keys.aggregate_pubkeys(
            [pk0, bytes.fromhex(v["g1_wrong_subgroup"])])
    with pytest.raises(ValueError):
        keys.aggregate_pubkeys([bytes.fromhex(v["g1_infinity"])])
    # the never-raises entry point degrades to False on the same inputs
    assert not keys.fast_aggregate_verify(
        [bytes.fromhex(v["g1_wrong_subgroup"])], msg, sig0)
    assert not keys.fast_aggregate_verify(
        [bytes.fromhex(v["g1_infinity"])], msg, sig0)
    # PoP domain separation: the same key's "proof" hashed under the
    # vote (NUL_) DST must fail PopVerify
    assert not keys.pop_verify(pk0, bytes.fromhex(v["pop_wrong_dst"]))
    assert keys.pop_verify(pk0, bytes.fromhex(v["keys"][0]["pop"]))


def test_aggregate_module_seam_policy():
    """Policy lives at the module seam (crypto/bls12381.py), not in the
    backends: empty sets and duplicate signers are caller bugs that must
    raise, while fast_aggregate_verify is documented never-raises."""
    from cometbft_tpu.crypto import bls12381 as keys

    sk, msg = 31337, b"seam-policy"
    pk = b.sk_to_pk(sk)
    sig = b.sign(sk, msg)

    with pytest.raises(ValueError):
        keys.aggregate_signatures([])
    with pytest.raises(ValueError):
        keys.aggregate_signatures([sig[:-1]])
    with pytest.raises(ValueError):
        keys.aggregate_pubkeys([])
    with pytest.raises(ValueError):
        keys.aggregate_pubkeys([pk, pk])     # bitmap can't repeat a signer
    with pytest.raises(ValueError):
        keys.aggregate_pubkeys([pk[:-1]])

    # never-raises: empty cohort, duplicate signer, truncated inputs
    assert keys.fast_aggregate_verify([], msg, sig) is False
    assert keys.fast_aggregate_verify([pk, pk], msg, sig) is False
    assert keys.fast_aggregate_verify([pk[:-1]], msg, sig) is False
    assert keys.fast_aggregate_verify([pk], msg, sig[:-1]) is False
    # and the single-signer aggregate degenerates to plain verification
    assert keys.fast_aggregate_verify([pk], msg, sig) is True
