"""Pure-Python BLS12-381 correctness (crypto/_bls12381_py.py).

No external vectors exist in this image, so correctness rests on the
algebra: generator/curve/subgroup relations, pairing bilinearity and
non-degeneracy, serialization round-trips, hash-to-curve determinism +
subgroup membership, and full signature semantics through the key seam.
"""

import pytest

from cometbft_tpu.crypto import _bls12381_py as b


def test_field_towers():
    a = (1234567, 7654321)
    assert b.f2_mul(a, b.f2_inv(a)) == b.F2_ONE
    assert b.f2_sqr(a) == b.f2_mul(a, a)
    s = b.f2_sqrt(b.f2_sqr(a))
    assert s in (a, b.f2_neg(a))
    # non-residue has no root
    assert b.f2_legendre(b.XI) in (1, -1)
    x6 = ((3, 4), (5, 6), (7, 8))
    assert b.f6_mul(x6, b.f6_inv(x6)) == b.F6_ONE
    x12 = (x6, ((9, 1), (2, 3), (4, 5)))
    assert b.f12_mul(x12, b.f12_inv(x12)) == b.F12_ONE
    assert b.f12_pow(x12, b.P ** 12 - 1) == b.F12_ONE   # Lagrange


def test_generators_and_subgroups():
    assert b.g1_is_on_curve(b.G1)
    assert b.g2_is_on_curve(b.G2)
    assert b.g1_in_subgroup(b.G1)
    assert b.g2_in_subgroup(b.G2)
    # group laws
    two_g = b.g1_add(b.G1, b.G1)
    assert b.g1_add(two_g, b.g1_neg(b.G1)) == b.G1
    assert b.g1_mul(b.G1, 5) == b.g1_add(two_g, b.g1_add(two_g, b.G1))


def test_pairing_bilinearity():
    e_ab = b.pairing(b.g1_mul(b.G1, 6), b.g2_mul(b.G2, 7))
    e_base = b.pairing(b.G1, b.G2)
    assert e_ab == b.f12_pow(e_base, 42)
    assert e_base != b.F12_ONE                       # non-degenerate
    # e(P, Q1+Q2) = e(P,Q1) e(P,Q2)
    q1 = b.g2_mul(b.G2, 3)
    q2 = b.g2_mul(b.G2, 11)
    lhs = b.pairing(b.G1, b.g2_add(q1, q2))
    rhs = b.f12_mul(b.pairing(b.G1, q1), b.pairing(b.G1, q2))
    assert lhs == rhs


def test_serialization_roundtrip_and_rejects():
    p = b.g1_mul(b.G1, 123456789)
    assert b.g1_decompress(b.g1_compress(p)) == p
    assert b.g1_decompress(b.g1_compress(None)) is None
    q = b.g2_mul(b.G2, 987654321)
    assert b.g2_decompress(b.g2_compress(q)) == q
    assert b.g2_decompress(b.g2_compress(None)) is None
    with pytest.raises(ValueError):
        b.g1_decompress(b"\x00" * 48)        # compression bit unset
    with pytest.raises(ValueError):
        b.g1_decompress(b"\xff" * 48)        # x out of range
    # an x with no curve point
    for xx in range(2, 50):
        raw = bytearray(xx.to_bytes(48, "big"))
        raw[0] |= 0x80
        try:
            b.g1_decompress(bytes(raw))
        except ValueError:
            break
    else:
        pytest.fail("no invalid x found in range (unexpected)")


def test_hash_to_g2_deterministic_and_in_subgroup():
    h1 = b.hash_to_g2(b"message")
    h2 = b.hash_to_g2(b"message")
    h3 = b.hash_to_g2(b"other")
    assert h1 == h2
    assert h1 != h3
    assert b.g2_in_subgroup(h1)
    # a mapped-but-uncleared point is NOT in the subgroup (cofactor > 1
    # actually does something)
    u = b._hash_to_field_fq2(b"x", 1, b"test")[0]
    raw_pt = b._map_to_curve_svdw(u)
    assert b.g2_is_on_curve(raw_pt)
    assert not b.g2_in_subgroup(raw_pt)


def test_signature_scheme_through_key_seam():
    from cometbft_tpu.crypto import bls12381 as keys

    assert keys.ENABLED
    sk = keys.Bls12381PrivKey.generate()
    pub = sk.pub_key()
    assert pub.type() == "bls12_381"
    assert len(pub.bytes()) == 48 and len(pub.address()) == 20
    sig = sk.sign(b"payload")
    assert len(sig) == 96
    assert pub.verify_signature(b"payload", sig)
    assert not pub.verify_signature(b"other", sig)
    assert not pub.verify_signature(b"payload", b"\x00" * 96)
    assert not pub.verify_signature(b"payload", sig[:-1])
