"""True SPMD dispatch (r19): the plan's mesh shape drives device
resolution, chunking and the blocksync window; a multi-device mesh runs
ONE sharded program per bucket (no per-device fan-out); sharded AOT
bundles are keyed by mesh shape and a mismatch degrades to jit with its
own staleness reason; and ``init_multihost`` probes the distributed
runtime through public API only.

Runs on the conftest's 8 emulated CPU host devices
(``--xla_force_host_platform_device_count=8``)."""

import dataclasses

import numpy as np
import pytest

from cometbft_tpu.crypto import aotbundle
from cometbft_tpu.crypto import batch as B
from cometbft_tpu.crypto import plan as P
from cometbft_tpu.parallel import mesh as M

pytestmark = pytest.mark.timeout(120)


@pytest.fixture(autouse=True)
def clean_plan():
    saved = P.active()
    yield
    P.set_plan(saved, push_min_lanes=False)
    P.set_devices(None)
    aotbundle.reset()


def _stale_counter():
    from cometbft_tpu.libs import metrics

    return metrics.counter("crypto_compile_bundle_stale_total", "")


# --------------------------------------------------- plan mesh semantics


def test_mesh_shape_resolves_devices():
    import jax

    assert len(jax.devices()) >= 8        # conftest forces 8 host devices
    P.configure(mesh_shape=(4,))
    devs = P.resolve_devices(None)
    assert len(devs) == 4
    assert devs == tuple(jax.devices())[:4]
    # an explicit pin still wins over the mesh
    assert P.resolve_devices(jax.devices()[5]) == (jax.devices()[5],)
    # no mesh declared: CPU hosts keep single-device (jit default)
    P.configure(mesh_shape=())
    assert P.resolve_devices(None) == ()


def test_mesh_shape_outside_plan_hash_but_in_describe():
    base = P.active()
    meshed = dataclasses.replace(base, mesh_shape=(4,))
    # a mesh change must NOT look like a plan change: the bundle guard
    # reports it as reason=mesh, not reason=version
    assert P.plan_hash(base) == P.plan_hash(meshed)
    d = P.describe(meshed)
    assert d["mesh_shape"] == [4]
    assert d["mesh_size"] == 4
    assert P.mesh_size(meshed) == 4
    assert P.mesh_size(base) == 1


def test_chunk_bucket_and_occupancy_past_cap_on_mesh():
    devs8 = tuple(range(8))
    # past the single-device cap the global shape is per-device-bucket x
    # mesh: 5000 over 8 devices -> ceil(5000/8)=625 -> 1024 x 8
    assert P.chunk_bucket(5000, devs8) == 8192
    assert P.chunk_bucket(5000, ()) == 5000       # single device: exact
    # at or below the cap the r13 semantics stand (pinned elsewhere)
    assert P.chunk_bucket(100, (1, 2, 3, 4)) == 256
    # occupancy is judged against the full-mesh padded shape; the chunk
    # cap scales with the mesh so 10k lanes on 8 devices is ONE dispatch
    assert abs(P.mesh_occupancy(10_000, 8) - 10_000 / 16_384) < 1e-9
    # non-power-of-two lane counts on a multi-device mesh
    assert abs(P.mesh_occupancy(3000, 3) - 3000 / 4098) < 1e-9
    assert abs(P.mesh_occupancy(5000, 4) - 5000 / 8192) < 1e-9
    assert P.mesh_occupancy(4096 * 2, 2) == 1.0


def test_window_blocks_snaps_to_full_mesh():
    # no mesh: the configured window stands
    assert P.window_blocks(32, 100) == 32
    P.configure(mesh_shape=(8,))
    # 32 blocks x 100 vals = 3200 lanes; per-device share 400 -> 1024
    # bucket -> full-mesh shape 8192 lanes -> 81 blocks (snapped from
    # below: 82 would spill 8 lanes into a second padded dispatch)
    assert P.window_blocks(32, 100) == 81
    assert P.mesh_occupancy(81 * 100, 8) >= 0.98
    # a window whose per-device share already sits at the lane cap only
    # snaps to the cap's full-mesh shape (never an uncompilable size)
    assert P.window_blocks(200, 100) == 327       # 4096 x 8 / 100
    # huge valsets fill the mesh from a single block: window stands
    assert P.window_blocks(32, 5000) == 32
    assert P.window_blocks(32, 0) == 32


# ------------------------------------------- ONE sharded dispatch per bucket


def test_one_sharded_dispatch_per_bucket(monkeypatch):
    """A multi-device mesh must execute ONE sharded program per bucket —
    never a per-device fan-out, never the single-device route."""
    calls = []

    def factory(name, result):
        def make(*key):
            def fn(*a, **k):
                calls.append(name)
                return result
            return fn
        return make

    bb = 1024                     # chunk_bucket(300, 4 devices)
    monkeypatch.setattr(B, "_compiled_rlc_sharded",
                        factory("rlc_sharded", np.asarray(True)))
    monkeypatch.setattr(B, "_compiled_verify_sharded",
                        factory("verify_sharded", np.ones((bb,), bool)))
    monkeypatch.setattr(
        B, "_compiled_rlc",
        factory("rlc_single", np.asarray(True)))
    monkeypatch.setattr(
        B, "_compiled_verify",
        factory("verify_single", np.ones((bb,), bool)))
    P.configure(mesh_shape=(4,))
    n = 300                       # >= rlc_min_lanes, one bucket
    z = np.zeros((n, 32), np.uint8)
    msgs = np.zeros((n, 8), np.uint8)
    lens = np.full((n,), 8, np.int64)
    out = B.device_verify_ed25519(z, z, z, msgs, lens)
    assert out.shape == (n,)
    assert calls == ["rlc_sharded"]          # exactly one dispatch
    # an RLC reject localizes with exactly ONE sharded per-lane dispatch
    calls.clear()
    monkeypatch.setattr(B, "_compiled_rlc_sharded",
                        factory("rlc_sharded", np.asarray(False)))
    B.device_verify_ed25519(z, z, z, msgs, lens)
    assert calls == ["rlc_sharded", "verify_sharded"]


def test_mesh_metrics_record_sharded_route(monkeypatch):
    monkeypatch.setattr(B, "_compiled_rlc_sharded",
                        lambda devs: lambda *a: np.asarray(True))
    gauge, occ, total = B._mesh_metrics()
    before = total.value(route="sharded")
    P.configure(mesh_shape=(4,))
    n = 300
    z = np.zeros((n, 32), np.uint8)
    B.device_verify_ed25519(z, z, z, np.zeros((n, 8), np.uint8),
                            np.full((n,), 8, np.int64))
    assert total.value(route="sharded") == before + 1
    assert gauge.value() == 4


# -------------------------------------------------- sharded AOT bundles


def _mesh_plan(nd=4, lanes=16):
    return dataclasses.replace(
        P.active(), warm_kinds=(), warm_tables=(),
        warm_merkle=(lanes,), mesh_shape=(nd,))


def test_sharded_bundle_roundtrip_keyed_by_mesh(tmp_path):
    """Build -> save -> fresh load of a sharded executable, keyed
    ``@m<D>``, with sharded output bit-identical to single-device."""
    import jax

    plan = _mesh_plan(nd=4, lanes=16)
    path = str(tmp_path / "bundle-m4.aot")
    info = aotbundle.build(plan=plan, path=path)
    key = "merkle_level:16@m4"
    assert info["buckets"] == {key: "warm"}
    rng = np.random.default_rng(3)
    left = rng.integers(0, 2**32, (16, 8), dtype=np.uint32)
    right = rng.integers(0, 2**32, (16, 8), dtype=np.uint32)
    sharded = np.asarray(aotbundle.timed_call(key, left, right))

    aotbundle.reset()
    info = aotbundle.load(path=path, plan=plan)
    assert info["status"] == "loaded"
    assert info["buckets"][key] == "warm"
    assert aotbundle.lookup(key) is not None
    assert aotbundle.lookup("merkle_level:16") is None   # tag required
    reloaded = np.asarray(aotbundle.timed_call(key, left, right))

    from cometbft_tpu.ops import sha256 as _sha

    single = np.asarray(jax.jit(_sha.merkle_inner_level)(left, right))
    assert (sharded == single).all()
    assert (reloaded == single).all()


def test_mesh_mismatch_degrades_with_reason_mesh(tmp_path):
    """A 4-device bundle must never load on an 8-device mesh: same
    bundle_version (mesh is outside the plan hash), so the header's mesh
    dims are the guard — reason=mesh, safe degrade to jit."""
    plan4 = _mesh_plan(nd=4, lanes=16)
    path = str(tmp_path / "bundle.aot")
    aotbundle.build(plan=plan4, path=path)
    aotbundle.reset()

    plan8 = dataclasses.replace(plan4, mesh_shape=(8,))
    assert aotbundle.bundle_version(plan4) == aotbundle.bundle_version(plan8)
    c = _stale_counter()
    before = c.value(reason="mesh")
    info = aotbundle.load(path=path, plan=plan8)
    assert info["status"] == "stale"
    assert info["buckets"] == {}
    assert aotbundle.lookup("merkle_level:16@m4") is None
    assert aotbundle.lookup("merkle_level:16@m8") is None
    assert c.value(reason="mesh") == before + 1
    # and a single-device plan rejects a sharded bundle the same way
    aotbundle.reset()
    plan1 = dataclasses.replace(plan4, mesh_shape=())
    assert aotbundle.load(path=path, plan=plan1)["status"] == "stale"


def test_default_path_carries_mesh_tag():
    plan = _mesh_plan(nd=4)
    p = aotbundle.default_path(plan=plan)
    assert p.endswith("-m4.aot")
    single = dataclasses.replace(plan, mesh_shape=())
    assert aotbundle.default_path(plan=single).endswith(
        f"bundle-{aotbundle.bundle_version(single)}.aot")


# --------------------------------------------- init_multihost public probe


def test_distributed_probe_never_touches_private_api(monkeypatch):
    import types

    import jax

    # a jax without the public probe (pre-0.4.34 layout): the probe must
    # answer False from PUBLIC api alone, never import jax._src state
    calls = []

    def fake_init(**kw):
        calls.append(kw)

    monkeypatch.setattr(
        jax, "distributed",
        types.SimpleNamespace(initialize=fake_init), raising=False)
    assert M._distributed_initialized() is False
    M.init_multihost(coordinator="127.0.0.1:9999", num_processes=1,
                     process_id=0)
    assert len(calls) == 1

    # probe present and truthy: no re-init
    monkeypatch.setattr(
        jax, "distributed",
        types.SimpleNamespace(initialize=fake_init,
                              is_initialized=lambda: True), raising=False)
    assert M._distributed_initialized() is True
    M.init_multihost(coordinator="127.0.0.1:9999")
    assert len(calls) == 1                       # unchanged

    # probe absent + runtime actually already live: the "already
    # initialized" RuntimeError is absorbed, anything else propagates
    def angry_init(**kw):
        raise RuntimeError("jax.distributed.initialize was already called")

    monkeypatch.setattr(
        jax, "distributed",
        types.SimpleNamespace(initialize=angry_init), raising=False)
    M.init_multihost(coordinator="127.0.0.1:9999")

    def broken_init(**kw):
        raise RuntimeError("coordinator unreachable")

    monkeypatch.setattr(
        jax, "distributed",
        types.SimpleNamespace(initialize=broken_init), raising=False)
    with pytest.raises(RuntimeError, match="unreachable"):
        M.init_multihost(coordinator="127.0.0.1:9999")


def test_mesh_module_has_no_private_jax_reach():
    import inspect

    src = inspect.getsource(M)
    assert "jax._src" not in src
