"""CLI + multi-process e2e: `testnet` generates wired homes, `start` runs
real node processes, RPC drives them — the reference's e2e tier
(``test/e2e/README.md``) on one machine, and VERDICT item 9's bar:
"the tier-2 testnet driven through the CLI + RPC instead of test harness
internals"."""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.timeout(150)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 28600


def _run_cli(*args, home=None):
    cmd = [sys.executable, "-m", "cometbft_tpu"]
    if home:
        cmd += ["--home", home]
    cmd += list(args)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=REPO, timeout=60)


def test_cli_init_and_key_commands(tmp_path):
    home = str(tmp_path / "node")
    res = _run_cli("init", "--chain-id", "cli-chain", "--moniker", "m0",
                   home=home)
    assert res.returncode == 0, res.stderr
    assert os.path.exists(f"{home}/config/config.toml")
    assert os.path.exists(f"{home}/config/genesis.json")
    assert os.path.exists(f"{home}/config/node_key.json")
    assert os.path.exists(f"{home}/config/priv_validator_key.json")

    rid = _run_cli("show-node-id", home=home)
    assert rid.returncode == 0 and len(rid.stdout.strip()) == 40

    rv = _run_cli("show-validator", home=home)
    assert rv.returncode == 0
    assert json.loads(rv.stdout)["type"] == "ed25519"

    rgv = _run_cli("gen-validator", home=home)
    assert rgv.returncode == 0
    assert "priv_key" in json.loads(rgv.stdout)

    rver = _run_cli("version", home=home)
    assert rver.returncode == 0 and rver.stdout.strip()

    # config round-trips through the TOML loader
    from cometbft_tpu.config import Config

    cfg = Config.load(f"{home}/config/config.toml")
    assert cfg.base.moniker == "m0"

    rr = _run_cli("unsafe-reset-all", home=home)
    assert rr.returncode == 0, rr.stderr



def _patch_testnet_configs(base, n=4):
    """Shrink consensus timeouts + pin the CPU backend for test speed."""
    from cometbft_tpu.config import Config

    for i in range(n):
        cfgp = f"{base}/node{i}/config/config.toml"
        cfg = Config.load(cfgp)
        cfg.consensus.timeout_propose = 300_000_000
        cfg.consensus.timeout_propose_delta = 100_000_000
        cfg.consensus.timeout_prevote = 150_000_000
        cfg.consensus.timeout_prevote_delta = 50_000_000
        cfg.consensus.timeout_precommit = 150_000_000
        cfg.consensus.timeout_precommit_delta = 50_000_000
        cfg.consensus.timeout_commit = 100_000_000
        cfg.base.signature_backend = "cpu"
        cfg.save(cfgp)


def _spawn_node(base, i):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu",
         "--home", f"{base}/node{i}", "start"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO)


def test_cli_testnet_multiprocess_commits_blocks(tmp_path):
    """4 real OS processes, launched by the CLI, commit blocks; txs and
    queries flow through RPC only."""
    base = str(tmp_path / "net")
    res = _run_cli("testnet", "--v", "4", "--output-dir", base,
                   "--base-port", str(BASE_PORT), "--chain-id", "proc-net")
    assert res.returncode == 0, res.stderr

    _patch_testnet_configs(base)
    procs = []
    try:
        for i in range(4):
            procs.append(_spawn_node(base, i))

        asyncio.run(_drive_rpc())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


async def _drive_rpc():
    sys.path.insert(0, REPO)
    from cometbft_tpu.rpc import HTTPClient, RPCError

    clients = [HTTPClient("127.0.0.1", BASE_PORT + 2 * i + 1)
               for i in range(4)]

    async def wait_rpc(cli, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                return await cli.call("status")
            except (OSError, RPCError, asyncio.TimeoutError):
                await asyncio.sleep(0.3)
        raise TimeoutError("rpc never came up")

    for cli in clients:
        await wait_rpc(cli)

    # a tx submitted to node0 must commit (gossip to whoever proposes)
    res = await clients[0].call("broadcast_tx_commit", tx=b"pk=pv".hex())
    assert res["tx_result"]["code"] == 0
    h = res["height"]

    # every node reaches that height and agrees on the block hash
    hashes = set()
    for cli in clients:
        deadline = time.monotonic() + 60
        while True:
            st = await cli.call("status")
            if st["sync_info"]["latest_block_height"] >= h:
                break
            assert time.monotonic() < deadline, "node stuck"
            await asyncio.sleep(0.3)
        blk = await cli.call("block", height=h)
        hashes.add(blk["block_id"]["hash"]["~b"])
    assert len(hashes) == 1, f"fork: {hashes}"

    # the app state is queryable through any node
    q = await clients[3].call("abci_query", path="/key", data=b"pk".hex())
    assert bytes.fromhex(q["response"]["value"]) == b"pv"


def test_cli_testnet_kill_and_restart_node(tmp_path):
    """The reference e2e runner's perturbations (test/e2e/runner/perturb.go)
    shrunk to one machine: SIGKILL a validator process mid-chain, the rest
    keep committing, the restarted process recovers from its WAL/stores and
    catches back up to the live chain."""
    base = str(tmp_path / "pnet")
    kill_port = BASE_PORT + 100
    res = _run_cli("testnet", "--v", "4", "--output-dir", base,
                   "--base-port", str(kill_port), "--chain-id", "perturb")
    assert res.returncode == 0, res.stderr

    _patch_testnet_configs(base)

    def spawn(i):
        return _spawn_node(base, i)

    procs = {i: spawn(i) for i in range(4)}
    try:
        asyncio.run(_drive_perturbation(procs, spawn, kill_port))
    finally:
        for p in procs.values():
            try:
                p.send_signal(signal.SIGTERM)
            except Exception:
                pass
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


async def _drive_perturbation(procs, spawn, base_port):
    sys.path.insert(0, REPO)
    from cometbft_tpu.rpc import HTTPClient, RPCError

    def cli(i):
        return HTTPClient("127.0.0.1", base_port + 2 * i + 1)

    async def height(i):
        st = await cli(i).call("status")
        return st["sync_info"]["latest_block_height"]

    async def wait_height(i, h, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if await height(i) >= h:
                    return
            except (OSError, RPCError, asyncio.TimeoutError):
                pass
            await asyncio.sleep(0.3)
        raise TimeoutError(f"node{i} never reached height {h}")

    for i in range(4):
        await wait_height(i, 1)

    # SIGKILL node3 — a hard crash, no cleanup
    procs[3].kill()
    procs[3].wait(timeout=10)

    # the remaining 3/4 (>2/3) keep committing
    h_at_kill = await height(0)
    await wait_height(0, h_at_kill + 5)

    # restart the crashed node: it must recover and catch up to the tip
    procs[3] = spawn(3)
    target = await height(0) + 3
    await wait_height(3, target, timeout=90)

    # all four agree on a recent block hash
    check_h = target
    hashes = set()
    for i in range(4):
        blk = await cli(i).call("block", height=check_h)
        hashes.add(blk["block_id"]["hash"]["~b"])
    assert len(hashes) == 1, f"fork after restart: {hashes}"


def test_start_option_overrides(tmp_path):
    """--option section.key=value overrides config.toml for one run
    (the reference binds a cobra flag per config field)."""
    home = str(tmp_path / "node")
    res = _run_cli("init", "--chain-id", "opt-chain", home=home)
    assert res.returncode == 0, res.stderr

    # bad forms fail fast with a clean error, not a traceback
    for bad in ("nonsense", "rpc.laddr", "bogus.key=1",
                "consensus.timeout_commit=abc", "p2p.pex=maybe",
                "__class__.__name__=X"):
        r = _run_cli("start", "-o", bad, home=home)
        assert r.returncode == 1, (bad, r.stdout)
        assert "Traceback" not in r.stderr, (bad, r.stderr)

    # a good override takes effect: node binds the overridden RPC port
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "start",
         "-o", "rpc.laddr=tcp://127.0.0.1:28799",
         "-o", "consensus.timeout_commit=100000000",
         "-o", "base.signature_backend=cpu"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    try:
        import urllib.request

        deadline = time.monotonic() + 60
        while True:
            try:
                body = urllib.request.urlopen(
                    "http://127.0.0.1:28799/status", timeout=2).read()
                break
            except Exception:
                assert time.monotonic() < deadline and proc.poll() is None
                time.sleep(0.3)
        assert b"opt-chain" in body
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_sigusr_stack_dumps(tmp_path):
    """SIGUSR1 dumps thread stacks, SIGUSR2 dumps asyncio tasks — the
    reference debug command's goroutine-dump analogue — without stopping
    the node."""
    home = str(tmp_path / "node")
    res = _run_cli("init", "--chain-id", "dump-chain", home=home)
    assert res.returncode == 0, res.stderr
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out_path = str(tmp_path / "node.log")
    with open(out_path, "wb") as out:
        proc = subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu", "--home", home, "start",
             "-o", "base.signature_backend=cpu",
             "-o", "rpc.laddr=tcp://127.0.0.1:28811"],
            stdout=out, stderr=subprocess.STDOUT, env=env, cwd=REPO)
    try:
        import urllib.request

        deadline = time.monotonic() + 60
        while True:
            try:
                urllib.request.urlopen(
                    "http://127.0.0.1:28811/health", timeout=2)
                break
            except Exception:
                assert time.monotonic() < deadline and proc.poll() is None
                time.sleep(0.3)
        proc.send_signal(signal.SIGUSR1)
        proc.send_signal(signal.SIGUSR2)
        deadline = time.monotonic() + 30
        while True:
            data = open(out_path).read()
            if "asyncio tasks ===" in data and "Current thread" in data:
                break
            assert time.monotonic() < deadline
            time.sleep(0.3)
        # node survived the dumps
        urllib.request.urlopen("http://127.0.0.1:28811/health", timeout=5)
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
