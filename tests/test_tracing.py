"""Flight-recorder tracing (``libs/tracing``): ring-buffer semantics,
concurrent writers, disabled-mode cost, the ``/dump_trace`` +enriched
``/status`` RPC surface, and the tentpole acceptance — one committed
height whose consensus step spans contain the vote scheduler's verify
micro-batch dispatches."""

import asyncio
import sys
import threading

import pytest

from cometbft_tpu.libs import tracing

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.fixture(autouse=True)
def _tracer_hygiene():
    """Tracing state is process-global: every test starts disabled/empty
    and leaves it that way (node tests elsewhere assume tracing off)."""
    tracing.configure(enabled=False, ring_size=8192)
    tracing.clear()
    yield
    tracing.configure(enabled=False, ring_size=8192)
    tracing.clear()


# ------------------------------------------------------------- core API


def test_event_span_records_and_ordering():
    tracing.configure(enabled=True)
    tracing.event("t", "first", x=1)
    with tracing.span("t", "outer", height=7):
        tracing.event("t", "inner")
    recs = tracing.dump()
    assert [r["name"] for r in recs] == ["first", "inner", "outer"]
    ev_first, ev_inner, sp = recs
    assert ev_first["kind"] == "event" and ev_first["attrs"] == {"x": 1}
    assert sp["kind"] == "span" and sp["attrs"]["height"] == 7
    assert sp["dur_us"] >= 0 and sp["end_ns"] >= sp["start_ns"]
    # the inner event happened within the outer span and points at it
    assert ev_inner["parent"] == sp["id"]
    assert sp["start_ns"] <= ev_inner["start_ns"] <= sp["end_ns"]
    # ids are unique
    assert len({r["id"] for r in recs}) == 3


def test_span_nesting_parent_chain():
    tracing.configure(enabled=True)
    with tracing.span("t", "a"):
        with tracing.span("t", "b"):
            with tracing.span("t", "c"):
                pass
    by_name = {r["name"]: r for r in tracing.dump()}
    assert by_name["c"]["parent"] == by_name["b"]["id"]
    assert by_name["b"]["parent"] == by_name["a"]["id"]
    assert by_name["a"]["parent"] == 0
    # completion order is inside-out; start order is outside-in
    starts = sorted(by_name.values(), key=lambda r: r["start_ns"])
    assert [r["name"] for r in starts] == ["a", "b", "c"]


def test_begin_finish_cross_frame_span_with_extra_attrs():
    tracing.configure(enabled=True)
    sp = tracing.begin("t", "step", step="Prevote")
    tracing.event("t", "mid")
    tracing.finish(sp, verdict="ok")
    span = [r for r in tracing.dump() if r["kind"] == "span"][0]
    assert span["attrs"] == {"step": "Prevote", "verdict": "ok"}
    # finish(None) is the disabled-mode contract
    tracing.finish(None)
    tracing.finish(None, extra=1)


def test_ring_bounded_memory_and_resize():
    tracing.configure(enabled=True, ring_size=64)
    for i in range(1000):
        tracing.event("t", "e", i=i)
    recs = tracing.dump()
    assert len(recs) == 64
    # newest survive, oldest fell off the back
    assert [r["attrs"]["i"] for r in recs] == list(range(936, 1000))
    assert tracing.stats()["buffered"] == 64
    # dump(limit) trims from the newest end
    assert [r["attrs"]["i"] for r in tracing.dump(5)] \
        == list(range(995, 1000))
    # shrinking keeps the newest records
    tracing.configure(ring_size=16)
    assert len(tracing.dump()) == 16


def test_attrs_sanitized_for_json():
    import json

    tracing.configure(enabled=True)
    tracing.event("t", "e", raw=b"\x01\x02", obj=object(), s="x", n=1.5)
    rec = tracing.dump()[0]
    json.dumps(rec)                      # must not raise
    assert rec["attrs"]["raw"] == "0102"
    assert rec["attrs"]["s"] == "x" and rec["attrs"]["n"] == 1.5


# ------------------------------------------------------ concurrency


def test_concurrent_writers_threads_and_asyncio_no_lost_or_torn():
    """8 threads + 8 asyncio tasks hammer the ring concurrently; with
    capacity >= total writes nothing may be lost, every record must be
    intact (id unique, attrs consistent with the writer that built it),
    and memory stays bounded by the ring."""
    per = 250
    n_threads = 8
    n_tasks = 8
    total = per * (n_threads + n_tasks)
    tracing.configure(enabled=True, ring_size=total + 100)

    def thread_writer(wid):
        for i in range(per):
            tracing.event("thr", "w", wid=wid, i=i, tag=wid * 1_000_000 + i)

    async def task_writer(wid):
        for i in range(per):
            tracing.event("aio", "w", wid=wid, i=i, tag=wid * 1_000_000 + i)
            if i % 50 == 0:
                await asyncio.sleep(0)

    async def main():
        threads = [threading.Thread(target=thread_writer, args=(w,))
                   for w in range(n_threads)]
        for t in threads:
            t.start()
        await asyncio.gather(*(task_writer(w) for w in range(n_tasks)))
        for t in threads:
            t.join()

    run(main())
    recs = tracing.dump(total + 100)
    assert len(recs) == total                       # nothing lost
    assert len({r["id"] for r in recs}) == total    # nothing duplicated
    for r in recs:                                  # nothing torn
        a = r["attrs"]
        assert a["tag"] == a["wid"] * 1_000_000 + a["i"], r
    # each writer's own events are in its program order
    for sub, wid in [("thr", 0), ("aio", 0), ("thr", 7), ("aio", 7)]:
        seq = [r["attrs"]["i"] for r in recs
               if r["sub"] == sub and r["attrs"]["wid"] == wid]
        assert seq == list(range(per))


# -------------------------------------------------------- disabled mode


def test_disabled_mode_is_noop_and_allocation_free():
    assert not tracing.is_enabled()
    # span() hands back one shared no-op object: no per-call allocation
    s1 = tracing.span("a", "b")
    s2 = tracing.span("a", "b", k=1)
    assert s1 is s2
    with s1:
        tracing.event("a", "b", x=1)
    assert tracing.begin("a", "b") is None
    assert tracing.dump() == []

    # steady-state allocation check: after warmup, a disabled
    # event/span cycle leaves the interpreter's allocated-block count
    # unchanged (everything it touches is freed before returning)
    def cycle():
        tracing.event("sub", "name", a=1, b="x")
        with tracing.span("sub", "name"):
            pass

    for _ in range(256):
        cycle()
    before = sys.getallocatedblocks()
    for _ in range(4096):
        cycle()
    after = sys.getallocatedblocks()
    assert after - before <= 8, f"disabled tracing leaked {after - before}"
    assert tracing.dump() == []


# ------------------------------------------------------- RPC round-trip


def _single_node_cfg():
    from cometbft_tpu.config import Config
    from cometbft_tpu.config import test_consensus_config as _tcc

    cfg = Config(consensus=_tcc())
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.instrumentation.tracing = True
    cfg.instrumentation.tracing_ring_size = 4096
    return cfg


def test_dump_trace_rpc_roundtrip_and_enriched_status():
    """A tracing-enabled single validator serves its flight recorder via
    GET /dump_trace and the timeline block via /status."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.node import Node
    from cometbft_tpu.rpc import HTTPClient
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    async def main():
        pv = MockPV.from_secret(b"trace-rpc")
        doc = GenesisDoc(chain_id="trace-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)])
        node = await Node.create(doc, KVStoreApplication(),
                                 priv_validator=pv,
                                 config=_single_node_cfg(), name="tr0")
        await node.start()
        try:
            for _ in range(600):
                if node.block_store.height() >= 1:
                    break
                await asyncio.sleep(0.05)
            assert node.block_store.height() >= 1
            cli = HTTPClient(*node.rpc_addr)
            out = await cli.call("dump_trace", limit=2000)
            assert out["enabled"] is True
            assert out["ring_size"] == 4096
            recs = out["records"]
            assert recs and len(recs) <= 2000
            steps = [r for r in recs if r["sub"] == "consensus"
                     and r["name"] == "step"]
            assert steps, "no consensus step spans in the dump"
            names = {r["attrs"]["step"] for r in steps}
            assert {"Propose", "Prevote", "Precommit"} <= names
            commits = [r for r in recs if r["sub"] == "consensus"
                       and r["name"] == "commit"]
            assert commits and commits[0]["attrs"]["height"] >= 1
            # the app calls rode the traced consensus connection
            assert any(r["sub"] == "abci" and
                       r["attrs"].get("method") == "finalize_block"
                       for r in recs)
            # bad limit is a clean RPC error
            from cometbft_tpu.rpc import RPCError

            with pytest.raises(RPCError):
                await cli.call("dump_trace", limit=-1)

            st = await cli.call("status")
            ci = st["consensus_info"]
            assert ci["height"] >= 1 and ci["round"] >= 0
            assert ci["step"] in ("NewHeight", "NewRound", "Propose",
                                  "Prevote", "PrevoteWait", "Precommit",
                                  "PrecommitWait", "Commit")
            assert ci["step_age_s"] >= 0
            assert ci["fatal_error"] is None
            await cli.close()
        finally:
            await node.stop()
        return True

    assert run(main())


# -------------------------------------------------- tentpole acceptance


def test_height_timeline_contains_scheduler_microbatches():
    """Acceptance: with tracing on, one committed height's trace shows
    its consensus step spans AND the verify micro-batch dispatches the
    vote scheduler ran inside them (time containment in the height's
    [first step start, last step end] window)."""
    from cometbft_tpu.crypto import scheduler as vsched
    from cometbft_tpu.testing import make_inproc_network

    async def main():
        tracing.configure(enabled=True, ring_size=16384)
        sched = await vsched.acquire_scheduler(backend="cpu",
                                               max_wait_ms=1.0)
        net = await make_inproc_network(4)
        # the ensemble shares ONE process-wide verified-sig cache, and
        # in-proc gossip is synchronous: a signer's own-vote verification
        # seeds the cache in the same event-loop slice that delivers the
        # vote to every peer, so prefetches always hit and the dispatch
        # path never runs.  Production hosts each hold their own cold
        # cache — emulate that by forcing lookups to miss (seeding and
        # in-flight dedup stay live), which routes gossip through the
        # micro-batch dispatches this test is about.
        sched.cache.hit = lambda key: False
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
        finally:
            await net.stop()
            await vsched.release_scheduler()
        assert sched.stats()["batches"] > 0, \
            "scheduler never dispatched a micro-batch"
        return tracing.dump(16384)

    recs = run(main())
    steps = [r for r in recs
             if r["sub"] == "consensus" and r["name"] == "step"]
    dispatches = [r for r in recs
                  if r["sub"] == "crypto.sched" and r["name"] == "dispatch"]
    flushes = [r for r in recs
               if r["sub"] == "crypto.sched" and r["name"] == "flush"]
    assert steps and dispatches and flushes
    # pick a committed height and build its wall-clock window from its
    # step spans; at least one micro-batch dispatch must sit inside it
    heights = sorted({r["attrs"]["height"] for r in steps
                      if r["attrs"]["step"] == "Commit"})
    assert heights, "no height reached Commit in the trace"
    found = None
    for h in heights:
        hs = [r for r in steps if r["attrs"]["height"] == h]
        t_lo = min(r["start_ns"] for r in hs)
        t_hi = max(r["end_ns"] for r in hs)
        inside = [d for d in dispatches
                  if t_lo <= d["start_ns"] and d["end_ns"] <= t_hi]
        # the height shows the nested propose->prevote->precommit
        # progression, not just a single step
        step_names = {r["attrs"]["step"] for r in hs}
        if inside and {"Propose", "Prevote", "Precommit"} <= step_names:
            found = (h, len(inside))
            break
    assert found, "no committed height contains a scheduler dispatch"
