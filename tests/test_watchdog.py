"""Liveness watchdog: stall detection, black-box incident bundles, the
rate limit, WAL-tail capture, `/dump_incidents`, and the per-peer label
budget.  Fast tests drive the watchdog synchronously against a stub node
(check() needs no event loop); the live induced-stall test is tier-2
with the other real-TCP net suites."""

import asyncio
import json
import os
from types import SimpleNamespace

import pytest

from cometbft_tpu.node.watchdog import (BUNDLE_PREFIX, LivenessWatchdog,
                                        list_incidents, load_incident,
                                        wal_tail)

pytestmark = pytest.mark.timeout(120)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ------------------------------------------------------------- stub node

class _StubRS:
    height, round = 7, 2

    def step_name(self):
        return "Prevote"


class _StubConsensus:
    """Looks enough like ConsensusState for the watchdog's read paths."""

    def __init__(self, step_age=999.0, commit_age_s=999.0):
        self.rs = _StubRS()
        self.fatal_error = None
        self.wal = None
        self._task = object()            # "started"
        self._step_age = step_age
        self._now = 1_000_000 * 10**9
        self._last_commit_wall_ns = self._now - int(commit_age_s * 1e9)

    def step_age_s(self):
        return self._step_age

    def now_ns(self):
        return self._now


def _stub_node(tmp_path, step_age=999.0, peers_quiet_age=None):
    switch = SimpleNamespace(
        peers={"p1": object()} if peers_quiet_age is not None else {},
        peer_snapshot=lambda: [{"node_id": "p1", "connection_status": {}}],
        quietest_peer_recv_age_s=lambda: peers_quiet_age)
    return SimpleNamespace(
        name="stub",
        consensus=_StubConsensus(step_age=step_age),
        switch=switch,
        block_store=SimpleNamespace(height=lambda: 7),
    )


def _watchdog(node, tmp_path, **kw):
    kw.setdefault("stall_threshold_s", 1.0)
    kw.setdefault("check_interval_s", 0.05)
    kw.setdefault("min_interval_s", 60.0)
    d = os.path.join(str(tmp_path), "incidents")
    os.makedirs(d, exist_ok=True)
    return LivenessWatchdog(node, d, **kw)


def _bundles(wd):
    return sorted(n for n in os.listdir(wd.incident_dir)
                  if n.startswith(BUNDLE_PREFIX))


# ----------------------------------------------------------- fast: trips

def test_stall_trips_and_writes_bundle(tmp_path):
    node = _stub_node(tmp_path, step_age=999.0, peers_quiet_age=500.0)
    wd = _watchdog(node, tmp_path)
    path = wd.check()
    assert path is not None and os.path.exists(path)
    bundle = json.loads(open(path).read())
    assert "consensus_step_stalled" in bundle["reasons"]
    assert "no_recent_commit" in bundle["reasons"]
    assert "peers_quiet" in bundle["reasons"]
    assert bundle["consensus"]["step"] == "Prevote"
    assert bundle["consensus"]["step_age_s"] == 999.0
    assert bundle["peers"] == [{"node_id": "p1", "connection_status": {}}]
    assert bundle["height"] == 7
    assert "records" in bundle["trace"]       # ring dump (may be empty)
    assert bundle["wal_tail"] == []           # stub has no WAL
    assert wd.trips == 1 and wd.bundles_written == 1


def test_no_stall_is_a_noop(tmp_path):
    node = _stub_node(tmp_path, step_age=0.01)
    node.consensus._last_commit_wall_ns = node.consensus._now
    wd = _watchdog(node, tmp_path)
    assert wd.check() is None
    assert wd.trips == 0 and _bundles(wd) == []


def test_unstarted_consensus_never_trips(tmp_path):
    """Blocksync/statesync phases park consensus legitimately: an
    unstarted state machine (no _task) must not read as a stall."""
    node = _stub_node(tmp_path, step_age=999.0)
    node.consensus._task = None
    wd = _watchdog(node, tmp_path)
    assert wd.check() is None
    assert wd.trips == 0


def test_fatal_error_is_a_reason(tmp_path):
    node = _stub_node(tmp_path, step_age=0.01)
    node.consensus._last_commit_wall_ns = node.consensus._now
    node.consensus.fatal_error = RuntimeError("boom")
    wd = _watchdog(node, tmp_path)
    path = wd.check()
    bundle = json.loads(open(path).read())
    assert bundle["reasons"] == ["consensus_fatal_error"]
    assert "boom" in bundle["consensus"]["fatal_error"]


def test_rate_limit_suppresses_and_recovers(tmp_path):
    node = _stub_node(tmp_path, step_age=999.0)
    wd = _watchdog(node, tmp_path, min_interval_s=3600.0)
    assert wd.check() is not None
    for _ in range(5):                       # persisting stall, same hour
        assert wd.check() is None
    assert wd.trips == 6                     # every detection counted
    assert wd.bundles_written == 1           # but one bundle
    assert len(_bundles(wd)) == 1
    wd._last_bundle_mono -= 3601             # the hour passes
    assert wd.check() is not None
    assert len(_bundles(wd)) == 2


def test_bundle_pruning_keeps_newest(tmp_path):
    node = _stub_node(tmp_path, step_age=999.0)
    wd = _watchdog(node, tmp_path, min_interval_s=0.0, max_bundles=3)
    paths = [wd.check() for _ in range(6)]
    kept = _bundles(wd)
    assert len(kept) == 3
    assert os.path.basename(paths[-1]) in kept
    assert os.path.basename(paths[0]) not in kept


# --------------------------------------------------------- fast: wal tail

def test_wal_tail_returns_newest_records(tmp_path):
    from cometbft_tpu.consensus.wal import WAL

    # tiny segments force rotation so the tail spans files
    wal = WAL(os.path.join(str(tmp_path), "cs.wal"),
              max_segment_bytes=2048)
    for i in range(300):
        wal.write({"seq": i, "pad": b"x" * 32})
    tail = wal_tail(wal, 50)
    assert [r["seq"] for r in tail] == list(range(250, 300))
    assert tail[0]["pad"] == (b"x" * 32).hex()      # bytes -> hex
    # limit larger than the log returns everything, in order
    assert [r["seq"] for r in wal_tail(wal, 10_000)] == list(range(300))
    assert wal_tail(wal, 0) == [] and wal_tail(None, 50) == []
    wal.close()


# ------------------------------------------------------ fast: listing/RPC

def test_list_and_load_incidents(tmp_path):
    node = _stub_node(tmp_path, step_age=999.0)
    wd = _watchdog(node, tmp_path, min_interval_s=0.0)
    p1 = wd.check()
    p2 = wd.check()
    listing = list_incidents(wd.incident_dir)
    assert len(listing) == 2
    assert listing[0]["name"] == os.path.basename(p2)   # newest first
    assert listing[0]["size_bytes"] > 0
    assert "consensus_step_stalled" in listing[0]["reasons"]
    assert listing[0]["wall_time_ns"] is not None
    loaded = load_incident(wd.incident_dir, listing[1]["name"])
    assert loaded["reasons"] == json.loads(open(p1).read())["reasons"]
    # RPC-reachable: path components and non-bundle names are refused
    assert load_incident(wd.incident_dir, "../secrets.json") is None
    assert load_incident(wd.incident_dir, "notabundle.json") is None
    assert load_incident(wd.incident_dir, "incident-x.json") is None
    assert list_incidents(os.path.join(str(tmp_path), "absent")) == []


def test_incident_dir_resolution():
    """No home + relative dir -> watchdog has nowhere safe to write and
    resolves to None; absolute dirs always win."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.node import Node

    n = Node()
    n.config = Config()
    assert n.incident_dir() is None
    n.home = "/tmp/home-x"
    assert n.incident_dir() == "/tmp/home-x/data/incidents"
    n.config.instrumentation.watchdog_incident_dir = "/var/incidents"
    n.home = None
    assert n.incident_dir() == "/var/incidents"


# ------------------------------------------- fast: per-peer label budget

def test_dup_vote_counter_labels_bounded_under_peer_churn():
    """Satellite regression: the per-peer gossip-efficiency counters ride
    the metric-level cardinality guard, so unbounded peer churn cannot
    grow the registry past the peer label budget."""
    from cometbft_tpu.consensus.reactor import (_dup_votes_metric,
                                                _useful_votes_metric)
    from cometbft_tpu.p2p.metrics import PEER_LABEL_BUDGET, peer_label

    dup, useful = _dup_votes_metric(), _useful_votes_metric()
    assert dup.max_label_sets == PEER_LABEL_BUDGET
    assert useful.max_label_sets == PEER_LABEL_BUDGET
    before_evictions = dup.evicted_total
    for i in range(PEER_LABEL_BUDGET * 3):      # churn 3 budgets of peers
        pid = f"{i:012d}" + "ab" * 14           # distinct 12-char prefixes
        dup.bind(peer=peer_label(pid)).inc()
        useful.inc(peer=peer_label(pid))
    assert dup.label_sets() <= PEER_LABEL_BUDGET
    assert useful.label_sets() <= PEER_LABEL_BUDGET
    assert dup.evicted_total > before_evictions


# --------------------------------------------------- tier-2: live 2-node

@pytest.mark.slow
def test_live_stall_produces_bundle_and_dump_incidents(tmp_path):
    """Acceptance: an induced consensus stall on a live 2-node TCP net
    (kill one of two equal-power validators -> no more 2/3) produces an
    on-disk incident bundle within the configured threshold containing
    step spans, the peer snapshot and the WAL tail — and the survivor's
    `GET /dump_incidents` serves it."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import Config
    from cometbft_tpu.config import test_consensus_config as _tcc
    from cometbft_tpu.node import Node
    from cometbft_tpu.p2p import NodeKey
    from cometbft_tpu.rpc import HTTPClient
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.priv_validator import MockPV

    async def main():
        pvs = [MockPV.from_secret(b"wdnode%d" % i) for i in range(2)]
        doc = GenesisDoc(chain_id="wd-net",
                         validators=[GenesisValidator(pv.get_pub_key(), 10)
                                     for pv in pvs])
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = Config(consensus=_tcc())
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.rpc.laddr = "tcp://127.0.0.1:0" if i == 0 else ""
            cfg.instrumentation.tracing = True
            cfg.instrumentation.watchdog_stall_threshold_s = 1.0
            cfg.instrumentation.watchdog_check_interval_s = 0.2
            cfg.instrumentation.watchdog_min_interval_s = 60.0
            cfg.p2p.telemetry_flush_interval_s = 0.5
            node = await Node.create(
                doc, KVStoreApplication(), priv_validator=pv, config=cfg,
                node_key=NodeKey.from_secret(b"wk%d" % i),
                home=os.path.join(str(tmp_path), f"n{i}"), name=f"wd{i}")
            nodes.append(node)
            await node.start()
        try:
            assert nodes[0].liveness_watchdog is not None
            await nodes[0].dial_peer(nodes[1].listen_addr,
                                     persistent=False)
            # both validators needed for 2/3: reach a height together
            for _ in range(600):
                if all(n.height() >= 2 for n in nodes):
                    break
                await asyncio.sleep(0.05)
            assert all(n.height() >= 2 for n in nodes), "net never started"

            # enriched /net_info while the peer is still up
            cli = HTTPClient(*nodes[0].rpc_addr)
            try:
                ni = await cli.call("net_info")
                assert ni["n_peers"] == 1
                peer = ni["peers"][0]
                conn = peer["connection_status"]
                assert conn["recv_bytes_total"] > 0
                assert "send_rate" in conn and "recv_rate" in conn
                assert "last_rtt_s" in conn
                vote_ch = conn["channels"]["vote"]
                assert vote_ch["recv_msgs"] > 0
                assert vote_ch["send_queue_capacity"] > 0
                assert "send_queue" in vote_ch
                assert "queue_full_drops" in vote_ch
                assert "useful_votes" in peer["gossip"]

                # induce the stall: the other validator dies
                await nodes[1].stop()
                incident_dir = nodes[0].incident_dir()
                deadline = asyncio.get_running_loop().time() + 30
                bundle_names = []
                while asyncio.get_running_loop().time() < deadline:
                    if os.path.isdir(incident_dir):
                        bundle_names = [
                            n for n in os.listdir(incident_dir)
                            if n.startswith(BUNDLE_PREFIX)
                            and n.endswith(".json")]
                        if bundle_names:
                            break
                    await asyncio.sleep(0.1)
                assert bundle_names, "watchdog never wrote a bundle"

                out = await cli.call("dump_incidents")
                assert out["enabled"] and len(out["incidents"]) >= 1
                name = out["incidents"][0]["name"]
                full = await cli.call("dump_incidents", name=name)
                bundle = full["bundle"]
                assert any(r in ("consensus_step_stalled",
                                 "no_recent_commit")
                           for r in bundle["reasons"])
                assert isinstance(bundle["peers"], list)
                steps = [r for r in bundle["trace"]["records"]
                         if r["sub"] == "consensus" and r["name"] == "step"]
                assert steps, "bundle carries no consensus step spans"
                assert bundle["wal_tail"], "bundle carries no WAL tail"
                assert bundle["consensus"]["height"] >= 2
            finally:
                await cli.close()
        finally:
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass
        return True

    assert run(main())
