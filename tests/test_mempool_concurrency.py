"""Mempool admission concurrency (VERDICT r3 item 9): check_tx no longer
serializes on one lock across the app round-trip — one slow CheckTx must
not stall other admissions — while the executor's update/flush critical
section stays exclusive against in-flight admissions."""

import asyncio
import time

import pytest

from cometbft_tpu.mempool.clist_mempool import CListMempool, TxRejectedError

pytestmark = pytest.mark.timeout(60)


class SlowCheckApp:
    """CheckTx sleeps per-tx as directed; records concurrency level."""

    def __init__(self):
        self.inflight = 0
        self.max_inflight = 0
        self.checked: list[bytes] = []

    async def check_tx(self, tx: bytes, recheck: bool = False):
        from cometbft_tpu.abci.types import CheckTxResponse

        self.inflight += 1
        self.max_inflight = max(self.max_inflight, self.inflight)
        delay = 0.3 if tx.startswith(b"slow") else 0.01
        await asyncio.sleep(delay)
        self.inflight -= 1
        self.checked.append(tx)
        return CheckTxResponse(code=0, gas_wanted=1)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_slow_checktx_does_not_stall_admission():
    """10 fast admissions complete while one slow CheckTx is in flight:
    total wall-clock ~= the slow call, not the sum."""

    async def main():
        app = SlowCheckApp()
        mp = CListMempool(app)
        t0 = time.perf_counter()
        txs = [b"slow-0"] + [b"fast-%d" % i for i in range(10)]
        await asyncio.gather(*(mp.check_tx(tx) for tx in txs))
        dt = time.perf_counter() - t0
        assert mp.size() == 11
        assert app.max_inflight > 1, "admissions were serialized"
        # serialized would be ~0.3 + 10*0.01 = 0.4s minimum; pipelined
        # is ~0.3s.  Assert well under the serial bound.
        assert dt < 0.38, dt
        return True

    assert run(main())


def test_update_excludes_inflight_admissions():
    """The executor's lock() (writer) waits for in-flight admissions and
    blocks new ones, so update/recheck sees a quiescent mempool."""

    async def main():
        app = SlowCheckApp()
        mp = CListMempool(app)
        await mp.check_tx(b"fast-pre")

        adm = asyncio.ensure_future(mp.check_tx(b"slow-1"))
        await asyncio.sleep(0.05)          # slow admission now in flight
        t0 = time.perf_counter()
        async with mp.lock():
            # writer acquired only after the in-flight admission finished
            waited = time.perf_counter() - t0
            assert waited > 0.15, waited
            late = asyncio.ensure_future(mp.check_tx(b"fast-late"))
            await asyncio.sleep(0.05)
            assert not late.done(), "admission ran during the critical section"
            await mp.update(2, [b"fast-pre"], [])
        await asyncio.gather(adm, late)
        assert mp.size() == 2              # slow-1 + fast-late survive
        assert mp.height == 2
        return True

    assert run(main())


def test_full_mempool_rechecked_after_app_roundtrip():
    """The capacity check re-runs after the await: concurrent admissions
    racing past the pre-check can't overfill the pool."""

    async def main():
        app = SlowCheckApp()
        mp = CListMempool(app, max_txs=3)
        results = await asyncio.gather(
            *(mp.check_tx(b"tx-%d" % i) for i in range(6)),
            return_exceptions=True)
        rejected = [r for r in results if isinstance(r, TxRejectedError)]
        assert mp.size() == 3
        assert len(rejected) == 3
        assert all("full" in str(r) for r in rejected)
        return True

    assert run(main())


def test_arrival_fifo_preserved_under_out_of_order_completion():
    """Reap/gossip order follows ARRIVAL order even when the app answers
    CheckTx out of order (the slow tx arrives first, completes last)."""

    async def main():
        app = SlowCheckApp()
        mp = CListMempool(app)
        txs = [b"slow-first"] + [b"fast-%d" % i for i in range(5)]
        await asyncio.gather(*(mp.check_tx(tx) for tx in txs))
        # dict insertion order is completion order (slow-first is LAST)…
        assert app.checked[-1] == b"slow-first"
        # …but reaping restores arrival order
        assert mp.reap_max_txs(10) == txs
        assert mp.contents() == txs
        assert mp.reap_max_bytes_max_gas(-1, -1) == txs
        return True

    assert run(main())
