"""Snapshot-fabric tests: content-addressed manifests, the blob-pool
spool (dedup / retention / adopt-resume), corrupt-chunk recovery
without restore resets, serving-side LRU + admission gate, the
fatal-IO spool discipline, provider retry, the ``[statesync]`` config
knobs, and the deterministic fleet scenario lab."""

import asyncio
import errno
import hashlib
from types import SimpleNamespace

import pytest

from cometbft_tpu.statesync.manifest import (ChunkManifest, hash_chunk,
                                             manifest_root,
                                             valid_hash_list)
from cometbft_tpu.statesync.syncer import (StatesyncError,
                                           StatesyncFatalError, Syncer,
                                           _BlobPool, _ChunkStore,
                                           _is_fatal_io_error,
                                           _PendingSnapshot)

pytestmark = pytest.mark.timeout(150)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ----------------------------------------------------------- manifest


def test_manifest_root_binds_snapshot_and_order():
    hs = [hash_chunk(b"c%d" % i) for i in range(4)]
    root = manifest_root(b"\xcd" * 32, hs)
    # bound to the snapshot hash: no cross-snapshot replay
    assert manifest_root(b"\xce" * 32, hs) != root
    # bound to chunk ORDER, not just the set
    assert manifest_root(b"\xcd" * 32, list(reversed(hs))) != root

    assert valid_hash_list(b"\xcd" * 32, hs, 4, root)
    assert not valid_hash_list(b"\xcd" * 32, hs, 5, root)      # count
    assert not valid_hash_list(b"\xcd" * 32, hs[:3], 4, root)  # short
    assert not valid_hash_list(b"\xce" * 32, hs, 4, root)      # binding
    assert not valid_hash_list(b"\xcd" * 32, hs, 4, b"\x00" * 32)
    # shape: every entry must be a 32-byte digest
    assert not valid_hash_list(b"\xcd" * 32, hs[:3] + [b"short"], 4, root)
    assert not valid_hash_list(b"\xcd" * 32, hs[:3] + ["str"], 4, root)


def test_chunk_manifest_verifies_chunks():
    chunks = [b"alpha", b"beta", b"gamma"]
    mf = ChunkManifest.from_chunks(b"\xcd" * 32, chunks)
    assert len(mf) == 3
    assert mf.root == manifest_root(b"\xcd" * 32,
                                    [hash_chunk(c) for c in chunks])
    for i, c in enumerate(chunks):
        assert mf.verify_chunk(i, c)
        assert not mf.verify_chunk(i, c + b"!")
    assert not mf.verify_chunk(-1, b"alpha")
    assert not mf.verify_chunk(3, b"alpha")


# -------------------------------------------------- blob pool / spool


def test_blob_pool_dedups_identical_content():
    pool = _BlobPool(in_memory=True, retain_bytes=1 << 20)
    h = hashlib.sha256(b"DATA").digest()
    assert pool.put(h, b"DATA")
    assert pool.put(h, b"DATA")          # second put: ref++, no copy
    assert pool.dedup_hits == 1
    assert pool.get(h) == b"DATA"
    pool.release(h)
    assert pool.get(h) == b"DATA"        # still referenced
    pool.release(h)                      # last ref -> retained tier
    assert pool.acquire(h)               # adopt path revives it
    assert pool.dedup_hits == 1          # acquire is not a dedup
    pool.close()


def test_blob_pool_retention_budget_evicts_oldest():
    pool = _BlobPool(in_memory=True, retain_bytes=250)
    hs = []
    for i in range(4):
        data = bytes([i]) * 100
        h = hashlib.sha256(data).digest()
        pool.put(h, data)
        hs.append(h)
    for h in hs:
        pool.release(h)          # all retire into the retained tier
    # 400 B over a 250 B budget: the two oldest blobs are gone
    assert not pool.acquire(hs[0])
    assert not pool.acquire(hs[1])
    assert pool.acquire(hs[2])
    assert pool.acquire(hs[3])
    pool.close()


def test_blob_pool_zero_budget_deletes_on_release():
    pool = _BlobPool(in_memory=True, retain_bytes=0)
    h = hashlib.sha256(b"X").digest()
    pool.put(h, b"X")
    pool.release(h)
    assert not pool.acquire(h)
    pool.close()


def test_chunk_store_adopts_retained_blobs_across_attempts():
    """The resume path: a failed attempt's chunks survive in the shared
    pool's retained tier and the NEXT attempt adopts them by manifest
    hash instead of re-fetching."""
    pool = _BlobPool(in_memory=True, retain_bytes=1 << 20)
    h = hashlib.sha256(b"CHUNK-0").digest()

    first = _ChunkStore(pool=pool)
    first[0] = (b"CHUNK-0", "peerA")
    first.close()                        # attempt failed: refs released

    second = _ChunkStore(pool=pool)
    assert second.adopt(0, h)
    assert second[0] == (b"CHUNK-0", "")
    assert not second.adopt(0, h)        # already indexed
    assert not second.adopt(1, b"\x00" * 32)   # unknown content
    second.close()
    pool.close()


def test_chunk_store_pop_if_sender_race():
    """The banned-mid-write guard: pop only when the chunk still came
    from the banned sender — never a good peer's fresh replacement."""
    store = _ChunkStore(in_memory=True)
    store[0] = (b"evil-bytes", "evil")
    assert not store.pop_if_sender(0, "good")
    assert 0 in store
    # good peer overwrote the slot before the late purge ran
    store[0] = (b"good-bytes", "good")
    assert not store.pop_if_sender(0, "evil")
    assert store[0] == (b"good-bytes", "good")
    assert store.pop_if_sender(0, "good")
    assert 0 not in store
    store.close()


# ------------------------------------------ add_chunk + manifest gate


def _syncer_with_manifest(chunks):
    sy = Syncer(app_conns=None, state_provider=None,
                in_memory_spool=True)
    snap = SimpleNamespace(height=7, format=1, chunks=len(chunks),
                           hash=b"\xcd" * 32)
    sy._current = _PendingSnapshot(snap)
    sy._manifest = [hash_chunk(c) for c in chunks]
    return sy


def test_add_chunk_spools_only_verified_bytes():
    sy = _syncer_with_manifest([b"C0", b"C1"])
    sy.add_chunk("evil", 7, 1, 0, b"CORRUPT", b"\xcd" * 32)
    assert 0 not in sy._chunks                 # never touched the spool
    assert "evil" in sy._banned
    assert 0 in sy._refetch                    # re-request flagged
    assert sy.tallies["chunk_hash_mismatches"] == 1
    assert sy.tallies["senders_banned"] == 1

    sy.add_chunk("good", 7, 1, 0, b"C0", b"\xcd" * 32)
    assert sy._chunks[0] == (b"C0", "good")
    assert sy.tallies["chunks_verified"] == 1
    # a late delivery from the banned sender is dropped outright
    sy.add_chunk("evil", 7, 1, 1, b"C1", b"\xcd" * 32)
    assert 1 not in sy._chunks
    sy._chunks.close()
    sy._pool.close()


def test_add_chunk_drops_stale_snapshot_responses():
    sy = _syncer_with_manifest([b"C0"])
    for h, f, sh in ((8, 1, b"\xcd" * 32),     # wrong height
                     (7, 2, b"\xcd" * 32),     # wrong format
                     (7, 1, b"\xee" * 32)):    # wrong snapshot hash
        sy.add_chunk("p", h, f, 0, b"C0", sh)
    assert 0 not in sy._chunks
    assert sy.tallies["chunks_verified"] == 0
    sy._chunks.close()
    sy._pool.close()


def test_restore_recovers_from_corrupt_chunks_without_reset():
    """The tentpole property end to end at the syncer layer: a peer
    serving corrupt bytes is caught against the negotiated manifest,
    banned, and routed around — the restore completes off the honest
    peer with ZERO whole-restore resets."""
    from cometbft_tpu.abci import types as abci_t

    chunks = [b"CHUNK-%d" % i for i in range(4)]
    hashes = [hash_chunk(c) for c in chunks]
    root = manifest_root(b"\xcd" * 32, hashes)

    applied = {}

    class SnapConn:
        async def offer_snapshot(self, snapshot, app_hash):
            return abci_t.OFFER_SNAPSHOT_ACCEPT

        async def apply_snapshot_chunk(self, index, chunk, sender):
            applied[index] = (chunk, sender)
            return abci_t.APPLY_CHUNK_ACCEPT

    class QueryConn:
        async def info(self):
            return abci_t.InfoResponse(last_block_height=5,
                                       last_block_app_hash=b"\xab" * 32)

    class Provider:
        async def app_hash(self, h):
            return b"\xab" * 32

        async def state(self, h):
            return "S"

        async def commit(self, h):
            return "C"

    class Reactor:
        def __init__(self, box):
            self.box = box

        def request_manifest(self, peer, height, format_, sh):
            self.box[0].add_manifest(peer, height, format_, sh,
                                     list(hashes))
            return True

        def request_chunk(self, peer, height, format_, index, sh):
            data = chunks[index] if peer == "good" \
                else chunks[index][:-1] + b"!"

            async def deliver():
                self.box[0].add_chunk(peer, height, format_, index,
                                      data, sh)

            asyncio.get_event_loop().create_task(deliver())
            return True

    async def main():
        conns = SimpleNamespace(snapshot=SnapConn(), query=QueryConn())
        box = [None]
        syncer = Syncer(conns, Provider(), reactor=Reactor(box),
                        in_memory_spool=True)
        box[0] = syncer
        snapshot = abci_t.Snapshot(height=5, format=1, chunks=4,
                                   hash=b"\xcd" * 32, metadata=b"")
        # the corrupting peer is FIRST in the rotation
        syncer.add_snapshot("evil", snapshot, manifest_root=root)
        syncer.add_snapshot("good", snapshot, manifest_root=root)
        state, commit = await syncer._restore(
            syncer._snapshots[(5, 1, b"\xcd" * 32)])
        assert (state, commit) == ("S", "C")
        return syncer

    syncer = run(main())
    assert set(applied) == {0, 1, 2, 3}
    assert all(s == "good" for _, s in applied.values())
    assert "evil" in syncer._banned
    t = syncer.tallies
    assert t["chunk_hash_mismatches"] >= 1
    assert t["chunks_verified"] == 4
    assert t["restore_resets"] == 0, \
        "a corrupt chunk must never reset the restore"
    syncer._pool.close()


def test_restore_rejects_lying_manifest_server():
    """A peer advertising the majority root but serving a DIFFERENT
    hash list is caught by the root check inside add_manifest, banned,
    and the next holder serves the real list."""
    from cometbft_tpu.abci import types as abci_t

    chunks = [b"A", b"B"]
    hashes = [hash_chunk(c) for c in chunks]
    root = manifest_root(b"\xcd" * 32, hashes)
    lies = [hash_chunk(b"X"), hash_chunk(b"Y")]

    class SnapConn:
        async def offer_snapshot(self, snapshot, app_hash):
            return abci_t.OFFER_SNAPSHOT_ACCEPT

        async def apply_snapshot_chunk(self, index, chunk, sender):
            return abci_t.APPLY_CHUNK_ACCEPT

    class QueryConn:
        async def info(self):
            return abci_t.InfoResponse(last_block_height=5,
                                       last_block_app_hash=b"\xab" * 32)

    class Provider:
        async def app_hash(self, h):
            return b"\xab" * 32

        async def state(self, h):
            return "S"

        async def commit(self, h):
            return "C"

    class Reactor:
        def __init__(self, box):
            self.box = box
            self.manifest_reqs = []

        def request_manifest(self, peer, height, format_, sh):
            self.manifest_reqs.append(peer)
            hs = lies if peer == "liar" else hashes
            self.box[0].add_manifest(peer, height, format_, sh, list(hs))
            return True

        def request_chunk(self, peer, height, format_, index, sh):
            async def deliver():
                self.box[0].add_chunk(peer, height, format_, index,
                                      chunks[index], sh)

            asyncio.get_event_loop().create_task(deliver())
            return True

    async def main():
        conns = SimpleNamespace(snapshot=SnapConn(), query=QueryConn())
        box = [None]
        reactor = Reactor(box)
        syncer = Syncer(conns, Provider(), reactor=reactor,
                        in_memory_spool=True)
        box[0] = syncer
        snapshot = abci_t.Snapshot(height=5, format=1, chunks=2,
                                   hash=b"\xcd" * 32, metadata=b"")
        syncer.add_snapshot("liar", snapshot, manifest_root=root)
        syncer.add_snapshot("hon1", snapshot, manifest_root=root)
        syncer.add_snapshot("hon2", snapshot, manifest_root=root)
        await syncer._restore(syncer._snapshots[(5, 1, b"\xcd" * 32)])
        return syncer, reactor

    syncer, reactor = run(main())
    assert "liar" in syncer._banned
    assert len(reactor.manifest_reqs) >= 2     # fell through to honest
    syncer._pool.close()


# ---------------------------------------------- fatal-IO spool (sat 1)


def test_is_fatal_io_error_classification():
    for e in (errno.EIO, errno.ENOSPC, errno.EROFS, errno.EDQUOT,
              errno.ENXIO):
        assert _is_fatal_io_error(OSError(e, "dead"))
    for e in (errno.ENOENT, errno.EAGAIN, errno.EINTR):
        assert not _is_fatal_io_error(OSError(e, "transient"))
    assert not _is_fatal_io_error(OSError("no errno"))


def test_spool_enospc_fails_sync_with_fatal_error():
    from cometbft_tpu.libs import failures as F

    F.reset()
    F.configure(enabled=True, seed=7,
                faults=["statesync.spool.enospc:every=1"])
    try:
        sy = Syncer(app_conns=None, state_provider=None,
                    in_memory_spool=True)
        snap = SimpleNamespace(height=7, format=1, chunks=2,
                               hash=b"\xcd" * 32)
        pending = _PendingSnapshot(snap)
        pending.peers.append("p")
        sy._current = pending
        sy.add_chunk("p", 7, 1, 0, b"data", b"\xcd" * 32)
        assert isinstance(sy._fatal, StatesyncFatalError)
        assert "ENOSPC" in str(sy._fatal)
        assert 0 not in sy._chunks

        async def main():
            with pytest.raises(StatesyncFatalError):
                await sy._fetch_and_apply(pending)

        run(main())
        sy._chunks.close()
        sy._pool.close()
    finally:
        F.reset()


def test_spool_nonfatal_oserror_does_not_kill_sync():
    sy = Syncer(app_conns=None, state_provider=None,
                in_memory_spool=True)
    sy._spool_failed(0, OSError(errno.ENOENT, "transient"))
    assert sy._fatal is None
    sy._chunks.close()
    sy._pool.close()


# ------------------------------------------- provider retries (sat 2)


def test_stateprovider_retries_transient_failures():
    from cometbft_tpu.statesync.stateprovider import StateProvider

    class FlakyLight:
        def __init__(self, fail, exc):
            self.calls = 0
            self.fail = fail
            self.exc = exc

        async def verify_light_block_at_height(self, height):
            self.calls += 1
            if self.calls <= self.fail:
                raise self.exc
            return SimpleNamespace(
                header=SimpleNamespace(app_hash=b"\xab" * 32),
                commit="COMMIT")

    async def main():
        # two transient failures, then success
        light = FlakyLight(2, TimeoutError("slow"))
        sp = StateProvider(light, None, retries=2, backoff_s=0.0)
        assert await sp.app_hash(4) == b"\xab" * 32
        assert light.calls == 3

        # retries exhausted: the transient error surfaces
        light = FlakyLight(99, ConnectionError("refused"))
        sp = StateProvider(light, None, retries=1, backoff_s=0.0)
        with pytest.raises(ConnectionError):
            await sp.commit(4)
        assert light.calls == 2

        # verification failures are NOT transient: no retry
        light = FlakyLight(99, ValueError("bad header"))
        sp = StateProvider(light, None, retries=3, backoff_s=0.0)
        with pytest.raises(ValueError):
            await sp.commit(4)
        assert light.calls == 1

    run(main())


# ------------------------------------------------ config knobs (sat 3)


def test_statesync_config_validation_bounds():
    from cometbft_tpu.config import Config, ConfigError

    Config().validate()
    bad = [("chunk_timeout_s", 0.0), ("chunk_timeout_s", -1.0),
           ("max_inflight_per_peer", 0), ("max_inflight_per_peer", 65),
           ("discovery_time_s", 0.0), ("discovery_rounds", 0),
           ("discovery_rounds", 101), ("chunk_retries", -1),
           ("spool_retain_bytes", -1), ("chunk_cache_bytes", -1),
           ("serve_concurrency", 0), ("serve_queue", -1)]
    for field_, value in bad:
        cfg = Config()
        setattr(cfg.statesync, field_, value)
        with pytest.raises(ConfigError):
            cfg.validate()


def test_statesync_config_toml_round_trip(tmp_path):
    from cometbft_tpu.config import Config

    cfg = Config()
    cfg.statesync.chunk_timeout_s = 7.5
    cfg.statesync.max_inflight_per_peer = 8
    cfg.statesync.discovery_rounds = 9
    cfg.statesync.chunk_retries = 5
    cfg.statesync.spool_retain_bytes = 1 << 20
    cfg.statesync.chunk_cache_bytes = 2 << 20
    cfg.statesync.serve_concurrency = 3
    cfg.statesync.serve_queue = 17
    path = str(tmp_path / "config.toml")
    cfg.save(path)
    back = Config.load(path)
    for f_ in ("chunk_timeout_s", "max_inflight_per_peer",
               "discovery_rounds", "chunk_retries",
               "spool_retain_bytes", "chunk_cache_bytes",
               "serve_concurrency", "serve_queue"):
        assert getattr(back.statesync, f_) == \
            getattr(cfg.statesync, f_), f_
    back.validate()


# -------------------------------------------- serving side (LRU/gate)


def test_chunk_lru_byte_budget():
    from cometbft_tpu.statesync.cache import ChunkLRU

    lru = ChunkLRU(max_size=10, max_bytes=250)
    for i in range(3):
        lru.put(("h", 1, i), bytes([i]) * 100)
    # 300 B over 250: the oldest entry evicted
    assert lru.get(("h", 1, 0)) is None
    assert lru.get(("h", 1, 1)) is not None
    assert lru.bytes == 200
    # get() refreshes recency: key 1 survives the next eviction
    lru.put(("h", 1, 3), b"z" * 100)
    assert lru.get(("h", 1, 1)) is not None
    assert lru.get(("h", 1, 2)) is None
    # never evicts below one entry even when over budget
    lru2 = ChunkLRU(max_size=10, max_bytes=10)
    lru2.put("k", b"x" * 100)
    assert len(lru2) == 1


def test_admission_gate_sheds_over_queue_budget():
    from cometbft_tpu.statesync.cache import AdmissionGate

    async def main():
        gate = AdmissionGate(concurrency=1, max_queued=1)
        release = asyncio.Event()
        entered = asyncio.Event()

        async def hold():
            async with gate:
                entered.set()
                await release.wait()

        holder = asyncio.get_event_loop().create_task(hold())
        await entered.wait()
        assert gate.try_queue()          # one slot in the queue

        async def wait_in_queue():
            async with gate:
                pass

        waiter = asyncio.get_event_loop().create_task(wait_in_queue())
        await asyncio.sleep(0)           # waiter parks (waiting == 1)
        assert not gate.try_queue()      # queue full: shed
        assert gate.shed == 1
        release.set()
        await asyncio.gather(holder, waiter)
        assert gate.try_queue()          # drained: admitting again

    run(main())


def test_reactor_offers_root_and_serves_manifest():
    from cometbft_tpu.abci import types as abci_t
    from cometbft_tpu.abci.client import LocalClient
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.statesync.reactor import StatesyncReactor, _pack

    import msgpack

    async def main():
        app = KVStoreApplication()
        client = LocalClient(app)
        await client.finalize_block(abci_t.FinalizeBlockRequest(
            txs=[b"k%02d=" % i + b"v" * 50000 for i in range(4)],
            height=1, time_ns=0))
        await client.commit()
        snaps = await client.list_snapshots()
        snap = snaps[-1]
        assert snap.chunks >= 2

        reactor = StatesyncReactor(SimpleNamespace(snapshot=client),
                                   name="t.ss")
        sent = []
        peer = SimpleNamespace(
            id="p1", send=lambda chan, msg: sent.append(
                (chan, msgpack.unpackb(msg, raw=False))) or True)

        await reactor._serve_snapshots(peer)
        offers = [d for _, d in sent if d["@"] == "sres"]
        assert offers
        offer = next(d for d in offers if d["h"] == snap.height
                     and d["f"] == snap.format)
        root = offer["mr"]

        sent.clear()
        await reactor._serve_manifest(
            peer, {"h": snap.height, "f": snap.format, "sh": snap.hash})
        mres = next(d for _, d in sent if d["@"] == "mres")
        assert valid_hash_list(snap.hash, mres["hs"], snap.chunks, root)

        # chunk serving goes through the LRU: second serve is a hit
        sent.clear()
        await reactor._serve_chunk(
            peer, {"h": snap.height, "f": snap.format, "i": 0})
        await reactor._serve_chunk(
            peer, {"h": snap.height, "f": snap.format, "i": 0})
        served = [d for _, d in sent if d["@"] == "cres"]
        assert len(served) == 2
        assert served[0]["chunk"] == served[1]["chunk"]
        assert hash_chunk(served[0]["chunk"]) == mres["hs"][0]
        assert len(reactor._cache) >= 1
        _ = _pack     # imported for parity with the wire format

    run(main())


def test_serve_corrupt_chaos_site_flips_served_bytes_not_cache():
    from cometbft_tpu.abci import types as abci_t
    from cometbft_tpu.abci.client import LocalClient
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.libs import failures as F
    from cometbft_tpu.statesync.reactor import StatesyncReactor

    import msgpack

    async def main():
        app = KVStoreApplication()
        client = LocalClient(app)
        await client.finalize_block(abci_t.FinalizeBlockRequest(
            txs=[b"k=" + b"v" * 1000], height=1, time_ns=0))
        await client.commit()
        snap = (await client.list_snapshots())[-1]
        honest = await client.load_snapshot_chunk(snap.height,
                                                  snap.format, 0)

        reactor = StatesyncReactor(SimpleNamespace(snapshot=client),
                                   name="byz.ss")
        sent = []
        peer = SimpleNamespace(
            id="p1", send=lambda chan, msg: sent.append(
                msgpack.unpackb(msg, raw=False)) or True)
        F.reset()
        F.configure(enabled=True, seed=3, faults=[
            "statesync.serve.corrupt:node=byz.ss:every=1"])
        try:
            await reactor._serve_chunk(
                peer, {"h": snap.height, "f": snap.format, "i": 0})
            served = sent[-1]["chunk"]
            assert served != honest              # exactly one bit apart
            diff = [a ^ b for a, b in zip(served, honest) if a != b]
            assert len(diff) == 1 and bin(diff[0]).count("1") == 1
            # the LRU kept the honest bytes (corruption is per-serve)
            key = (snap.height, snap.format, 0)
            assert reactor._cache.get(key) == honest
        finally:
            F.reset()

    run(main())


# ------------------------------------------- heterogeneous peers (p2p)


def test_peer_send_filters_unadvertised_channels():
    """Sender-side channel filtering (reference peer.go hasChannel): a
    statesync-only bootstrapper must not be killed by consensus gossip
    frames it cannot parse — the sender just skips it."""
    from cometbft_tpu.p2p.node_info import NodeInfo
    from cometbft_tpu.p2p.peer import Peer

    sent = []
    mconn = SimpleNamespace(send=lambda chan, msg: sent.append(chan)
                            or True)
    info = NodeInfo(node_id="n1", listen_addr="mem://x", network="net",
                    channels=bytes([0x60, 0x61]), moniker="x")
    peer = Peer(info, mconn, outbound=True)
    assert peer.has_channel(0x60)
    assert not peer.has_channel(0x20)
    assert peer.send(0x60, b"m")
    assert not peer.send(0x20, b"m")     # consensus channel: filtered
    assert sent == [0x60]
    # empty advertisement = pre-channels peer: allow everything
    info2 = NodeInfo(node_id="n2", listen_addr="mem://y", network="net",
                     channels=b"", moniker="y")
    peer2 = Peer(info2, mconn, outbound=True)
    assert peer2.send(0x20, b"m")


# --------------------------------------------------- fleet scenarios


def test_small_fleet_scenario_replay_identical():
    from cometbft_tpu.sim.statesync_lab import (StatesyncScenario,
                                                curated_statesync_scenario,
                                                run_statesync_scenario)

    scn = curated_statesync_scenario(small=True)
    v1 = run_statesync_scenario(scn)
    v2 = run_statesync_scenario(scn)
    assert v1 == v2, "verdict must be a pure function of (scenario, seed)"
    assert v1["completed"] == scn.n_bootstrappers, v1["failed"]
    assert v1["restored_state_matches_chain"]
    t = v1["syncer_tallies"]
    assert t["chunk_hash_mismatches"] >= 1     # byzantine seed caught
    assert t["restore_resets"] == 0            # ...without a reset
    assert len(v1["byzantine_banned_by"]) == scn.n_bootstrappers
    assert v1["chaos"]["sites"].get("statesync.serve.corrupt", 0) >= 1
    # the scenario survives the JSON round trip (replay-from-file)
    rt = StatesyncScenario.from_dict(scn.to_dict())
    assert rt.to_dict() == scn.to_dict()


@pytest.mark.slow
def test_fleet_50_node_bootstrap_scenario():
    """The flagship program: 40 bootstrappers, 4 seeds, gray failures,
    one byzantine seed — every bootstrapper completes, the byzantine
    seed is banned fleet-wide, zero restore resets."""
    from cometbft_tpu.sim.statesync_lab import (curated_statesync_scenario,
                                                run_statesync_scenario)

    scn = curated_statesync_scenario()
    v = run_statesync_scenario(scn)
    assert v["completed"] == scn.n_bootstrappers, v["failed"]
    assert v["restored_state_matches_chain"]
    assert v["syncer_tallies"]["restore_resets"] == 0
    assert len(v["byzantine_banned_by"]) == scn.n_bootstrappers
    d = v["time_to_serving_height_s"]
    assert d["min"] is not None and d["max"] is not None
    assert d["min"] <= d["p50"] <= d["p90"] <= d["max"]
