"""Differential fuzzer: native/secp256k1.cpp vs the OpenSSL-backed
Python path (VERDICT r3 item 1b).

Every triple is derived from a seeded PRNG and RFC 6979 signing, so ANY
mismatch is replayable from the printed (seed, index) alone — the exact
failure mode the r3 flake investigation lacked.

Run standalone:   python tests/fuzz_secp256k1.py [N] [seed]
Run in-process:   pytest tests/test_secp256k1.py -k fuzz   (small N, same
process as the rest of the suite, catching cross-library state effects)

Case classes per triple:
  - the valid signature itself (must accept on both paths)
  - single-bit flip at a random position in sig (identity-proof tamper)
  - last-byte SET (the r3 flake shape, including the identity case)
  - random 64-byte garbage sig
  - boundary r/s: 0, 1, n-1, n, half_n, half_n+1 substituted into a
    valid signature
  - message tamper (flip one bit of the message)
  - wrong pubkey (valid sig checked against a different key)
"""

from __future__ import annotations

import os
import random
import secrets
import sys
import unittest.mock as mock

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.crypto import secp256k1 as s


def _oracle(pub: "s.Secp256k1PubKey", m: bytes, sig: bytes) -> bool:
    with mock.patch.object(s, "_native_lib", lambda: None):
        return pub.verify_signature(m, sig)


def _check(pub, m, sig, ctx):
    native = s._native_verify(pub.bytes(), m, sig)
    oracle = _oracle(pub, m, sig)
    if bool(native) != bool(oracle):
        raise AssertionError(
            f"DIVERGENCE [{ctx}]: native={native} oracle={oracle}\n"
            f"  pub={pub.bytes().hex()}\n  msg={m.hex()}\n"
            f"  sig={sig.hex()}")
    return bool(native)


def fuzz(n_triples: int = 2000, seed: int = 1, progress: bool = False):
    assert s._native_lib() is not None, \
        "native secp256k1 unavailable — nothing to differential-test"
    rng = random.Random(seed)
    n_checked = 0
    bounds = [0, 1, s._N - 1, s._N, s._HALF_N, s._HALF_N + 1]
    for i in range(n_triples):
        sk = s.Secp256k1PrivKey.from_secret(b"fuzz-%d-%d" % (seed, i))
        pub = sk.pub_key()
        m = bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 120)))
        sig = sk.sign(m)

        assert _check(pub, m, sig, f"valid i={i}"), \
            f"valid sig rejected at i={i}"
        n_checked += 1
        bit = rng.randrange(512)
        flipped = bytearray(sig)
        flipped[bit // 8] ^= 1 << (bit % 8)
        _check(pub, m, bytes(flipped), f"bitflip i={i} bit={bit}")
        n_checked += 1
        setlast = sig[:-1] + bytes([rng.randrange(256)])
        _check(pub, m, setlast, f"setlast i={i}")
        n_checked += 1
        _check(pub, m, secrets.token_bytes(64), f"garbage i={i}")
        n_checked += 1
        which, v = rng.randrange(2), rng.choice(bounds)
        bsig = (v.to_bytes(32, "big") + sig[32:] if which == 0
                else sig[:32] + v.to_bytes(32, "big"))
        _check(pub, m, bsig, f"boundary i={i} {'r' if which == 0 else 's'}")
        n_checked += 1
        if m:
            mbit = rng.randrange(len(m) * 8)
            m2 = bytearray(m)
            m2[mbit // 8] ^= 1 << (mbit % 8)
            _check(pub, bytes(m2), sig, f"msgflip i={i}")
            n_checked += 1
        other = s.Secp256k1PrivKey.from_secret(b"other-%d-%d" % (seed, i))
        _check(other.pub_key(), m, sig, f"wrongkey i={i}")
        n_checked += 1
        if progress and (i + 1) % 500 == 0:
            print(f"  {i + 1}/{n_triples} triples, {n_checked} checks, "
                  "0 divergences", flush=True)
    return n_checked


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
    seed = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    checked = fuzz(n, seed, progress=True)
    print(f"OK: {n} triples / {checked} checks, native == oracle on all")
